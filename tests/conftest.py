"""Test harness configuration.

The reference tests multi-rank behavior by forking N local processes with a
fake NCCL rendezvous (tests/unit/common.py:86 DistributedExec). On TPU the
equivalent — and much faster — trick is a single process with N virtual CPU
devices: every "distributed" test becomes a single-process mesh test
(SURVEY.md §4 lesson). These env vars must be set before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The axon TPU plugin (container sitecustomize) registers itself before
# conftest runs and pins jax_platforms; override via the config API, which
# takes precedence over anything set at interpreter start.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end tests")


def pytest_addoption(parser):
    parser.addoption(
        "--stress", action="store_true", default=False,
        help="wrap serving/fleet locks in a seeded LockPerturber: "
             "deterministic GIL-yield points at lock boundaries widen "
             "race windows in the threaded chaos tests")
    parser.addoption(
        "--stress-seed", type=int, default=1234,
        help="LCG seed for --stress yield-point placement")


@pytest.fixture
def stress_perturber(request):
    """A seeded LockPerturber under ``--stress``, else None. Tests that
    accept it instrument their engines/routers when present — the same
    test body runs plain in tier-1 and perturbed in the chaos gate."""
    if not request.config.getoption("--stress"):
        return None
    from deepspeed_tpu.observability.faultinject import LockPerturber

    return LockPerturber(seed=request.config.getoption("--stress-seed"))


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    yield
    from deepspeed_tpu.parallel import mesh

    mesh.reset_mesh()


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
