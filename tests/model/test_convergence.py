"""Model-convergence sanity tier — the ``tests/model/run_sanity_check.py``
analog (SURVEY §4: the reference keeps end-to-end convergence checks like
Megatron_GPT2 run_sanity_check / BingBertSquad alongside its unit tiers).

Trains a small byte-level LM on REAL text (the repo's own prose — no
network, fully deterministic) for a few hundred steps under the flagship
config shape (ZeRO-3 + remat; the flash kernels engage on TPU, the jnp
path on the CPU mesh) and asserts the loss CURVE: large initial drop,
smoothed-monotone decrease, and a final level far below the random-init
entropy. This is the tier that catches "mathematically consistent but
learns nothing" bugs that trajectory-equivalence tests cannot."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer import TransformerConfig, build_model

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _corpus() -> np.ndarray:
    """Byte tokens of the repo's prose documents (~80 KB of real English +
    code text). Committed files only — deterministic across machines."""
    buf = []
    for name in ("README.md", "SURVEY.md", "docs/offload_design.md"):
        with open(os.path.join(_REPO, name), "rb") as f:
            buf.append(f.read())
    data = b"\n".join(buf)
    assert len(data) > 40_000, "corpus unexpectedly small"
    return np.frombuffer(data, np.uint8).astype(np.int32)


def _batches(data: np.ndarray, steps: int, batch: int, seq: int):
    rng = np.random.default_rng(0)
    for _ in range(steps):
        starts = rng.integers(0, len(data) - seq - 1, size=batch)
        yield np.stack([data[s:s + seq] for s in starts])[None]


def test_byte_lm_convergence():
    steps, batch, seq = 300, 8, 128
    model = build_model(TransformerConfig(
        vocab_size=256, hidden_size=128, num_layers=4, num_heads=4,
        max_seq_len=seq, dtype=jnp.float32, remat=True,
        tie_embeddings=True))
    engine, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": max(1, batch // 8),
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10_000,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 30}},
        "zero_optimization": {"stage": 3}},
        rng=jax.random.PRNGKey(0))

    data = _corpus()
    losses = [float(engine.train_batch(batch={"input_ids": jnp.asarray(b)}))
              for b in _batches(data, steps, batch, seq)]
    losses = np.asarray(losses)

    first, last = losses[:20].mean(), losses[-20:].mean()
    # random-init byte entropy is ~ln(256)=5.55; English bytes compress far
    # below that even for a tiny model in 300 steps (measured on this
    # config: 4.60 -> 2.77)
    assert first > 4.0, f"suspicious init loss {first}"
    assert last < 3.0, f"did not learn: final avg loss {last} (from {first})"
    # smoothed curve decreases monotonically-ish: every 50-step mean is
    # below the previous one
    win = losses.reshape(-1, 50).mean(axis=1)
    assert all(b < a for a, b in zip(win, win[1:])), f"non-monotone: {win}"


if __name__ == "__main__":
    test_byte_lm_convergence()
    print("CONVERGENCE-OK")
