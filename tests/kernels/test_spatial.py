"""Spatial (diffusers) kernel parity — GroupNorm vs jnp oracle and torch,
spatial attention vs dense reference (reference csrc/spatial +
diffusers_attention concerns)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.spatial import (diffusers_attention, fused_group_norm,
                                       reference_group_norm)

INTERPRET = True  # CPU mesh — pallas interpreter


class TestFusedGroupNorm:
    @pytest.mark.parametrize("B,HW,C,G", [(2, 256, 64, 8), (1, 1024, 96, 12),
                                          (3, 640, 128, 32)])
    def test_matches_oracle(self, B, HW, C, G):
        rng = jax.random.split(jax.random.PRNGKey(0), 3)
        x = jax.random.normal(rng[0], (B, HW, C), jnp.float32) * 3 + 1
        scale = jax.random.normal(rng[1], (C,)) * 0.1 + 1
        bias = jax.random.normal(rng[2], (C,)) * 0.1
        out = fused_group_norm(x, scale, bias, G, interpret=INTERPRET)
        ref = reference_group_norm(x, scale, bias, G)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_matches_torch_groupnorm(self):
        import torch

        B, HW, C, G = 2, 64, 32, 8
        x = np.random.RandomState(0).randn(B, HW, C).astype(np.float32)
        scale = np.random.RandomState(1).randn(C).astype(np.float32)
        bias = np.random.RandomState(2).randn(C).astype(np.float32)
        out = fused_group_norm(jnp.asarray(x), jnp.asarray(scale),
                               jnp.asarray(bias), G, interpret=INTERPRET)
        gn = torch.nn.GroupNorm(G, C)
        with torch.no_grad():
            gn.weight.copy_(torch.tensor(scale))
            gn.bias.copy_(torch.tensor(bias))
            # torch is NCHW: (B, C, HW, 1)
            t = gn(torch.tensor(x).permute(0, 2, 1).unsqueeze(-1))
        ref = t.squeeze(-1).permute(0, 2, 1).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)

    def test_bf16_io(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 64), jnp.bfloat16)
        out = fused_group_norm(x, jnp.ones((64,)), jnp.zeros((64,)), 8,
                               interpret=INTERPRET)
        assert out.dtype == jnp.bfloat16
        ref = reference_group_norm(x, jnp.ones((64,)), jnp.zeros((64,)), 8)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32), atol=2e-2)

    def test_validation(self):
        x = jnp.zeros((1, 64, 30))
        with pytest.raises(ValueError, match="divisible"):
            fused_group_norm(x, jnp.ones(30), jnp.zeros(30), 4,
                             interpret=INTERPRET)


class TestDiffusersAttention:
    def test_self_attention_matches_dense(self):
        from deepspeed_tpu.models.transformer import dot_product_attention

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (2, 256, 4, 64), jnp.float32)
        k = jax.random.normal(ks[1], (2, 256, 4, 64), jnp.float32)
        v = jax.random.normal(ks[2], (2, 256, 4, 64), jnp.float32)
        out = diffusers_attention(q, k, v, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_cross_attention_shapes(self):
        # cross attention: kv from text encoder (different length)
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (1, 256, 4, 64))
        k = jax.random.normal(ks[1], (1, 128, 4, 64))
        v = jax.random.normal(ks[2], (1, 128, 4, 64))
        out = diffusers_attention(q, k, v, interpret=INTERPRET)
        assert out.shape == q.shape
