"""Pallas kernel parity tests (interpret mode on CPU) — analog of reference
tests/unit/ops/* which check each CUDA kernel against a torch oracle on small
shapes. Every kernel is compared against its pure-jnp reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import alibi_slopes, dot_product_attention
from deepspeed_tpu.ops import (decode_attention, dequantize_symmetric,
                               fake_quantize, flash_attention, fused_adam_flat,
                               fused_layer_norm, op_report,
                               quantize_symmetric, reference_adam_flat,
                               reference_decode_attention,
                               reference_layer_norm,
                               reference_quantize_symmetric)

INTERPRET = True  # CPU mesh — run kernels through the pallas interpreter


def _qkv(b=2, s=128, n=2, d=64, t=None, kv_heads=None, seed=0, dtype=jnp.float32):
    t = t or s
    kv_heads = kv_heads or n
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, n, d), dtype)
    k = jax.random.normal(ks[1], (b, t, kv_heads, d), dtype)
    v = jax.random.normal(ks[2], (b, t, kv_heads, d), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = _qkv(s=256)
        out = flash_attention(q, k, v, causal=causal, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_forward_unaligned_seq(self):
        # S=100 not a multiple of the 128 block — exercises padding path
        q, k, v = _qkv(s=100, t=100)
        out = flash_attention(q, k, v, causal=True, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_forward_gqa(self):
        q, k, v = _qkv(n=4, kv_heads=2)
        out = flash_attention(q, k, v, causal=True, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_cross_attention_shapes(self):
        q, k, v = _qkv(s=128, t=256)
        out = flash_attention(q, k, v, causal=False, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        q, k, v = _qkv(s=128)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=INTERPRET) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, None, causal=causal) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{name} mismatch")

    def test_grad_unaligned(self):
        q, k, v = _qkv(s=100, t=100)

        def loss_flash(q):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=INTERPRET) ** 2)

        def loss_ref(q):
            return jnp.sum(dot_product_attention(q, k, v, None, causal=True) ** 2)

        np.testing.assert_allclose(np.asarray(jax.grad(loss_flash)(q)),
                                   np.asarray(jax.grad(loss_ref)(q)),
                                   atol=5e-4, rtol=1e-3)

    @pytest.mark.parametrize("causal", [True, False])
    def test_key_padding_mask_in_kernel(self, causal):
        # (B,T) key-padding masks run inside the kernel (round-1 gap: any
        # mask silently dropped to the jnp path — VERDICT weak #8)
        q, k, v = _qkv(s=256)
        mask = jnp.ones((2, 256), jnp.int32).at[0, 200:].set(0).at[1, 100:].set(0)
        out = flash_attention(q, k, v, mask=mask, causal=causal, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, mask, causal=causal)
        # compare only at valid query positions (padded queries are ignored
        # by the loss; jnp ref computes them identically anyway)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_key_padding_mask_grads(self):
        q, k, v = _qkv(s=128)
        mask = jnp.ones((2, 128), jnp.int32).at[:, 96:].set(0)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, mask=mask, causal=True,
                                           interpret=INTERPRET) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, mask, causal=True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{name} mismatch")

    @pytest.mark.parametrize("causal", [True, False])
    def test_alibi_in_kernel(self, causal):
        from deepspeed_tpu.models.transformer import alibi_slopes

        q, k, v = _qkv(s=256, n=4)
        al = alibi_slopes(4)
        out = flash_attention(q, k, v, causal=causal, alibi=al,
                              interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, None, causal=causal, alibi=al)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_alibi_grads(self):
        from deepspeed_tpu.models.transformer import alibi_slopes

        q, k, v = _qkv(s=128, n=4)
        al = alibi_slopes(4)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True, alibi=al,
                                           interpret=INTERPRET) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, None, causal=True,
                                                 alibi=al) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{name} mismatch")

    def test_full_mask_falls_back(self):
        q, k, v = _qkv(s=64)
        full = jnp.ones((2, 64, 64), jnp.int32)
        out = flash_attention(q, k, v, mask=full, causal=True, interpret=INTERPRET)
        ref = dot_product_attention(q, k, v, full, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


class TestDecodeAttention:
    def _setup(self, b=2, t=256, n=8, kv=None, d=64, length=100, seed=0):
        kv = kv or n
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (b, n, d))
        kc = jax.random.normal(ks[1], (b, t, kv, d))
        vc = jax.random.normal(ks[2], (b, t, kv, d))
        valid = (jnp.arange(t)[None, :] < length).astype(jnp.int32)
        valid = jnp.broadcast_to(valid, (b, t))
        return q, kc, vc, valid

    def test_matches_reference(self):
        q, kc, vc, valid = self._setup()
        out = decode_attention(q, kc, vc, valid, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        q, kc, vc, valid = self._setup(n=8, kv=2)
        out = decode_attention(q, kc, vc, valid, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_alibi(self):
        q, kc, vc, valid = self._setup(n=8)
        al = alibi_slopes(8)
        out = decode_attention(q, kc, vc, valid, alibi=al, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid, alibi=al)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_alibi_key_positions(self):
        """Ragged-batch alibi: per-row key positions override the arena
        column index in the bias (and default to it when omitted)."""
        q, kc, vc, valid = self._setup(n=8, b=2)
        al = alibi_slopes(8)
        col = jnp.arange(256, dtype=jnp.float32)
        # row 1: shift only a SUBSET of the valid keys (a row-constant shift
        # would be softmax-invariant and prove nothing)
        kpos = jnp.stack([col, col - 30.0 * (col >= 50)])
        out = decode_attention(q, kc, vc, valid, alibi=al,
                               key_positions=kpos, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid, alibi=al,
                                         key_positions=kpos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # row 0 uses identity positions == the no-kpos default
        base = decode_attention(q, kc, vc, valid, alibi=al,
                                interpret=INTERPRET)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(base[0]),
                                   atol=2e-5, rtol=2e-5)
        assert np.abs(np.asarray(out[1] - base[1])).max() > 1e-4

    def test_matches_full_attention_oracle(self):
        # decode over a cache == last-row of full causal attention
        b, t, n, d, length = 1, 128, 4, 64, 77
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        keys = jax.random.normal(ks[1], (b, length, n, d))
        vals = jax.random.normal(ks[2], (b, length, n, d))
        q_full = jax.random.normal(ks[0], (b, length, n, d))
        full = dot_product_attention(q_full, keys, vals, None, causal=True)
        kc = jnp.zeros((b, t, n, d)).at[:, :length].set(keys)
        vc = jnp.zeros((b, t, n, d)).at[:, :length].set(vals)
        valid = (jnp.arange(t)[None, :] < length).astype(jnp.int32)
        out = decode_attention(q_full[:, -1], kc, vc,
                               jnp.broadcast_to(valid, (b, t)),
                               interpret=INTERPRET)
        np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, -1]),
                                   atol=2e-5, rtol=2e-5)


class TestFusedAdam:
    @pytest.mark.parametrize("wd,adam_w", [(0.0, True), (0.01, True), (0.01, False)])
    def test_matches_reference(self, wd, adam_w):
        rng = np.random.RandomState(0)
        n = 10000  # not a block multiple — exercises padding
        p = jnp.asarray(rng.randn(n), jnp.float32)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m = jnp.zeros(n)
        v = jnp.zeros(n)
        p1, m1, v1 = p, m, v
        p2, m2, v2 = p, m, v
        for step in range(1, 4):
            p1, m1, v1 = fused_adam_flat(p1, g, m1, v1, step, lr=1e-2,
                                         weight_decay=wd, adam_w_mode=adam_w,
                                         interpret=INTERPRET)
            p2, m2, v2 = reference_adam_flat(p2, g, m2, v2, step, lr=1e-2,
                                             weight_decay=wd, adam_w_mode=adam_w)
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-6)

    def test_matches_torch_adamw(self):
        import torch

        rng = np.random.RandomState(1)
        n = 512
        p0 = rng.randn(n).astype(np.float32)
        g0 = rng.randn(n).astype(np.float32)
        p, m, v = jnp.asarray(p0), jnp.zeros(n), jnp.zeros(n)
        t = torch.tensor(p0, requires_grad=True)
        opt = torch.optim.AdamW([t], lr=1e-2, weight_decay=0.01)
        for step in range(1, 5):
            p, m, v = fused_adam_flat(p, jnp.asarray(g0), m, v, step, lr=1e-2,
                                      weight_decay=0.01, interpret=INTERPRET)
            t.grad = torch.tensor(g0)
            opt.step()
        np.testing.assert_allclose(np.asarray(p), t.detach().numpy(),
                                   atol=1e-5, rtol=1e-5)


class TestFusedLamb:
    @pytest.mark.parametrize("wd", [0.0, 0.01])
    def test_matches_reference(self, wd):
        from deepspeed_tpu.ops import fused_lamb_flat, reference_lamb_flat

        rng = np.random.RandomState(0)
        n = 10000  # not a block multiple — exercises padding
        p = jnp.asarray(rng.randn(n), jnp.float32)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        p1 = p2 = p
        m1 = v1 = m2 = v2 = jnp.zeros(n)
        for step in range(1, 4):
            p1, m1, v1 = fused_lamb_flat(p1, g, m1, v1, step, lr=1e-2,
                                         weight_decay=wd, interpret=INTERPRET)
            p2, m2, v2 = reference_lamb_flat(p2, g, m2, v2, step, lr=1e-2,
                                             weight_decay=wd)
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-6)

    def test_trust_ratio_scales_step(self):
        """LAMB's point: the applied step length is lr * ||p|| / ||u|| when
        the ratio is inside the clamp window."""
        from deepspeed_tpu.ops import fused_lamb_flat

        rng = np.random.RandomState(2)
        n = 8192
        p = jnp.asarray(rng.randn(n), jnp.float32) * 5.0
        g = jnp.asarray(rng.randn(n), jnp.float32)
        p1, _, _ = fused_lamb_flat(p, g, jnp.zeros(n), jnp.zeros(n), 1,
                                   lr=1e-2, interpret=INTERPRET)
        step_norm = float(jnp.linalg.norm(p1 - p))
        # applied step = lr * (||p||/||u||) * u, so its norm is lr * ||p||
        expected = 1e-2 * float(jnp.linalg.norm(p))
        assert abs(step_norm - expected) / expected < 0.05

    def test_zero_param_tensor_uses_unit_ratio(self):
        from deepspeed_tpu.ops import fused_lamb_flat, reference_lamb_flat

        n = 8192
        p = jnp.zeros(n)
        g = jnp.ones(n)
        p1, _, _ = fused_lamb_flat(p, g, jnp.zeros(n), jnp.zeros(n), 1,
                                   lr=1e-2, interpret=INTERPRET)
        p2, _, _ = reference_lamb_flat(p, g, jnp.zeros(n), jnp.zeros(n), 1,
                                       lr=1e-2)
        assert not np.allclose(np.asarray(p1), 0.0)  # ratio 1.0, not 0
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)


class TestLayerNorm:
    @pytest.mark.parametrize("rms", [False, True])
    def test_forward(self, rms):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 100, 256))
        scale = jax.random.normal(jax.random.PRNGKey(1), (256,)) + 1.0
        bias = None if rms else jax.random.normal(jax.random.PRNGKey(2), (256,))
        out = fused_layer_norm(x, scale, bias, 1e-5, rms, INTERPRET)
        ref = reference_layer_norm(x, scale, bias, 1e-5, rms)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("rms", [False, True])
    def test_backward(self, rms):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 256))
        scale = jax.random.normal(jax.random.PRNGKey(1), (256,)) + 1.0
        bias = None if rms else jnp.zeros((256,))

        def loss_fused(x, scale):
            return jnp.sum(fused_layer_norm(x, scale, bias, 1e-5, rms,
                                            INTERPRET) ** 2)

        def loss_ref(x, scale):
            return jnp.sum(reference_layer_norm(x, scale, bias, 1e-5, rms) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(x, scale)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(x, scale)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestQuantization:
    @pytest.mark.parametrize("bits", [8, 4])
    def test_roundtrip_error_bounded(self, bits):
        x = jax.random.normal(jax.random.PRNGKey(0), (4096,))
        q, s = quantize_symmetric(x, bits=bits, interpret=INTERPRET)
        qr, sr = reference_quantize_symmetric(x, bits=bits)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(qr))
        np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-6)
        deq = dequantize_symmetric(q, s)
        max_group_scale = float(jnp.max(s))
        assert float(jnp.max(jnp.abs(deq - x))) <= max_group_scale * 0.5 + 1e-6

    def test_fake_quantize_straight_through(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 128))
        y = fake_quantize(x, interpret=INTERPRET)
        assert y.shape == x.shape
        g = jax.grad(lambda x: jnp.sum(fake_quantize(x, interpret=INTERPRET) * 2))(x)
        np.testing.assert_allclose(np.asarray(g), 2.0)


def test_op_report():
    report = op_report()
    assert "flash_attention" in report
    assert "fused_adam" in report


class TestInt8Matmul:
    @pytest.mark.parametrize("M,K,N", [(1, 512, 512), (8, 1024, 1536),
                                       (3, 640, 384)])  # last: odd tiles
    def test_matches_reference(self, M, K, N):
        from deepspeed_tpu.ops import int8_matmul, reference_int8_matmul

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(M, K), jnp.float32)
        q8 = jnp.asarray(rng.randint(-127, 128, (K, N)), jnp.int8)
        s = jnp.asarray(np.abs(rng.randn(1, N)) * 0.01, jnp.float32)
        out = int8_matmul(x, q8, s, interpret=INTERPRET)
        ref = reference_int8_matmul(x, q8, s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-3, rtol=1e-4)

    def test_unaligned_rejected(self):
        from deepspeed_tpu.ops import int8_matmul

        with pytest.raises(ValueError, match="128"):
            int8_matmul(jnp.zeros((1, 700)), jnp.zeros((700, 300), jnp.int8),
                        jnp.ones((1, 300)), interpret=INTERPRET)

    def test_bf16_out(self):
        from deepspeed_tpu.ops import int8_matmul, reference_int8_matmul

        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 512), jnp.bfloat16)
        q8 = jnp.asarray(rng.randint(-127, 128, (512, 512)), jnp.int8)
        s = jnp.asarray(np.abs(rng.randn(1, 512)) * 0.01, jnp.float32)
        out = int8_matmul(x, q8, s, interpret=INTERPRET)
        assert out.dtype == jnp.bfloat16
        ref = reference_int8_matmul(x, q8, s, out_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), atol=0.5, rtol=2e-2)


class TestInt4Matmul:
    def test_pack_roundtrip_exact(self):
        from deepspeed_tpu.ops import quantize_int4, unpack_int4

        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(512, 256), jnp.float32)
        q4, s = quantize_int4(w, group_size=128)
        assert q4.shape == (256, 256) and q4.dtype == jnp.uint8
        assert s.shape == (4, 256)
        # unpack(pack(w)) must equal the quantization grid exactly:
        # re-quantizing the unpacked weight is a fixed point
        w_hat = unpack_int4(q4, s, jnp.float32)
        q4b, s_b = quantize_int4(w_hat, group_size=128)
        np.testing.assert_array_equal(np.asarray(q4), np.asarray(q4b))
        np.testing.assert_allclose(np.asarray(s), np.asarray(s_b), rtol=1e-6)
        # quantization error bounded by half a step per group
        step = np.asarray(s)[:, None, :]
        err = np.abs(np.asarray(w_hat - w)).reshape(4, 128, 256)
        assert (err <= step * 0.5 + 1e-7).all()

    @pytest.mark.parametrize("M,K,N,gs", [(1, 512, 512, None),
                                          (8, 1024, 768, 128),
                                          (3, 512, 384, 256)])
    def test_matches_reference(self, M, K, N, gs):
        from deepspeed_tpu.ops import (int4_matmul, quantize_int4,
                                       reference_int4_matmul)

        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(M, K), jnp.float32)
        w = jnp.asarray(rng.randn(K, N) * 0.02, jnp.float32)
        q4, s = quantize_int4(w, group_size=gs)
        out = int4_matmul(x, q4, s, interpret=INTERPRET)
        ref = reference_int4_matmul(x, q4, s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-3, rtol=1e-4)

    def test_unaligned_rejected(self):
        from deepspeed_tpu.ops import int4_matmul

        with pytest.raises(ValueError, match="128"):
            int4_matmul(jnp.zeros((1, 700)),
                        jnp.zeros((350, 300), jnp.uint8),
                        jnp.ones((1, 300)), interpret=INTERPRET)

    def test_bad_group_rejected(self):
        from deepspeed_tpu.ops import quantize_int4

        with pytest.raises(ValueError, match="group_size"):
            quantize_int4(jnp.zeros((512, 128)), group_size=384)


class TestInt8A8Matmul:
    """W8A8 decode GEMM: s8xs8 MXU with dynamic per-row activation
    quantization (the weight-only kernel's VPU-convert bottleneck removed)."""

    @pytest.mark.parametrize("M,K,N", [(1, 512, 512), (8, 1024, 1536),
                                       (3, 640, 384)])
    def test_matches_reference(self, M, K, N):
        from deepspeed_tpu.ops import int8_a8_matmul, reference_int8_a8_matmul

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(M, K), jnp.float32)
        q8 = jnp.asarray(rng.randint(-127, 128, (K, N)), jnp.int8)
        s = jnp.asarray(np.abs(rng.randn(1, N)) * 0.01, jnp.float32)
        out = int8_a8_matmul(x, q8, s, interpret=INTERPRET)
        ref = reference_int8_a8_matmul(x, q8, s)
        # integer accumulation: the kernel and oracle are EXACT twins
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_close_to_weight_only(self):
        """Activation quantization costs only int8 rounding relative to the
        weight-only path."""
        from deepspeed_tpu.ops import (int8_a8_matmul, reference_int8_matmul)

        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 512), jnp.float32)
        q8 = jnp.asarray(rng.randint(-127, 128, (512, 512)), jnp.int8)
        s = jnp.asarray(np.abs(rng.randn(1, 512)) * 0.01, jnp.float32)
        a8 = np.asarray(int8_a8_matmul(x, q8, s, interpret=INTERPRET),
                        np.float32)
        wonly = np.asarray(reference_int8_matmul(x, q8, s), np.float32)
        denom = np.abs(wonly).mean()
        assert np.abs(a8 - wonly).mean() / denom < 0.02

    def test_unaligned_rejected(self):
        from deepspeed_tpu.ops import int8_a8_matmul

        with pytest.raises(ValueError, match="128"):
            int8_a8_matmul(jnp.zeros((1, 700)),
                           jnp.zeros((700, 300), jnp.int8),
                           jnp.ones((1, 300)), interpret=INTERPRET)


class TestInt4A8Matmul:
    """W4A8: in-VMEM nibble unpack to s8 + s8xs8 MXU dots (no bf16 weight
    convert in the body)."""

    @pytest.mark.parametrize("M,K,N,gs", [(1, 512, 512, None),
                                          (8, 1024, 768, None),
                                          (2, 1024, 512, 256)])
    def test_matches_reference(self, M, K, N, gs):
        from deepspeed_tpu.ops import (int4_a8_matmul, quantize_int4,
                                       reference_int4_a8_matmul)

        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(K, N) * 0.02, jnp.float32)
        q4, s = quantize_int4(w, gs)
        x = jnp.asarray(rng.randn(M, K), jnp.float32)
        out = int4_a8_matmul(x, q4, s, interpret=INTERPRET)
        ref = reference_int4_a8_matmul(x, q4, s)
        # integer accumulation per group: exact twins up to fp32 sum order
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_close_to_weight_only_int4(self):
        from deepspeed_tpu.ops import (int4_a8_matmul, quantize_int4,
                                       reference_int4_matmul)

        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(512, 512) * 0.02, jnp.float32)
        q4, s = quantize_int4(w, None)
        x = jnp.asarray(rng.randn(4, 512), jnp.float32)
        a8 = np.asarray(int4_a8_matmul(x, q4, s, interpret=INTERPRET),
                        np.float32)
        wonly = np.asarray(reference_int4_matmul(x, q4, s), np.float32)
        assert np.abs(a8 - wonly).mean() / np.abs(wonly).mean() < 0.02
