"""Paged-attention kernel parity (interpret mode on CPU).

The serving acceptance story rests on three read paths producing the same
attention: the dense ``arena[block_table]`` gather view (PR-6 baseline,
``paged_impl='gather'``), the GQA-native jnp paged reference (CPU serving
fallback), and the Pallas paged kernels (TPU; interpret-mode here). Every
test pins two of them against each other across ragged occupancy, GQA and
alibi — the greedy bit-exactness smoke in tests/unit/test_serving.py then
covers the end-to-end program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import (alibi_slopes,
                                              dot_product_attention)
from deepspeed_tpu.ops import (decode_attention, paged_decode_attention,
                               paged_prefill_attention,
                               reference_decode_attention,
                               reference_paged_attention)

INTERPRET = True


def _pool(nb=9, bs=16, k=2, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    return (jax.random.normal(ks[0], (nb, bs, k, d), dtype),
            jax.random.normal(ks[1], (nb, bs, k, d), dtype))


def _ragged_tables(bs=16, maxb=4):
    """Three rows at different occupancy; physical pages deliberately
    non-contiguous and out of order."""
    bt = np.zeros((3, maxb), np.int32)
    bt[0, :3] = [5, 1, 7]
    bt[1, :1] = [3]
    bt[2, :4] = [8, 2, 4, 6]
    lengths = np.array([bs * 2 + 5, 9, bs * 4], np.int32)
    return jnp.asarray(bt), jnp.asarray(lengths)


def _dense_view(pool, bt):
    nb, bs, k, d = pool.shape
    b, maxb = bt.shape
    return pool[bt].reshape(b, maxb * bs, k, d)


class TestPagedDecodeKernel:
    @pytest.mark.parametrize("n,k", [(4, 4), (4, 2), (8, 2)])
    def test_matches_reference_ragged_gqa(self, n, k):
        kp, vp = _pool(k=k)
        bt, lengths = _ragged_tables()
        q = jax.random.normal(jax.random.PRNGKey(3), (3, n, 32))
        out = paged_decode_attention(q, kp, vp, bt, lengths,
                                     interpret=INTERPRET)
        ref = reference_paged_attention(q[:, None], kp, vp, bt,
                                        lengths[:, None] - 1)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_alibi_uses_true_positions(self):
        kp, vp = _pool(k=2)
        bt, lengths = _ragged_tables()
        n = 4
        q = jax.random.normal(jax.random.PRNGKey(4), (3, n, 32))
        al = alibi_slopes(n)
        out = paged_decode_attention(q, kp, vp, bt, lengths, alibi=al,
                                     interpret=INTERPRET)
        ref = reference_paged_attention(q[:, None], kp, vp, bt,
                                        lengths[:, None] - 1, alibi=al)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_inactive_row_outputs_zero(self):
        kp, vp = _pool()
        bt, lengths = _ragged_tables()
        lengths = lengths.at[1].set(0)          # inactive decode row
        q = jax.random.normal(jax.random.PRNGKey(5), (3, 4, 32))
        out = paged_decode_attention(q, kp, vp, bt, lengths,
                                     interpret=INTERPRET)
        assert bool(jnp.all(out[1] == 0))

    def test_reference_matches_dense_gather_path(self):
        """The jnp paged reference (CPU serving fallback) computes the
        same attention as the PR-6 gather + dot_product_attention path —
        what 'paged_kernel=off' A/Bs against."""
        kp, vp = _pool(k=2)
        bt, lengths = _ragged_tables()
        n = 4
        q1 = jax.random.normal(jax.random.PRNGKey(6), (3, 1, n, 32))
        pos = lengths[:, None] - 1
        ref = reference_paged_attention(q1, kp, vp, bt, pos)
        kk, vv = _dense_view(kp, bt), _dense_view(vp, bt)
        col = jnp.arange(kk.shape[1], dtype=jnp.int32)
        full = (col[None, None, :] <= pos[:, :, None]).astype(jnp.int32)
        want = dot_product_attention(q1, kk, vv, full, causal=False)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)


class TestPagedPrefillKernel:
    @pytest.mark.parametrize("n,k", [(4, 4), (8, 2)])
    def test_chunk_matches_reference(self, n, k):
        kp, vp = _pool(k=k)
        bt = jnp.asarray(np.array([[5, 1, 7, 0], [3, 8, 0, 0]], np.int32))
        start = jnp.asarray(np.array([21, 0], np.int32))
        C = 16
        q = jax.random.normal(jax.random.PRNGKey(7), (2, C, n, 32))
        pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        out = paged_prefill_attention(q, kp, vp, bt, start,
                                      interpret=INTERPRET)
        ref = reference_paged_attention(q, kp, vp, bt, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_chunk_alibi(self):
        kp, vp = _pool(k=2)
        bt = jnp.asarray(np.array([[5, 1, 7, 0]], np.int32))
        start = jnp.asarray(np.array([17], np.int32))
        n, C = 4, 16
        q = jax.random.normal(jax.random.PRNGKey(8), (1, C, n, 32))
        al = alibi_slopes(n)
        pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        out = paged_prefill_attention(q, kp, vp, bt, start, alibi=al,
                                      interpret=INTERPRET)
        ref = reference_paged_attention(q, kp, vp, bt, pos, alibi=al)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_chunk_matches_dense_gather_path(self):
        kp, vp = _pool(k=2)
        bt = jnp.asarray(np.array([[5, 1, 7, 0]], np.int32))
        start = jnp.asarray(np.array([21], np.int32))
        n, C = 4, 16
        q = jax.random.normal(jax.random.PRNGKey(9), (1, C, n, 32))
        pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        out = paged_prefill_attention(q, kp, vp, bt, start,
                                      interpret=INTERPRET)
        kk, vv = _dense_view(kp, bt), _dense_view(vp, bt)
        col = jnp.arange(kk.shape[1], dtype=jnp.int32)
        full = (col[None, None, :] <= pos[:, :, None]).astype(jnp.int32)
        want = dot_product_attention(q, kk, vv, full, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)


class TestDecodeAttentionUnalignedCache:
    """The T % 128 gate is gone: the final KV tile is edge-padded by the
    pipeline and masked by true column in-kernel, so bucketed non-multiple
    cache lengths stay on the kernel instead of silently falling back to
    jnp attention."""

    @pytest.mark.parametrize("t", [100, 160, 257, 64])
    def test_non_multiple_cache_length(self, t):
        ks = jax.random.split(jax.random.PRNGKey(10), 3)
        q = jax.random.normal(ks[0], (2, 4, 32))
        kc = jax.random.normal(ks[1], (2, t, 2, 32))
        vc = jax.random.normal(ks[2], (2, t, 2, 32))
        valid = jnp.asarray(
            (np.arange(t)[None, :] < np.array([t - 3, t // 2])[:, None]
             ).astype(np.int32))
        out = decode_attention(q, kc, vc, valid, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_non_multiple_with_alibi_key_positions(self):
        t = 100
        ks = jax.random.split(jax.random.PRNGKey(11), 4)
        q = jax.random.normal(ks[0], (2, 4, 32))
        kc = jax.random.normal(ks[1], (2, t, 2, 32))
        vc = jax.random.normal(ks[2], (2, t, 2, 32))
        valid = jnp.asarray(
            (np.arange(t)[None, :] < np.array([t - 7, 41])[:, None]
             ).astype(np.int32))
        al = alibi_slopes(4)
        kpos = jnp.asarray(np.tile(np.arange(t, dtype=np.float32), (2, 1)))
        out = decode_attention(q, kc, vc, valid, alibi=al,
                               key_positions=kpos, interpret=INTERPRET)
        ref = reference_decode_attention(q, kc, vc, valid, alibi=al,
                                         key_positions=kpos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
