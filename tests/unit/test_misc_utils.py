"""Misc parity-shim tests: OnDevice construction placement, MoE TP token
mappings (reference utils/init_on_device.py, moe/mappings.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import create_model
from deepspeed_tpu.utils.init_on_device import OnDevice, abstract_init


def test_ondevice_meta_is_abstract():
    model = create_model("tiny", dtype=jnp.float32)
    with OnDevice(device="meta") as ctx:
        shapes = ctx.init(model.init, jax.random.PRNGKey(0))
    leaf = jax.tree.leaves(shapes)[0]
    assert isinstance(leaf, jax.ShapeDtypeStruct)
    assert abstract_init(model.init, jax.random.PRNGKey(0))


def test_ondevice_real_with_dtype():
    model = create_model("tiny", dtype=jnp.float32)
    with OnDevice(dtype=jnp.bfloat16, device="device") as ctx:
        params = ctx.init(model.init, jax.random.PRNGKey(0))
    assert params["embed"]["tokens"].dtype == jnp.bfloat16


def test_moe_mappings_roundtrip():
    from deepspeed_tpu.config.config import ParallelConfig
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.parallel.moe_mappings import drop_tokens, gather_tokens

    mesh = mesh_mod.build_mesh(ParallelConfig(tensor_parallel_size=2,
                                              data_parallel_size=4))
    x = jnp.arange(2 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 4)

    @jax.jit
    def fn(x):
        g = gather_tokens(drop_tokens(x))
        return g * 2

    with mesh_mod.mesh_context(mesh):
        out = fn(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2)
