"""Triggered deep profiling tests — ``observability/profiler.py`` (ISSUE-20).

Four layers, matching the subsystem's own:

* trace **parsing** in isolation — the committed miniature trace fixture
  (per-program device/host seconds, op hotspots, module-level fallback,
  compile-flood skip) and the tolerant XSpace wire reader on both crafted
  and garbage bytes;
* the **trigger state machine** on a fake clock and fake trace hooks —
  burn fires once then cools down, budget exhaustion, schedule cadence,
  steady-recompile pending, hang pre-fire, keep-last-K pruning: no wall
  time, no jax.profiler;
* the **live CPU capture smoke** — a burn-triggered window on a real
  serving engine produces a parsed ``profile_summary.json`` joining
  measured seconds against the tpucost prediction for >= 4 registry
  entries, rendered by the report CLI;
* the **boot recommendations path** — ``init_serving(recommendations=)``
  applies valid shape knobs with provenance and refuses stale /
  under-evidenced artifacts with a named reason; plus the disabled-path
  zero-overhead contract.
"""

import glob
import gzip
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import (ObservabilityConfig,
                                         ProfilingConfig, ServingConfig,
                                         TuneConfig)
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, get_session,
                                         reset_session)
from deepspeed_tpu.observability import profiler as profiler_mod
from deepspeed_tpu.observability.hangdetect import HangWatchdog
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.observability.profiler import (Capture, DeepProfiler,
                                                  PROFILE_FORMAT,
                                                  entry_program_map,
                                                  parse_trace_dir,
                                                  summarize_capture)
from deepspeed_tpu.observability.report import (crash_report, report,
                                                summarize_profiling)
from deepspeed_tpu.observability.timeseries import TimeSeriesStore
from deepspeed_tpu.serving import ServingEngine

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "..", "fixtures",
                           "profile_capture")


@pytest.fixture(autouse=True)
def _obs_isolation():
    reset_session()
    get_registry().reset()
    yield
    reset_session()
    get_registry().reset()


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


def serving(tiny_engine, spec="off", **cfg):
    defaults = dict(block_size=16, num_blocks=64, max_seqs=4,
                    max_model_len=128, prefill_chunk=16, max_queue=64)
    defaults.update(cfg)
    speculative = {"mode": spec, "num_draft_tokens": 4}
    return ServingEngine(tiny_engine,
                         ServingConfig(speculative=speculative, **defaults))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeTracer:
    """Injectable start/stop trace hooks: records the capture dirs and, on
    stop, drops ``payload`` in as the trace artifact — the state machine
    runs with zero jax.profiler involvement."""

    def __init__(self, payload=None):
        self.dirs = []
        self.payload = payload
        self.active = False

    def start(self, path):
        assert not self.active, "overlapping start_trace"
        self.active = True
        self.dirs.append(path)

    def stop(self):
        assert self.active, "stop without start"
        self.active = False
        if self.payload is not None:
            d = os.path.join(self.dirs[-1], "plugins", "profile", "000")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "host.trace.json"), "w") as fh:
                json.dump(self.payload, fh)


def make_profiler(tmp_path, payload=None, timeseries=None, registry=None,
                  clock=None, **cfg):
    defaults = dict(enabled=True, window_iterations=4,
                    cooldown_iterations=50, check_interval_iterations=1,
                    capture_budget=8, keep_last=4, burn_ceiling=2.0)
    defaults.update(cfg)
    pc = ProfilingConfig(**defaults)
    pc.validate()
    ft = FakeTracer(payload)
    prof = DeepProfiler(pc, registry=registry, timeseries=timeseries,
                        output_dir=str(tmp_path),
                        clock=clock or FakeClock(),
                        start_trace=ft.start, stop_trace=ft.stop)
    return prof, ft


def burn_store(value=5.0, n=8):
    ts = TimeSeriesStore()
    for i in range(n):
        ts.observe("serve_goodput/ttft_slo_burn_rate/replica=0", value,
                   step=i)
    return ts


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


class TestProfilingConfig:
    def test_defaults_valid_and_disabled(self):
        cfg = ObservabilityConfig()
        cfg.validate()
        assert cfg.profiling.enabled is False

    def test_dict_coercion(self):
        cfg = ObservabilityConfig(profiling={"enabled": True,
                                             "window_iterations": 2})
        cfg.validate()
        assert isinstance(cfg.profiling, ProfilingConfig)
        assert cfg.profiling.window_iterations == 2

    @pytest.mark.parametrize("bad", [
        {"window_iterations": 0}, {"capture_budget": -1},
        {"keep_last": 0}, {"cooldown_iterations": -1},
        {"check_interval_iterations": 0}, {"hang_prefire_fraction": 1.5},
        {"window_wall_s": 0}, {"hotspot_top_k": 0},
        {"profile_every_steps": -2},
    ])
    def test_validation_rejects(self, bad):
        with pytest.raises(ConfigError):
            ProfilingConfig(**bad).validate()


# ---------------------------------------------------------------------------
# trace parsing (committed fixture, no jax)
# ---------------------------------------------------------------------------


class TestParseTrace:
    def test_fixture_attribution(self):
        parsed = parse_trace_dir(FIXTURE_DIR)
        progs = parsed["programs"]
        assert set(progs) == {"jit_decode", "jit_prefill_chunk"}
        dec = progs["jit_decode"]
        # op slices sum; the module-level 2000us event must NOT double
        # count on top of them
        assert dec["device_s"] == pytest.approx(0.002)
        assert dec["ops"] == {"fusion.1": pytest.approx(0.0015),
                              "dot.3": pytest.approx(0.0005)}
        assert dec["host_s"] == pytest.approx(0.005)
        assert dec["invocations"] == 2
        pre = progs["jit_prefill_chunk"]
        # no op slices -> module-level total is the device evidence
        assert pre["device_s"] == pytest.approx(0.004)
        assert pre["invocations"] == 1
        # the $-prefixed compile-flood event contributed nowhere
        assert parsed["trace_files"] == 1

    def test_gzipped_trace_parses_identically(self, tmp_path):
        with open(os.path.join(FIXTURE_DIR, "mini.trace.json")) as fh:
            doc = fh.read()
        with gzip.open(tmp_path / "host.trace.json.gz", "wt") as fh:
            fh.write(doc)
        parsed = parse_trace_dir(str(tmp_path))
        assert parsed["programs"]["jit_decode"]["device_s"] \
            == pytest.approx(0.002)

    def test_torn_artifact_skipped_not_fatal(self, tmp_path):
        (tmp_path / "torn.trace.json").write_text('{"traceEvents": [')
        parsed = parse_trace_dir(str(tmp_path))
        assert parsed["programs"] == {}

    def test_empty_dir(self, tmp_path):
        parsed = parse_trace_dir(str(tmp_path))
        assert parsed == {"programs": {}, "trace_files": 0, "events": 0}

    def test_xplane_wire_reader_finds_names(self, tmp_path):
        # field 1, wire type 2, payload "jit_decode" — a minimal valid
        # length-delimited protobuf record
        name = b"jit_decode"
        buf = bytes([0x0A, len(name)]) + name
        p = tmp_path / "x.xplane.pb"
        p.write_bytes(buf)
        assert profiler_mod._xplane_program_names(str(p)) == {"jit_decode"}

    def test_xplane_wire_reader_tolerates_garbage(self, tmp_path):
        p = tmp_path / "g.xplane.pb"
        p.write_bytes(bytes(range(256)) * 64)
        # must not raise, whatever it finds
        profiler_mod._xplane_program_names(str(p))

    def test_xplane_census_adds_zero_duration_row(self, tmp_path):
        name = b"jit_orphan"
        (tmp_path / "x.xplane.pb").write_bytes(
            bytes([0x0A, len(name)]) + name)
        parsed = parse_trace_dir(str(tmp_path))
        assert parsed["programs"]["jit_orphan"]["device_s"] == 0.0


class TestSummarizeCapture:
    def test_join_and_hotspots(self, monkeypatch):
        monkeypatch.setattr(
            profiler_mod, "entry_program_map",
            lambda: {"jit_decode": ["serving/decode",
                                    "serving/draft_decode"]})
        parsed = parse_trace_dir(FIXTURE_DIR)
        joined = []

        def cost_join(entry, measured_s):
            joined.append((entry, measured_s))
            return {"predicted_step_ms": 1.0, "bound": "hbm",
                    "model_error": measured_s / 1e-3}

        body = summarize_capture(parsed, top_k=1, cost_join=cost_join)
        row = body["entries"]["serving/decode"]
        assert row["program"] == "jit_decode"
        assert row["shared_with"] == ["serving/draft_decode"]
        assert row["invocations"] == 2
        assert row["measured_step_ms"] == pytest.approx(1.0)   # 2ms / 2
        assert row["hlo_hotspots"] == [
            {"op": "fusion.1", "seconds": pytest.approx(0.0015)}]
        assert row["bound"] == "hbm"
        assert joined == [("serving/decode", pytest.approx(0.001))]
        assert body["unmatched_programs"] == ["jit_prefill_chunk"]

    def test_cost_join_failure_is_missing_column(self, monkeypatch):
        monkeypatch.setattr(profiler_mod, "entry_program_map",
                            lambda: {"jit_decode": ["serving/decode"]})

        def bad_join(entry, measured_s):
            raise RuntimeError("no registry")

        body = summarize_capture(parse_trace_dir(FIXTURE_DIR),
                                 cost_join=bad_join)
        assert "predicted_step_ms" not in body["entries"]["serving/decode"]


# ---------------------------------------------------------------------------
# trigger state machine (fake clock, fake tracer)
# ---------------------------------------------------------------------------


class TestTriggers:
    def test_burn_fires_once_then_cools_down(self, tmp_path):
        prof, ft = make_profiler(tmp_path, timeseries=burn_store(),
                                 window_iterations=4,
                                 cooldown_iterations=50)
        prof.on_iteration(1)
        assert prof._open is not None
        assert prof.captures[0].trigger == "burn"
        # window closes after window_iterations ticks
        for it in range(2, 6):
            prof.on_iteration(it)
        assert prof._open is None
        assert len(prof.captures) == 1
        # burn still hot: nothing re-fires inside the cooldown
        for it in range(6, 51):
            prof.on_iteration(it)
        assert len(prof.captures) == 1
        prof.on_iteration(51)
        assert len(prof.captures) == 2

    def test_wall_clock_bound_closes_window(self, tmp_path):
        clk = FakeClock()
        prof, ft = make_profiler(tmp_path, timeseries=burn_store(),
                                 clock=clk, window_iterations=1000,
                                 window_wall_s=30.0)
        prof.on_iteration(1)
        assert prof._open is not None
        clk.advance(31.0)
        prof.on_iteration(2)
        assert prof._open is None
        assert prof.captures[0].wall_s == pytest.approx(31.0)

    def test_budget_exhaustion(self, tmp_path):
        prof, ft = make_profiler(tmp_path, timeseries=burn_store(),
                                 capture_budget=2, cooldown_iterations=1,
                                 window_iterations=1)
        for it in range(1, 200):
            prof.on_iteration(it)
        assert len(prof.captures) == 2
        assert prof._budget == 0

    def test_manual_bypasses_budget(self, tmp_path):
        prof, ft = make_profiler(tmp_path, capture_budget=1)
        prof._budget = 0          # drained by earlier triggered captures
        prof.request_capture("manual")
        prof.on_iteration(1)
        assert prof._open is not None and prof._budget == 0
        prof.close_window()
        assert prof.captures[0].trigger == "manual"

    def test_schedule_cadence(self, tmp_path):
        prof, ft = make_profiler(tmp_path, profile_every_steps=10,
                                 window_iterations=2,
                                 cooldown_iterations=1)
        for it in range(1, 25):
            prof.on_iteration(it)
        assert [c.opened_iteration for c in prof.captures] == [10, 20]
        assert all(c.trigger == "schedule" for c in prof.captures)

    def test_steady_recompile_sets_pending(self, tmp_path):
        prof, ft = make_profiler(tmp_path)
        prof.on_compile(1.0, "train_batch", steady=False)
        prof.on_iteration(1)
        assert prof._open is None
        prof.on_compile(1.0, "train_batch", steady=True)
        prof.on_iteration(2)
        assert prof._open is not None
        assert prof.captures[0].trigger == "recompile"

    def test_summary_time_compiles_do_not_retrigger(self, tmp_path):
        prof, ft = make_profiler(tmp_path, window_iterations=1)
        prof.open_window("manual")
        # a cost-vector compile during close_window's summary must not
        # queue the next capture — simulate via the _summarizing flag
        prof._summarizing = True
        prof.on_compile(1.0, "tpucost", steady=True)
        prof._summarizing = False
        assert prof._pending is None

    def test_keep_last_k_pruning(self, tmp_path):
        prof, ft = make_profiler(tmp_path, keep_last=2)
        for _ in range(5):
            assert prof.open_window("manual") is not None
            prof.close_window()
        dirs = sorted(glob.glob(os.path.join(prof.trace_dir, "capture-*")))
        assert len(dirs) == 2
        assert dirs[-1].endswith("capture-005-manual")

    def test_pruning_never_removes_open_window(self, tmp_path):
        prof, ft = make_profiler(tmp_path, keep_last=1)
        prof.open_window("manual")
        prof.close_window()
        cap = prof.open_window("manual")
        assert os.path.isdir(cap.dir)
        prof.close_window()

    def test_single_window_at_a_time(self, tmp_path):
        prof, ft = make_profiler(tmp_path)
        assert prof.open_window("manual") is not None
        assert prof.open_window("manual") is None
        assert len(prof.captures) == 1

    def test_hang_prefire_opens_window_and_latches(self, tmp_path):
        # no iterations tick in this test, so zero the iteration-denominated
        # cooldown: the watchdog latch is the once-per-stall guard here
        prof, ft = make_profiler(tmp_path, cooldown_iterations=0)
        clk = FakeClock()
        wd = HangWatchdog(clock=clk, timeout_floor_s=10.0)
        wd.prefire_fraction = 0.5
        wd.on_prefire = lambda stalled_span, waited, deadline: \
            prof.on_hang_prefire(stalled_span, waited, deadline)
        wd.heartbeat("train_batch")
        clk.advance(6.0)                 # past 50% of the 10s deadline
        assert wd.check() is False       # not fired — but prefired
        assert prof._open is not None
        assert prof.captures[0].trigger == "hang_prefire"
        wd.check()                       # latched: no second window
        assert len(prof.captures) == 1
        clk.advance(5.0)
        assert wd.check() is True        # the real fire still happens
        # a new stall (fresh heartbeat) re-arms the prefire latch
        wd.heartbeat("train_batch")
        prof.close_window()
        clk.advance(6.0)
        wd.check()
        assert len(prof.captures) == 2

    def test_bundle_context_flushes_open_hang_window(self, tmp_path):
        prof, ft = make_profiler(tmp_path)
        prof.on_hang_prefire("train_batch", 6.0, 10.0)
        assert prof._open is not None
        ctx = prof.bundle_context()
        assert prof._open is None        # closed so the trace flushed
        assert ctx is not None and ctx["captures"][0]["status"] in (
            "empty", "parsed")

    def test_close_flushes_and_publishes(self, tmp_path):
        reg = MetricsRegistry()
        prof, ft = make_profiler(tmp_path, registry=reg)
        prof.open_window("manual")
        prof.close()
        assert prof._open is None
        assert not ft.active
        caps = reg.counter("profile/captures").series()
        assert sum(caps.values()) == 1

    def test_summary_written_and_metrics_published(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setattr(profiler_mod, "entry_program_map",
                            lambda: {"jit_decode": ["serving/decode"]})
        monkeypatch.setattr(
            profiler_mod, "_tpucost_join",
            lambda entry, s: {"predicted_step_ms": 2.0, "bound": "hbm",
                              "model_error": 0.5, "measured_mfu": 0.1,
                              "mfu_ceiling": 0.4})
        with open(os.path.join(FIXTURE_DIR, "mini.trace.json")) as fh:
            payload = json.load(fh)
        reg = MetricsRegistry()
        prof, ft = make_profiler(tmp_path, payload=payload, registry=reg)
        prof.open_window("manual")
        summary = prof.close_window()
        assert summary["format"] == PROFILE_FORMAT
        assert summary["capture"]["status"] == "parsed"
        on_disk = json.load(open(prof.summary_path))
        assert on_disk["entries"]["serving/decode"]["model_error"] == 0.5
        assert prof.captures[0].entries_matched == 1
        g = reg.gauge("profile/model_error").series()
        assert list(g.values()) == [0.5]
        # the report CLI renders these same records as == profiling ==
        out = summarize_profiling(reg.snapshot())
        assert "== profiling ==" in out
        assert "serving/decode" in out and "manual=1" in out


# ---------------------------------------------------------------------------
# disabled path — zero overhead
# ---------------------------------------------------------------------------


class TestDisabledPath:
    def test_disabled_session_wires_nothing(self):
        sess = get_session()
        assert sess.profiler is None

    def test_enabled_session_without_profiling_gate(self, tmp_path):
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        assert sess.profiler is None
        assert sess.hang is None or sess.hang.on_prefire is None

    def test_profiling_off_streams_bit_identical(self, tiny_engine,
                                                 tmp_path):
        prompt = np.arange(24) % 250
        want = np.asarray(tiny_engine.generate(
            prompt[None], max_new_tokens=6))[0]
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        srv = serving(tiny_engine)
        got = srv.submit(prompt, max_new_tokens=6).result()
        np.testing.assert_array_equal(np.asarray(got), want)
        # no profiler => no capture dirs, no trace starts
        assert not os.path.isdir(os.path.join(str(tmp_path), "profile"))


# ---------------------------------------------------------------------------
# live CPU capture smoke (real jax.profiler, real engine)
# ---------------------------------------------------------------------------


class TestLiveCaptureSmoke:
    def test_burn_triggered_capture_joins_cost_model(self, tiny_engine,
                                                     tmp_path):
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path),
            tune=TuneConfig(enabled=True),
            profiling=ProfilingConfig(
                enabled=True, window_iterations=10,
                check_interval_iterations=1, cooldown_iterations=10_000,
                burn_ceiling=2.0, sigusr2=False)))
        assert sess.profiler is not None
        srv = serving(tiny_engine, spec="ngram")
        rng = np.random.RandomState(0)
        pat = rng.randint(0, 250, (6,))

        def workload():
            srv.submit(np.tile(pat, 6)[:30], max_new_tokens=8, n=2)
            srv.submit(rng.randint(0, 250, (20,)), max_new_tokens=8)
            srv.run()
            srv.score_logprobs(np.arange(2, 40) % 250)

        # warmup OUTSIDE any window: every program compiles here, so the
        # captured window sees steady-state executions (whose trace events
        # carry hlo_module attribution) and zero compile flood
        workload()
        srv.spec_suspended = True     # warm the plain decode program too
        srv.submit(rng.randint(0, 250, (12,)), max_new_tokens=4)
        srv.run()
        srv.spec_suspended = False
        # a hot burn series makes the NEXT engine tick open the window
        for i in range(8):
            sess.timeseries.observe(
                "serve_goodput/ttft_slo_burn_rate/replica=0", 5.0, step=i)
        workload()                    # runs inside the capture window
        srv.spec_suspended = True
        srv.submit(rng.randint(0, 250, (12,)), max_new_tokens=4)
        srv.run()
        srv.spec_suspended = False
        prof = sess.profiler
        assert prof.captures and prof.captures[0].trigger == "burn"
        if prof._open is not None:    # drain: the window closes in-test
            prof.close_window()
        summary = prof.latest_summary
        assert summary is not None and summary["capture"]["status"] == \
            "parsed"
        entries = summary["entries"]
        assert len(entries) >= 4, sorted(entries)
        # measured + predicted joined for at least 4 registry entries
        paired = [e for e, row in entries.items()
                  if row.get("measured_step_ms") is not None
                  and row.get("predicted_step_ms") is not None]
        assert len(paired) >= 4, (sorted(entries), paired)
        for e in paired:
            assert entries[e]["model_error"] > 0
        # the ledger + per-entry table render in the report CLI
        sess.dump_metrics()
        out = report([sess.metrics_path()])
        assert "== profiling ==" in out
        assert "burn=1" in out
        for e in paired[:2]:
            assert e in out
        # and the summary staples into crash bundles when a recorder is
        # present (here: render the staple directly)
        assert prof.bundle_context() is summary

    def test_entry_program_map_covers_serving(self, tiny_engine):
        configure_observability(ObservabilityConfig(enabled=True))
        srv = serving(tiny_engine, spec="ngram")
        emap = entry_program_map()
        assert emap.get("jit_decode") == ["serving/decode"]
        assert emap.get("jit_prefill_chunk") == ["serving/prefill_chunk"]
        assert emap.get("jit_verify") == ["serving/verify"]
        assert emap.get("jit_score_chunk") == ["serving/score_chunk"]
        assert emap.get("jit_cow_copy") == ["serving/cow_copy"]
        del srv


# ---------------------------------------------------------------------------
# crash-bundle rendering (satellite: the PR-18 timeseries digest + the
# profile staple surface in `report --crash-dump`)
# ---------------------------------------------------------------------------


class TestCrashBundleRendering:
    def _bundle(self, tmp_path, manifest):
        d = tmp_path / "bundle"
        d.mkdir()
        manifest.setdefault("reason", "hang")
        with open(d / "MANIFEST.json", "w") as fh:
            json.dump(manifest, fh)
        return str(d)

    def test_timeseries_digest_rendered(self, tmp_path):
        man = {"timeseries": {
            "series": 2, "points_total": 40, "dropped_series": 0,
            "series_stats": {
                "serve_goodput/ttft_slo_burn_rate/replica=0": {
                    "n": 20, "last": 5.0, "ewma": 4.2, "slope": 0.3,
                    "tail": [[1, 3.0], [2, 4.0], [3, 5.0]]},
                "serving/queue_depth": {
                    "n": 20, "last": 1.0, "ewma": 1.0, "slope": 0.0,
                    "tail": []}}}}
        out = crash_report(self._bundle(tmp_path, man))
        assert "== metric trajectories ==" in out
        assert "ttft_slo_burn_rate" in out
        assert "slope=+0.3" in out
        # most-volatile ranks first
        assert out.index("ttft_slo_burn_rate") < out.index("queue_depth")

    def test_profile_staple_rendered(self, tmp_path):
        man = {"profile_summary": {
            "format": 1,
            "capture": {"seq": 2, "trigger": "hang_prefire",
                        "status": "parsed", "wall_s": 1.25},
            "captures": [{"seq": 1, "trigger": "burn",
                          "opened_iteration": 10, "status": "parsed"}],
            "entries": {"serving/decode": {
                "device_s": 0.5, "measured_step_ms": 2.0,
                "predicted_step_ms": 1.0, "model_error": 2.0}}}}
        out = crash_report(self._bundle(tmp_path, man))
        assert "== profiling staple ==" in out
        assert "hang_prefire" in out and "serving/decode" in out
        assert "err=2.0x" in out

    def test_bundle_without_staples_unchanged(self, tmp_path):
        out = crash_report(self._bundle(tmp_path, {}))
        assert "metric trajectories" not in out
        assert "profiling staple" not in out


# ---------------------------------------------------------------------------
# boot recommendations (satellite: init_serving(recommendations=...))
# ---------------------------------------------------------------------------


def make_artifact(tmp_path, recs, fmt=1, name="tune_recommendations.json"):
    art = {"format": fmt, "generated_at_iteration": 500, "moves": 3,
           "rollbacks": 0, "objective": {"initial": 0.5, "last": 0.8},
           "knobs": {}, "signals": {}, "recommendations": recs}
    p = tmp_path / name
    with open(p, "w") as fh:
        json.dump(art, fh)
    return str(p)


SPEC_REC = {"knob": "speculative.num_draft_tokens", "kind": "shape",
            "current": 4, "recommended": 5,
            "reason": "near-unity draft acceptance",
            "evidence": {"acceptance_rate": 0.95, "proposed": 640}}
BLOCKS_REC = {"knob": "serving.num_blocks", "kind": "shape",
              "current": 64, "recommended": 80,
              "reason": "occupancy p99 near saturation",
              "evidence": {"occupancy_p99": 0.97}}
CHUNK_REC = {"knob": "serving.prefill_chunk", "kind": "shape",
             "current": 16, "recommended": 32,
             "reason": "settled on 2 chunks/iteration",
             "evidence": {"chunks_per_iteration": 2}}


def base_scfg(**kw):
    d = dict(block_size=16, num_blocks=64, max_seqs=4, max_model_len=128,
             prefill_chunk=16, max_queue=64,
             speculative={"mode": "ngram", "num_draft_tokens": 4})
    d.update(kw)
    scfg = ServingConfig(**d)
    scfg.validate()   # coerces the speculative dict; boot path does too
    return scfg


class TestRecommendationsApply:
    def test_valid_artifact_applies_all_three_knobs(self):
        from deepspeed_tpu.autotuning.livetuner import apply_recommendations

        scfg = base_scfg()
        applied, refused = apply_recommendations(
            scfg, {"recommendations": [SPEC_REC, BLOCKS_REC, CHUNK_REC]})
        assert not refused
        assert [a["knob"] for a in applied] == [
            "speculative.num_draft_tokens", "serving.num_blocks",
            "serving.prefill_chunk"]
        assert scfg.speculative.num_draft_tokens == 5
        assert scfg.num_blocks == 80
        assert scfg.prefill_chunk == 32
        scfg.validate()

    @pytest.mark.parametrize("rec,reason", [
        (dict(SPEC_REC, evidence={"acceptance_rate": 0.95, "proposed": 10}),
         "insufficient_evidence"),
        (dict(BLOCKS_REC, evidence={}), "insufficient_evidence"),
        (dict(CHUNK_REC, evidence={"chunks_per_iteration": 1}),
         "insufficient_evidence"),
        (dict(CHUNK_REC, recommended=24), "not_block_multiple"),
        (dict(BLOCKS_REC, recommended=4), "below_blocks_per_seq"),
        (dict(SPEC_REC, knob="serving.mesh"), "unknown_knob"),
        (dict(SPEC_REC, kind="online"), "not_a_shape_knob"),
        (dict(SPEC_REC, recommended=0), "invalid_value"),
    ])
    def test_refusals_named(self, rec, reason):
        from deepspeed_tpu.autotuning.livetuner import apply_recommendations

        scfg = base_scfg()
        applied, refused = apply_recommendations(
            scfg, {"recommendations": [rec]})
        assert not applied
        assert len(refused) == 1
        assert refused[0]["reason"].startswith(reason)
        # nothing moved
        assert scfg.speculative.num_draft_tokens == 4
        assert scfg.num_blocks == 64 and scfg.prefill_chunk == 16

    def test_spec_knob_refused_when_speculation_off(self):
        from deepspeed_tpu.autotuning.livetuner import apply_recommendations

        scfg = base_scfg(speculative={"mode": "off"})
        _, refused = apply_recommendations(
            scfg, {"recommendations": [SPEC_REC]})
        assert refused[0]["reason"] == "speculative_off"

    def test_format_version_mismatch_refused(self, tmp_path):
        from deepspeed_tpu.autotuning.livetuner import load_recommendations

        p = make_artifact(tmp_path, [SPEC_REC], fmt=99)
        with pytest.raises(ValueError, match="format_version"):
            load_recommendations(p)

    def test_discovery_picks_newest(self, tmp_path):
        from deepspeed_tpu.autotuning.livetuner import (
            discover_recommendations)

        old = tmp_path / "run1"
        new = tmp_path / "run2"
        old.mkdir(), new.mkdir()
        make_artifact(old, [])
        os.utime(old / "tune_recommendations.json", (1, 1))
        want = make_artifact(new, [SPEC_REC])
        assert discover_recommendations(str(tmp_path)) == want
        assert discover_recommendations(str(tmp_path / "empty")) is None

    def test_init_serving_applies_with_provenance(self, tmp_path):
        p = make_artifact(tmp_path, [SPEC_REC, CHUNK_REC])
        from deepspeed_tpu.serving import init_serving

        srv = init_serving("tiny", serving_config=dict(
            block_size=16, num_blocks=64, max_seqs=4, max_model_len=128,
            prefill_chunk=16,
            speculative={"mode": "ngram", "num_draft_tokens": 4}),
            recommendations=p, dtype=jnp.float32)
        assert srv.config.speculative.num_draft_tokens == 5
        assert srv.config.prefill_chunk == 32
        assert [a["knob"] for a in srv.recommendations_applied] == [
            "speculative.num_draft_tokens", "serving.prefill_chunk"]
        assert srv.recommendations_refused == []
        # provenance counters land in the process registry -> report line
        reg = get_registry()
        series = reg.counter("tune/recommendations_applied").series()
        assert sum(series.values()) == 2
        from deepspeed_tpu.observability.report import summarize_autotune
        out = summarize_autotune(reg.snapshot())
        assert "recommendations applied at boot" in out
        assert "speculative.num_draft_tokens" in out

    def test_init_serving_refuses_bad_artifact_and_boots(self, tmp_path):
        p = make_artifact(tmp_path, [SPEC_REC], fmt=99)
        from deepspeed_tpu.serving import init_serving

        srv = init_serving("tiny", serving_config=dict(
            block_size=16, num_blocks=64, max_seqs=4, max_model_len=128,
            prefill_chunk=16,
            speculative={"mode": "ngram", "num_draft_tokens": 4}),
            recommendations=p, dtype=jnp.float32)
        # configured shapes untouched; the refusal is named
        assert srv.config.speculative.num_draft_tokens == 4
        assert srv.recommendations_applied == []
        assert srv.recommendations_refused[0]["reason"].startswith(
            "format_version")
        series = get_registry().counter(
            "tune/recommendations_refused").series()
        assert sum(series.values()) == 1

    def test_init_serving_auto_without_artifact(self, tmp_path,
                                                monkeypatch):
        from deepspeed_tpu.serving import init_serving

        monkeypatch.chdir(tmp_path)   # no dstpu_obs dir here
        srv = init_serving("tiny", serving_config=dict(
            block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16), recommendations="auto", dtype=jnp.float32)
        assert srv.recommendations_applied == []


# ---------------------------------------------------------------------------
# benchdiff learns profile_summary.json (satellite)
# ---------------------------------------------------------------------------


class TestBenchdiffProfileSummary:
    def _load_benchdiff(self):
        import importlib.util

        path = os.path.join(os.path.dirname(__file__), "..", "..",
                            "scripts", "benchdiff.py")
        spec = importlib.util.spec_from_file_location("benchdiff", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _summary(self, tmp_path, name, err):
        doc = {"format": 1, "entries": {
            "serving/decode": {"measured_step_ms": 2.0,
                               "predicted_step_ms": 1.0,
                               "model_error": err, "measured_mfu": 0.1,
                               "device_s": 0.5, "invocations": 100}}}
        p = tmp_path / name
        with open(p, "w") as fh:
            json.dump(doc, fh)
        return str(p)

    def test_widening_model_error_flags_regression(self, tmp_path):
        bd = self._load_benchdiff()
        old = bd.load(self._summary(tmp_path, "old.json", 1.1))
        new = bd.load(self._summary(tmp_path, "new.json", 2.2))
        rows = list(bd.diff(old, new, threshold_pct=5.0))
        flagged = {path: flag for _, path, _, _, flag in rows}
        assert flagged["serving/decode.model_error"] == "REGRESSION"

    def test_direction_tokens(self):
        bd = self._load_benchdiff()
        assert bd.direction("serving/decode.model_error") == -1
        assert bd.direction("serving/decode.measured_mfu") == 1
        assert bd.direction("serving/decode.device_s") == -1
        # pre-existing classification unharmed by the new tokens
        assert bd.direction(
            "serve_goodput/fleet_tokens_per_device_sec") == 1

    def test_non_summary_json_rejected(self, tmp_path):
        bd = self._load_benchdiff()
        p = tmp_path / "x.json"
        p.write_text("{}")
        with pytest.raises(SystemExit):
            bd.load(str(p))
