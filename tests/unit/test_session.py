"""Self-healing training session tests (runtime/session.py +
observability/faultinject.py + the goodput `recovery` bucket + the report
CLI's resilience section).

Policy/plumbing tests run against a fake engine with fake clocks — no
sleeps, no devices. The real-engine smoke (8 virtual CPU devices, numerics
sentinel on abort, NaN fault injected) exercises the acceptance loop:
failure → detect → rollback → replay, with the post-recovery loss sequence
bit-identical to a clean run restarted from the same checkpoint. The
multi-process kill→shrink→resume end-to-end lives in TestChaosEndToEnd
(slow marker; scripts/chaos.sh runs it as the CI chaos gate).
"""

import json
import os
import sys
import textwrap

import numpy as np
import pytest

from deepspeed_tpu.config.config import ResilienceConfig
from deepspeed_tpu.observability import NumericsTrip
from deepspeed_tpu.observability.faultinject import (Fault, FaultInjector,
                                                     load_plan)
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.runtime.session import RecoveryExhausted, TrainingSession

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class FakeEngine:
    """Minimal engine surface for supervisor policy tests: step counter,
    tag-addressed checkpoint state, scripted failures."""

    def __init__(self, fail=None):
        self.global_steps = 0
        self.fail = dict(fail or {})   # step -> exception to raise once
        self.params = {"w": 0.0}
        self._tags = {}
        self.loads = 0

    def train_batch(self, batch=None):
        exc = self.fail.pop(self.global_steps, None)
        if exc is not None:
            raise exc
        self.global_steps += 1
        return float(self.global_steps)

    def save_checkpoint(self, save_dir, **kw):
        tag = f"step{self.global_steps}"
        self._tags[tag] = self.global_steps
        os.makedirs(save_dir, exist_ok=True)
        with open(os.path.join(save_dir, "latest"), "w") as fh:
            fh.write(tag)
        return os.path.join(save_dir, tag)

    def load_checkpoint(self, load_dir, verify=False, **kw):
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            return None, {}
        with open(latest) as fh:
            tag = fh.read()
        self.loads += 1
        self.global_steps = self._tags[tag]
        return load_dir, {"_checkpoint_tag": tag,
                          "global_steps": self.global_steps}


def make_session(tmp_path, engine, **cfg):
    cfg.setdefault("checkpoint_every_steps", 2)
    return TrainingSession(lambda: engine, lambda step: {"step": step},
                           total_steps=8, save_dir=str(tmp_path),
                           resilience=ResilienceConfig(**cfg))


class TestPolicySelection:
    def test_numerics_rollback(self, tmp_path):
        eng = FakeEngine(fail={5: NumericsTrip("nan")})
        s = make_session(tmp_path, eng)
        out = s.run()
        assert out["completed"] and out["rollbacks"] == 1
        ev = out["recoveries"][0]
        assert ev["kind"] == "numerics" and ev["policy"] == "rollback"
        # rollback landed on the last cadence save before the failure
        assert ev["failed_step"] == 5 and ev["resumed_step"] == 4
        assert ev["tag"] == "step4"

    def test_numerics_skip_continues_without_rollback(self, tmp_path):
        eng = FakeEngine(fail={5: NumericsTrip("nan")})
        s = make_session(tmp_path, eng, on_numerics="skip")
        out = s.run()
        assert out["completed"] and out["rollbacks"] == 0
        assert out["recoveries"][0]["policy"] == "skip"
        assert eng.loads == 0

    def test_numerics_raise(self, tmp_path):
        eng = FakeEngine(fail={5: NumericsTrip("nan")})
        s = make_session(tmp_path, eng, on_numerics="raise")
        with pytest.raises(NumericsTrip):
            s.run()

    def test_crash_raises_by_default(self, tmp_path):
        eng = FakeEngine(fail={3: RuntimeError("boom")})
        s = make_session(tmp_path, eng)
        with pytest.raises(RuntimeError, match="boom"):
            s.run()

    def test_crash_rollback_when_configured(self, tmp_path):
        eng = FakeEngine(fail={3: RuntimeError("boom")})
        s = make_session(tmp_path, eng, on_crash="rollback")
        out = s.run()
        assert out["completed"]
        assert out["recoveries"][0]["kind"] == "crash"

    def test_rollback_budget_exhausted(self, tmp_path):
        eng = FakeEngine()
        # persistent failure: every attempt at step 3 trips again
        orig = eng.train_batch

        def always_fail(batch=None):
            if eng.global_steps == 3:
                raise NumericsTrip("sticky nan")
            return orig(batch)

        eng.train_batch = always_fail
        s = make_session(tmp_path, eng, max_rollbacks=2)
        with pytest.raises(RecoveryExhausted) as ei:
            s.run()
        assert s.rollbacks == 2
        assert isinstance(ei.value.__cause__, NumericsTrip)

    def test_rollback_without_restore_point_reraises(self, tmp_path):
        eng = FakeEngine(fail={1: NumericsTrip("nan")})
        eng.save_checkpoint = lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("no saves in this test"))
        s = TrainingSession(lambda: eng, lambda step: {}, total_steps=4,
                            save_dir=str(tmp_path),
                            resilience=ResilienceConfig())
        s._wire(eng)
        with pytest.raises(NumericsTrip):
            s._rollback("numerics", NumericsTrip("nan"))

    def test_resume_from_existing_checkpoint(self, tmp_path):
        eng = FakeEngine()
        s = make_session(tmp_path, eng)
        s.run()
        eng2 = FakeEngine()
        eng2._tags = dict(eng._tags)
        s2 = make_session(tmp_path, eng2)
        out = s2.run()
        # nothing to do: resumed at step 8 == total
        assert out["completed"] and eng2.global_steps == 8
        assert eng2.loads == 1

    def test_recovery_metrics_published(self, tmp_path):
        eng = FakeEngine(fail={5: NumericsTrip("nan")})
        s = make_session(tmp_path, eng)
        reg = MetricsRegistry()
        s._registry = lambda: reg
        s.run()
        events = reg.counter("resilience/recovery_events").series()
        assert sum(events.values()) == 1
        (labels,) = events.keys()
        assert dict(labels)["kind"] == "numerics"
        assert dict(labels)["policy"] == "rollback"
        assert sum(reg.counter(
            "resilience/recovery_seconds").series().values()) >= 0


class TestHangEscalation:
    class _Hang:
        def __init__(self):
            self.abort = False
            self.abort_after_fires = 1
            self.fired = 0

    class _Obs:
        def __init__(self):
            self.hang = TestHangEscalation._Hang()
            self.fleet = None
            self.recorder = None
            from deepspeed_tpu.observability.metrics import MetricsRegistry
            self.registry = MetricsRegistry()

        def span(self, name, **kw):
            from deepspeed_tpu.observability.spans import SpanTracer
            return SpanTracer(enabled=False).span(name)

    def test_wire_sets_escalation_ladder(self, tmp_path):
        eng = FakeEngine()
        eng._obs = self._Obs()
        s = make_session(tmp_path, eng, hang_soft_restarts=2)
        s._wire(eng)
        assert eng._obs.hang.abort is True
        assert eng._obs.hang.abort_after_fires == 3

    def test_fire_triggers_soft_restart_on_return(self, tmp_path):
        """A watchdog fire during a step that EVENTUALLY returns control is
        remediated by an in-process engine rebuild + reload at the next
        loop iteration (the dump→soft-restart rungs of the ladder)."""
        obs = self._Obs()
        first = FakeEngine()
        first._obs = obs
        fresh = FakeEngine()
        fresh._obs = obs
        built = []

        def factory():
            eng = first if not built else fresh
            eng._tags = dict(first._tags)   # share the checkpoint store
            built.append(eng)
            return eng

        orig = FakeEngine.train_batch

        def slow_step(batch=None):
            if first.global_steps == 3 and obs.hang.fired == 0:
                obs.hang.fired += 1   # the watchdog fired mid-stall...
            return orig(first, batch)  # ...but the step returned

        first.train_batch = slow_step
        s = TrainingSession(factory, lambda step: {}, total_steps=6,
                            save_dir=str(tmp_path),
                            resilience=ResilienceConfig(
                                checkpoint_every_steps=2))
        out = s.run()
        assert out["soft_restarts"] == 1 and out["completed"]
        ev = [r for r in out["recoveries"] if r["policy"] == "soft_restart"]
        assert ev and ev[0]["kind"] == "hang"
        assert built == [first, fresh]   # the rebuild used the factory
        assert fresh.global_steps == 6   # the fresh engine finished the run

    def test_soft_restart_budget_escalates(self, tmp_path):
        """Each rebuild installs a FRESH watchdog, so the ladder's hard rung
        is enforced session-side: past hang_soft_restarts the session
        raises RecoveryExhausted (worker exits nonzero → agent restart)."""
        obs = self._Obs()
        engines = []

        def factory():
            eng = FakeEngine()
            eng._obs = obs
            if engines:
                eng._tags = dict(engines[0]._tags)
            engines.append(eng)
            return eng

        s = TrainingSession(factory, lambda step: {}, total_steps=64,
                            save_dir=str(tmp_path),
                            resilience=ResilienceConfig(
                                checkpoint_every_steps=2,
                                hang_soft_restarts=1))
        s._wire(factory())
        s._resume(s.engine)
        s.engine.save_checkpoint(str(tmp_path))
        s._soft_restart()               # rung 1: within budget
        assert s.soft_restarts == 1
        with pytest.raises(RecoveryExhausted, match="soft-restart budget"):
            s._soft_restart()           # rung 2: escalate to the agent
        assert s.soft_restarts == 1


class TestStragglerEviction:
    class _Fleet:
        def __init__(self, rank=0, world=8):
            self.rank, self.world = rank, world
            self.on_straggler = None

    class _Obs:
        def __init__(self, fleet):
            self.hang = None
            self.fleet = fleet
            self.recorder = None
            self.registry = MetricsRegistry()

        def span(self, name, **kw):
            from deepspeed_tpu.observability.spans import SpanTracer
            return SpanTracer(enabled=False).span(name)

    def _session(self, tmp_path, fleet, **cfg):
        eng = FakeEngine()
        eng._obs = self._Obs(fleet)
        cfg.setdefault("straggler_patience", 2)
        s = make_session(tmp_path, eng, **cfg)
        s._wire(eng)
        return s

    def test_patience_then_request(self, tmp_path, monkeypatch):
        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        monkeypatch.setenv("DSTPU_AGENT_DIR", str(agent_dir))
        fleet = self._Fleet()
        s = self._session(tmp_path, fleet)
        fleet.on_straggler(3, {"step": 10, "step_time_s": 0.9,
                               "fleet_median_s": 0.1})
        assert not (agent_dir / "evict.json").exists()   # patience 2
        fleet.on_straggler(3, {"step": 20, "step_time_s": 0.9,
                               "fleet_median_s": 0.1})
        req = json.loads((agent_dir / "evict.json").read_text())
        assert req["rank"] == 3 and "straggler" in req["reason"]
        assert s.evictions_requested == 1
        # once per incarnation
        fleet.on_straggler(3, {"step": 30})
        assert s.evictions_requested == 1

    def test_streak_resets_on_different_rank(self, tmp_path, monkeypatch):
        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        monkeypatch.setenv("DSTPU_AGENT_DIR", str(agent_dir))
        fleet = self._Fleet()
        s = self._session(tmp_path, fleet)
        fleet.on_straggler(3, {"step": 10})
        fleet.on_straggler(5, {"step": 20})
        fleet.on_straggler(3, {"step": 30})
        assert not (agent_dir / "evict.json").exists()
        assert s.evictions_requested == 0

    def test_min_world_floor_blocks_eviction(self, tmp_path, monkeypatch):
        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        monkeypatch.setenv("DSTPU_AGENT_DIR", str(agent_dir))
        fleet = self._Fleet(world=4)
        s = self._session(tmp_path, fleet, min_world=4)
        for step in (10, 20, 30):
            fleet.on_straggler(2, {"step": step})
        assert not (agent_dir / "evict.json").exists()
        assert s.evictions_requested == 0

    def test_only_rank0_writes(self, tmp_path, monkeypatch):
        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        monkeypatch.setenv("DSTPU_AGENT_DIR", str(agent_dir))
        fleet = self._Fleet(rank=1)
        s = self._session(tmp_path, fleet)
        for step in (10, 20):
            fleet.on_straggler(3, {"step": step})
        assert not (agent_dir / "evict.json").exists()


class TestFaultInjector:
    def test_plan_parsing(self, tmp_path):
        plan = load_plan('[{"kind": "rank_kill", "step": 3, "rank": 2}]')
        assert plan[0].kind == "rank_kill" and plan[0].restart == 0
        p = tmp_path / "plan.json"
        p.write_text('[{"kind": "straggle", "step": 1, "sleep_s": 0.5}]')
        plan = load_plan(f"@{p}")
        assert plan[0].sleep_s == 0.5
        with pytest.raises(ValueError, match="unknown fault kind"):
            load_plan('[{"kind": "meteor", "step": 1}]')
        with pytest.raises(ValueError, match="unknown keys"):
            load_plan('[{"kind": "rank_kill", "step": 1, "zap": true}]')

    def test_from_env(self, monkeypatch):
        monkeypatch.delenv("DSTPU_FAULT_PLAN", raising=False)
        assert FaultInjector.from_env() is None
        monkeypatch.setenv("DSTPU_FAULT_PLAN",
                           '[{"kind": "rank_kill", "step": 2, "rank": 1}]')
        monkeypatch.setenv("RANK", "1")
        monkeypatch.setenv("DSTPU_RESTART_COUNT", "0")
        inj = FaultInjector.from_env()
        assert inj is not None and inj.rank == 1 and inj.restart == 0

    def test_rank_kill_targets_step_rank_restart(self):
        kills = []
        inj = FaultInjector(
            plan=[Fault(kind="rank_kill", step=3, rank=2, restart=0)],
            rank=2, restart=0, kill_fn=lambda: kills.append(True))
        inj.before_step(2)
        assert not kills
        inj.before_step(3)
        assert len(kills) == 1
        # wrong rank / wrong incarnation never fire
        for rank, restart in ((1, 0), (2, 1)):
            other = FaultInjector(
                plan=[Fault(kind="rank_kill", step=3, rank=2, restart=0)],
                rank=rank, restart=restart,
                kill_fn=lambda: kills.append(True))
            other.before_step(3)
        assert len(kills) == 1

    def test_straggle_sleeps_for_duration(self):
        sleeps = []
        inj = FaultInjector(
            plan=[Fault(kind="straggle", step=2, rank=0, sleep_s=0.25,
                        steps=3)],
            rank=0, restart=0, sleep_fn=sleeps.append)
        for step in range(7):
            inj.before_step(step)
        assert sleeps == [0.25, 0.25, 0.25]
        assert inj.applied[0]["kind"] == "straggle"

    def test_nan_params_poisons_first_float_leaf(self):
        import jax.numpy as jnp

        class E:
            params = {"a": jnp.ones((4,), jnp.int32),
                      "b": jnp.ones((2, 2), jnp.float32),
                      "c": jnp.ones((3,), jnp.float32)}

        eng = E()
        inj = FaultInjector(plan=[Fault(kind="nan_params", step=1, rank=0)],
                            rank=0, restart=0)
        inj.before_step(1, engine=eng)
        assert np.isnan(np.asarray(eng.params["b"])).all()
        assert np.isfinite(np.asarray(eng.params["c"])).all()
        assert np.asarray(eng.params["a"]).sum() == 4   # int leaf untouched

    def test_ckpt_truncate_maims_latest_tag(self, tmp_path, devices8):
        import jax.numpy as jnp
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec as P)

        from deepspeed_tpu.runtime.checkpoint import (save_checkpoint,
                                                      verify_checkpoint)

        mesh = Mesh(np.array(devices8), ("data",))
        params = {"w": jax.device_put(jnp.ones((8, 8)),
                                      NamedSharding(mesh, P("data", None)))}
        save_checkpoint(str(tmp_path), "t1", params)
        inj = FaultInjector(
            plan=[Fault(kind="ckpt_truncate", step=0, rank=0)],
            rank=0, restart=0)
        inj.after_save(str(tmp_path))
        assert verify_checkpoint(str(tmp_path), "t1")   # problems found
        assert inj.applied[0]["kind"] == "ckpt_truncate"
        # one-shot: a second save is not re-maimed
        save_checkpoint(str(tmp_path), "t2", params)
        inj.after_save(str(tmp_path))
        assert not verify_checkpoint(str(tmp_path), "t2")


class TestGoodputRecoveryBucket:
    def _accountant(self):
        from deepspeed_tpu.observability.goodput import GoodputAccountant

        t = [1000.0]
        acc = GoodputAccountant(registry=MetricsRegistry(),
                                clock=lambda: t[-1])
        return acc, t

    def test_recovery_span_swallows_nested_buckets(self):
        acc, _ = self._accountant()
        # a normal checkpoint span -> checkpoint bucket
        acc.on_span("begin", "checkpoint/save", 10.0)
        acc.on_span("end", "checkpoint/save", 12.0, dur_s=2.0)
        # a rollback: recovery span with the reload's checkpoint span inside
        acc.on_span("begin", "recovery/rollback", 20.0)
        acc.on_span("begin", "checkpoint/load", 20.5)
        acc.on_span("end", "checkpoint/load", 23.5, dur_s=3.0)
        acc.on_compile(1.0, where="train_batch/dispatch")
        acc.on_span("end", "recovery/rollback", 25.0, dur_s=5.0)
        tot = acc.totals()
        assert tot["buckets"]["recovery"] == pytest.approx(5.0)
        assert tot["buckets"]["checkpoint"] == pytest.approx(2.0)
        # the nested load + compile were swallowed, not double-bucketed
        assert tot["buckets"]["recompile"] == pytest.approx(0.0)
        assert sum(tot["buckets"].values()) == pytest.approx(tot["wall_s"])

    def test_bucket_sums_equal_wall_with_recovery_between_steps(self):
        acc, _ = self._accountant()
        acc.on_span("begin", "train_batch", 0.0)
        acc.on_span("begin", "train_batch/dispatch", 0.1)
        acc.on_span("end", "train_batch/dispatch", 0.9, dur_s=0.8)
        acc.on_span("end", "train_batch", 1.0, dur_s=1.0)
        acc.on_span("begin", "recovery/rollback", 1.2)
        acc.on_span("end", "recovery/rollback", 2.2, dur_s=1.0)
        acc.on_span("begin", "train_batch", 2.5)
        acc.on_span("begin", "train_batch/dispatch", 2.6)
        acc.on_span("end", "train_batch/dispatch", 3.4, dur_s=0.8)
        acc.on_span("end", "train_batch", 3.5, dur_s=1.0)
        tot = acc.totals()
        assert tot["buckets"]["recovery"] == pytest.approx(1.0)
        # the recovery second is NOT re-counted as input_wait in the
        # inter-step gap (only the 0.2s + 0.3s of unattributed gap is)
        assert tot["buckets"]["input_wait"] == pytest.approx(0.5)
        assert sum(tot["buckets"].values()) == pytest.approx(tot["wall_s"])
        assert tot["steps"] == 2

    def test_recovery_in_buckets_constant(self):
        from deepspeed_tpu.observability.goodput import BUCKETS

        assert "recovery" in BUCKETS

    def test_step_span_ending_inside_recovery_keeps_gap_math(self):
        """A step span whose end lands inside a recovery region must still
        reset the in-step flag, or input_wait attribution wedges for the
        rest of the run."""
        acc, _ = self._accountant()
        acc.on_span("begin", "train_batch", 0.0)
        acc.on_span("begin", "recovery/rollback", 0.5)
        acc.on_span("end", "train_batch", 0.9, dur_s=0.9)   # swallowed end
        acc.on_span("end", "recovery/rollback", 1.5, dur_s=1.0)
        acc.on_span("begin", "train_batch", 2.0)
        acc.on_span("end", "train_batch", 3.0, dur_s=1.0)
        tot = acc.totals()
        assert tot["steps"] == 2
        # gap 0.9→2.0 minus the 1.0s recovery tail = 0.1s of input wait
        assert tot["buckets"]["input_wait"] == pytest.approx(0.1)
        assert sum(tot["buckets"].values()) == pytest.approx(tot["wall_s"])


class TestReportResilience:
    def _records(self):
        return [
            {"type": "counter", "name": "resilience/recovery_events",
             "labels": {"kind": "numerics", "policy": "rollback"},
             "value": 2},
            {"type": "counter", "name": "resilience/recovery_events",
             "labels": {"kind": "hang", "policy": "soft_restart"},
             "value": 1},
            {"type": "counter", "name": "resilience/recovery_seconds",
             "labels": {}, "value": 4.5},
            {"type": "gauge", "name": "resilience/last_recovery_s",
             "labels": {}, "value": 1.5},
            {"type": "counter", "name": "resilience/evictions_requested",
             "labels": {"rank": 3}, "value": 1},
            {"type": "counter", "name": "resilience/faults_injected",
             "labels": {"kind": "rank_kill"}, "value": 1},
            {"type": "gauge", "name": "goodput/seconds",
             "labels": {"bucket": "recovery"}, "value": 4.5},
            {"type": "gauge", "name": "goodput/wall_seconds",
             "labels": {}, "value": 45.0},
            {"type": "gauge", "name": "goodput/goodput_fraction",
             "labels": {}, "value": 0.8},
        ]

    def test_section_renders(self):
        from deepspeed_tpu.observability.report import summarize_resilience

        text = summarize_resilience(self._records())
        assert "== resilience ==" in text
        assert "numerics" in text and "rollback" in text
        assert "soft_restart" in text
        assert "eviction requests: 1" in text
        assert "rank_kill=1" in text
        assert "total=4.500s" in text and "mean=1.500s" in text
        assert "recovery bucket 4.500s (10.0% of wall)" in text
        assert "goodput_fraction = 0.8000" in text

    def test_report_includes_section(self):
        from deepspeed_tpu.observability.report import report

        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                         delete=False) as fh:
            for r in self._records():
                fh.write(json.dumps(r) + "\n")
            path = fh.name
        try:
            out = report([path])
            assert "== resilience ==" in out
        finally:
            os.unlink(path)

    def test_absent_without_metrics(self):
        from deepspeed_tpu.observability.report import summarize_resilience

        assert summarize_resilience([{"type": "gauge", "name": "goodput/mfu",
                                      "labels": {}, "value": 0.5}]) == ""


class TestElasticEnvOverrides:
    def test_noop_without_env(self):
        from deepspeed_tpu.config import Config
        from deepspeed_tpu.elasticity import apply_elastic_env_overrides

        cfg = Config(train_batch_size=16,
                     train_micro_batch_size_per_gpu=2)
        out = apply_elastic_env_overrides(cfg, env={})
        assert out is cfg

    def test_override_replaces_micro_and_clears_gas(self):
        from deepspeed_tpu.config import Config
        from deepspeed_tpu.elasticity import apply_elastic_env_overrides

        cfg = Config(train_batch_size=16, train_micro_batch_size_per_gpu=2,
                     gradient_accumulation_steps=1)
        out = apply_elastic_env_overrides(
            cfg, env={"DSTPU_ELASTIC_MICRO": "4"})
        assert out.train_micro_batch_size_per_gpu == 4
        assert out.gradient_accumulation_steps == 0
        assert out.train_batch_size == 16
        # the engine's triad resolution now derives gas for the new world
        assert out.resolve_batch_sizes(2).gradient_accumulation_steps == 2

    def test_micro_gas_config_preserves_global_batch_via_batch_env(self):
        """A config expressing its batch as micro+gas (no train_batch_size)
        must still preserve the GLOBAL batch across a shrink — the agent
        ships it as DSTPU_ELASTIC_BATCH."""
        from deepspeed_tpu.config import Config
        from deepspeed_tpu.elasticity import apply_elastic_env_overrides

        cfg = Config(train_micro_batch_size_per_gpu=4,
                     gradient_accumulation_steps=8)
        out = apply_elastic_env_overrides(
            cfg, env={"DSTPU_ELASTIC_MICRO": "2",
                      "DSTPU_ELASTIC_BATCH": "48"})
        assert out.train_batch_size == 48
        assert out.resolve_batch_sizes(6).gradient_accumulation_steps == 4
        # without the batch env and no tb, the override cannot preserve the
        # global batch: it must refuse rather than silently shrink it
        out2 = apply_elastic_env_overrides(
            cfg, env={"DSTPU_ELASTIC_MICRO": "2"})
        assert out2 is cfg

    def test_agent_exports_elastic_batch_env(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig)

        elastic = {"elasticity": {
            "enabled": True, "max_train_batch_size": 48,
            "micro_batch_sizes": [2], "min_gpus": 1, "max_gpus": 8,
            "version": 0.1}}
        probe = tmp_path / "p.py"
        probe.write_text(
            "import json, os, sys\n"
            "with open(sys.argv[1], 'w') as fh:\n"
            "    fh.write(json.dumps({'b': os.environ['DSTPU_ELASTIC_BATCH'],"
            " 'm': os.environ['DSTPU_ELASTIC_MICRO']}))\n")
        out = tmp_path / "env.json"
        agent = ElasticAgent(
            [sys.executable, str(probe), str(out)], nprocs=8,
            config=ElasticAgentConfig(master_port=29557,
                                      monitor_interval=0.05,
                                      elastic_config=elastic))
        assert agent.run() == 0
        env = json.loads(out.read_text())
        assert env == {"b": "48", "m": "2"}


import jax  # noqa: E402  (after the conftest env setup)


class TestSessionEngineSmoke:
    """The in-process half of the chaos acceptance: a supervised session on
    the 8-device CPU mesh survives an injected NaN step via sentinel-abort →
    rollback, and the post-recovery losses are bit-identical to a clean run
    restarted from the same checkpoint."""

    def _build(self, tmp_path, obs_dir, inj=None, numerics=True):
        import deepspeed_tpu as ds
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      build_model)

        model = build_model(TransformerConfig(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
            max_seq_len=16))
        cfg = {
            "train_micro_batch_size_per_gpu": 1, "steps_per_print": 1000,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "observability": {
                "enabled": True, "output_dir": str(obs_dir),
                "numerics_sentinel": numerics, "numerics_action": "abort",
                "numerics_check_steps": 1},
            "resilience": {"checkpoint_every_steps": 2, "max_rollbacks": 2},
        }
        return ds, model, cfg

    @staticmethod
    def _data_fn(step):
        r = np.random.default_rng(1234 + step)
        return {"input_ids": r.integers(0, 64, (1, 8, 16))}

    def test_nan_rollback_bit_continuity(self, tmp_path):
        ds, model, cfg = self._build(tmp_path / "ck", tmp_path / "obs")
        inj = FaultInjector(
            plan=[Fault(kind="nan_params", step=5, rank=0)],
            rank=0, restart=0)
        steps = []
        out = ds.run_training_session(
            model=model, config=cfg, data_fn=self._data_fn, total_steps=8,
            save_dir=str(tmp_path / "ck"), injector=inj,
            on_step=lambda step, loss: steps.append((step, loss)))
        from deepspeed_tpu.observability import get_session, reset_session

        try:
            assert out["completed"] and out["rollbacks"] == 1
            ev = out["recoveries"][0]
            assert ev["kind"] == "numerics" and ev["policy"] == "rollback"
            assert ev["tag"] == "global_step4"
            assert all(np.isfinite(l) for _, l in steps)
            # goodput: the lost time landed in `recovery`; sums == wall
            tot = get_session().goodput.totals()
            assert tot["buckets"]["recovery"] > 0
            assert sum(tot["buckets"].values()) == pytest.approx(
                tot["wall_s"])
            # the report CLI surfaces the event
            mpath = get_session().dump_metrics(
                str(tmp_path / "metrics.jsonl"))
            from deepspeed_tpu.observability.report import report

            text = report([mpath])
            assert "== resilience ==" in text
            assert "numerics" in text and "rollback" in text
        finally:
            reset_session()

        # control: a fresh engine restarted from the SAME checkpoint the
        # rollback used, replaying the same data — bit-identical losses
        chaos_after = [(s, l) for s, l in steps[-4:]]   # steps 4..7 replayed
        assert [s for s, _ in chaos_after] == [4, 5, 6, 7]
        engine, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1, "steps_per_print": 1000,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
        try:
            engine.load_checkpoint(str(tmp_path / "ck"), tag="global_step4",
                                   verify=True)
            assert engine.global_steps == 4
            control = []
            while engine.global_steps < 8:
                step = engine.global_steps
                control.append(
                    (step,
                     float(engine.train_batch(batch=self._data_fn(step)))))
            assert control == chaos_after   # BIT-identical, not allclose
        finally:
            reset_session()


@pytest.mark.slow
class TestChaosEndToEnd:
    """kill → shrink → re-rendezvous → resume end-to-end, driven through the
    real ElasticAgent + run_training_session on an 8-process CPU mesh. The
    fault plan (DSTPU_FAULT_PLAN, exactly as scripts/chaos.sh passes it)
    SIGKILLs rank 2 at step 3 of incarnation 0; the agent shrinks
    membership 8→6 through the elastic batch math (DSTPU_ELASTIC_MICRO
    recomputed, global batch preserved) and the respawned sessions resume
    from their latest checkpoints. A control run (6 processes, no faults)
    restarted from a snapshot of the same restore point must produce a
    BIT-identical post-recovery loss sequence.

    NOTE: this container's jaxlib cannot compile cross-process SPMD
    programs on the CPU backend ("Multiprocess computations aren't
    implemented"), so — like the seed's elastic-agent test — each worker
    runs an independent single-device engine with a per-rank checkpoint
    dir; the supervision loop (agent, fault plan, kill, shrink, elastic
    micro recompute, per-rank resume, bit-continuity) is fully real."""

    WORKER = textwrap.dedent("""
        import json, os, shutil, sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import deepspeed_tpu as ds
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      build_model)

        ckpt_root, log_path, total = (sys.argv[1], sys.argv[2],
                                      int(sys.argv[3]))
        ctrl_copy = os.environ.get("CHAOS_CTRL_COPY", "")
        rank = int(os.environ["RANK"])
        world = int(os.environ["WORLD_SIZE"])
        restart = int(os.environ.get("DSTPU_RESTART_COUNT", "0"))
        ckpt = os.path.join(ckpt_root, f"rank{rank}")
        # per-rank independent engines (this container cannot compile
        # cross-process SPMD on CPU): the fleet-level global batch does
        # not apply — keep the local micro-only batch
        os.environ.pop("DSTPU_ELASTIC_BATCH", None)
        if ctrl_copy and restart == 1 and os.path.isdir(ckpt):
            # snapshot MY restore point before the engine touches it — the
            # control run replays from this exact state (each rank copies
            # only its own quiescent dir: no cross-process races)
            dst = os.path.join(ctrl_copy, f"rank{rank}")
            if not os.path.isdir(dst):
                shutil.copytree(ckpt, dst)

        def data_fn(step):
            # pure function of (step, rank): replay after resume — and the
            # control run — feeds bit-identical data
            r = np.random.default_rng(777 + 1000 * rank + step)
            return {"input_ids": r.integers(0, 64, (1, 2, 16))}

        def on_step(step, loss):
            # append-per-step so a SIGKILL loses nothing already logged
            with open(log_path, "a") as fh:
                fh.write(json.dumps({
                    "rank": rank, "restart": restart, "world": world,
                    "micro": os.environ.get("DSTPU_ELASTIC_MICRO"),
                    "step": step, "loss": repr(loss)}) + chr(10))

        model = build_model(TransformerConfig(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
            max_seq_len=16))
        out = ds.run_training_session(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "steps_per_print": 1000,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "resilience": {"checkpoint_every_steps": 1}},
            data_fn=data_fn, total_steps=total, save_dir=ckpt,
            on_step=on_step)
        assert out["completed"], out
        print("WORKER-DONE", rank, flush=True)
        sys.stdout.flush()
        os._exit(0)   # skip interpreter teardown: a jax atexit segfault
        #   would read as a worker failure and trigger a spurious restart
    """ % REPO)

    # batch 48 / micro 2 => 24 replicas, valid worlds {1,2,3,4,6,8}: the
    # shrink from 8 (min_workers=4) lands on 6, the largest valid below 8
    ELASTIC = {"elasticity": {
        "enabled": True, "max_train_batch_size": 48,
        "micro_batch_sizes": [2], "min_gpus": 1, "max_gpus": 8,
        "version": 0.1}}

    def _agent(self, script, args, nprocs, port, env=None, plan=None):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig)

        env_base = {"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        if env:
            env_base.update(env)
        if plan is not None:
            env_base["DSTPU_FAULT_PLAN"] = json.dumps(plan)
        return ElasticAgent(
            [sys.executable, str(script)] + [str(a) for a in args],
            nprocs=nprocs,
            config=ElasticAgentConfig(
                max_restarts=2, min_workers=4, master_port=port,
                monitor_interval=0.05, backoff_base_s=0.05,
                elastic_config=self.ELASTIC),
            env_base=env_base)

    def test_kill_shrink_resume_bit_continuity(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(self.WORKER)
        ckpt, log = tmp_path / "ck", tmp_path / "chaos.jsonl"
        ctrl = tmp_path / "ck_ctrl"
        ctrl.mkdir()
        total = 6
        agent = self._agent(
            script, [ckpt, log, total], nprocs=8, port=29560,
            env={"CHAOS_CTRL_COPY": str(ctrl)},
            plan=[{"kind": "rank_kill", "step": 3, "rank": 2,
                   "restart": 0}])
        rc = agent.run()
        assert rc == 0
        # >= / in: tolerate ONE unrelated spurious worker crash adding an
        # extra restart (CPU-jax teardown flakes) — the recovery story
        # below (shrink, resume continuity, bit-identical control) is
        # still asserted in full
        assert agent.restart_count >= 1
        assert agent._world in (6, 4)
        lines = [json.loads(l) for l in log.read_text().splitlines()]
        r0 = [l for l in lines if l["rank"] == 0]
        inc0 = [l for l in r0 if l["restart"] == 0]
        inc1 = [l for l in r0 if l["restart"] == 1]
        post = [l for l in r0 if l["restart"] >= 1]
        assert all(l["world"] == 8 for l in inc0)
        assert all(l["world"] == 6 for l in inc1)
        # the shrunken incarnation got the recomputed elastic micro batch
        assert all(l["micro"] == "2" for l in inc1)
        # incarnation 0 died around the rank-2 kill at step 3; incarnation
        # 1 RESUMED from rank 0's last committed checkpoint, not step 0.
        # (The group teardown races rank 0's own post-step save: resume is
        # at the last logged step when the save did not commit, or one
        # past it when it did.)
        # (no `>= 3` floor: ranks are NOT lockstepped here — rank 2 can hit
        # its step-3 kill while rank 0 is still mid-step-2/3, so rank 0's
        # resume point is whatever ITS last commit covered)
        assert inc1[0]["step"] in (inc0[-1]["step"], inc0[-1]["step"] + 1)
        assert post[-1]["step"] == total - 1

        # control: a clean 6-process run restarted from the snapshot the
        # post-kill incarnation took of its own restore point
        log2 = tmp_path / "control.jsonl"
        assert (ctrl / "rank0").is_dir(), "control snapshot was not taken"
        agent2 = self._agent(script, [ctrl, log2, total], nprocs=6,
                             port=29575)
        assert agent2.run() == 0
        ctrl_lines = [json.loads(l) for l in log2.read_text().splitlines()]
        ctrl_r0 = {l["step"]: l["loss"] for l in ctrl_lines
                   if l["rank"] == 0}
        # by-step map over ALL post-kill incarnations: replays re-log the
        # same step with (asserted below) identical losses
        chaos_r0 = {l["step"]: l["loss"] for l in post}
        assert set(chaos_r0) == set(ctrl_r0), (chaos_r0, ctrl_r0)
        for step, loss in chaos_r0.items():
            assert loss == ctrl_r0[step], (
                f"step {step}: chaos {loss} != control {ctrl_r0[step]} — "
                "post-recovery training is not bit-continuous")
