"""tpushard unit tests: registry↔legacy golden spec parity (the migration's
behavior-preservation proof), compiled-HLO canonical-hash parity, the four
finding classes on in-process entries (rule-violation, implicit-reshard,
cross-program-mismatch, replication-waste), the fault-injection seam (a
deliberately wrong rule must fail the gate naming entry, parameter and
expected-vs-actual spec), the report CLI's ``== sharding ==`` section, and
the repo-wide gate (selftest engines vs the committed baseline — what makes
tier-1 enforce program-layout analysis)."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_SHARD, EXPERT_AXIS
from deepspeed_tpu.parallel.rules import (DEFAULT_TP_RULES, EXPERT,
                                          get_policy, policy_names,
                                          resolve_param_specs, shard_tag,
                                          zero_policy)
from deepspeed_tpu.parallel.zero import build_sharding_plan
from tools.tpuaudit import clear_registry, register_entry_point
from tools.tpushard.cli import main as tpushard_main
from tools.tpushard.core import canonical_hash, run_shard

REPO = Path(__file__).resolve().parents[2]


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_registry()
    yield
    clear_registry()


def sds(shape, dtype=jnp.float32, sharding=None):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def mesh3():
    devs = np.array(jax.devices()).reshape(1, 4, 2)
    return Mesh(devs, ("expert", "data", "model"))


SHAPES = {"emb": sds((512, 64)), "w": sds((64, 256)), "b": sds((256,)),
          "experts": sds((4, 64, 64))}
AXES = {"emb": ("vocab", "embed"), "w": ("embed", "mlp"), "b": ("mlp",),
        "experts": ("expert", "embed", "embed")}


# ---------------------------------------------------------------------------
# golden parity: the registry derives EXACTLY the legacy hand-built trees
# (spec equality is the static form of the HLO-parity guarantee: identical
# specs -> identical out_shardings -> identical compiled programs)


class TestPolicyGolden:
    def test_registered_policies(self):
        assert policy_names() == ("fsdp", "serving", "tp")

    def test_tp_matches_legacy_resolution(self):
        assert get_policy("tp").param_specs(SHAPES, AXES) == \
            resolve_param_specs(SHAPES, AXES, dict(DEFAULT_TP_RULES),
                                fsdp_axis=None)

    def test_fsdp_matches_legacy_resolution(self):
        for min_size in (2 ** 11, 2 ** 14, 1):
            assert get_policy("fsdp").param_specs(
                SHAPES, AXES, fsdp_min_size=min_size) == \
                resolve_param_specs(SHAPES, AXES, dict(DEFAULT_TP_RULES),
                                    fsdp_axis=DATA_SHARD,
                                    fsdp_min_size=min_size)

    def test_serving_ep_matches_legacy_resolution(self):
        legacy = resolve_param_specs(
            SHAPES, AXES, {**DEFAULT_TP_RULES, EXPERT: EXPERT_AXIS},
            fsdp_axis=None)
        assert get_policy("serving").param_specs(
            SHAPES, AXES, expert_parallel=True) == legacy
        # the expert bank picked up the expert axis
        assert legacy["experts"][0] == EXPERT_AXIS

    def test_zero_policy_table(self):
        # params: fsdp iff stage >= 3; grads >= 2; masters >= 1
        assert [zero_policy(s, "params").name for s in range(4)] == \
            ["tp", "tp", "tp", "fsdp"]
        assert [zero_policy(s, "grads").name for s in range(4)] == \
            ["tp", "tp", "fsdp", "fsdp"]
        assert [zero_policy(s, "masters").name for s in range(4)] == \
            ["tp", "fsdp", "fsdp", "fsdp"]
        with pytest.raises(ValueError):
            zero_policy(3, "momentum")

    def test_plan_derives_from_registry(self):
        plan = build_sharding_plan(3, SHAPES, AXES, fsdp_min_size=2 ** 11)
        assert plan.param_specs == get_policy("fsdp").param_specs(
            SHAPES, AXES, fsdp_min_size=2 ** 11)
        plan0 = build_sharding_plan(0, SHAPES, AXES)
        assert plan0.param_specs == get_policy("tp").param_specs(SHAPES, AXES)
        assert plan0.grad_specs == plan0.master_specs == plan0.param_specs

    def test_rule_override_seam(self):
        rules = get_policy("tp").rules_dict(overrides={"vocab": "data"})
        assert rules["vocab"] == "data"
        # the policy's own rules are immutable — overrides never leak back
        assert dict(get_policy("tp").rules)["vocab"] == "model"

    def test_shard_tag_validates_policy(self):
        tag = shard_tag("serving", axes=AXES, expert_parallel=True,
                        group="g")
        assert tag["policy"] == "serving" and tag["group"] == "g"
        with pytest.raises(KeyError):
            shard_tag("nope", axes=AXES)

    def test_hlo_parity_registry_vs_legacy(self):
        """The actual compiled programs are identical whichever path
        resolves the specs — the load-bearing migration guarantee."""
        mesh = mesh3()
        specs_new = get_policy("tp").param_specs(SHAPES, AXES)
        specs_old = resolve_param_specs(SHAPES, AXES, dict(DEFAULT_TP_RULES),
                                        fsdp_axis=None)

        def compile_with(specs):
            shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                     is_leaf=lambda x: isinstance(x, P))
            fn = jax.jit(lambda p: jax.tree.map(lambda a: a * 2.0, p),
                         out_shardings=shardings)
            args = jax.tree.map(
                lambda x, s: sds(x.shape, x.dtype,
                                 sharding=NamedSharding(mesh, s)),
                SHAPES, specs)
            return fn.trace(args).lower().compile().as_text()

        assert canonical_hash(compile_with(specs_new)) == \
            canonical_hash(compile_with(specs_old))


class TestCanonicalHash:
    def test_metadata_and_whitespace_invariant(self):
        a = ('%add = f32[4] add(%x, %y), metadata={op_name="jit(f)/add" '
             'source_file="a.py" source_line=3}\n')
        b = ('%add  =  f32[4]  add(%x, %y), metadata={op_name="jit(g)/add" '
             'source_file="b.py" source_line=99}')
        assert canonical_hash(a) == canonical_hash(b)

    def test_distinguishes_programs(self):
        assert canonical_hash("%add = f32[4] add(%x, %y)") != \
            canonical_hash("%mul = f32[4] multiply(%x, %y)")


# ---------------------------------------------------------------------------
# the analyzer on in-process entries


def _register(name, params, axes, policy="tp", group=None, fn=None,
              mesh=None, expected_collectives=frozenset(), **tag_kw):
    mesh = mesh or mesh3()
    fn = fn or (lambda p: jax.tree.map(lambda a: a * 2.0, p))
    register_entry_point(
        name, fn=jax.jit(fn), args=(params,),
        expected_collectives=expected_collectives, mesh=mesh,
        tags={"shard": shard_tag(policy, axes=axes, group=group, **tag_kw)})


def _placed(specs, mesh):
    return jax.tree.map(
        lambda x, s: sds(x.shape, x.dtype, sharding=NamedSharding(mesh, s)),
        SHAPES, specs)


class TestAnalyzer:
    def test_clean_entry(self):
        mesh = mesh3()
        params = _placed(get_policy("tp").param_specs(SHAPES, AXES), mesh)
        _register("t/clean", params, AXES, mesh=mesh)
        from tools.tpuaudit.registry import get_entry_points

        findings, reports = run_shard(get_entry_points(),
                                      publish_metrics=False)
        assert findings == []
        (r,) = reports
        assert r.entry == "t/clean" and r.policy == "tp"
        assert r.params_checked == r.params_total == 4
        assert r.rule_violations == 0 and r.program_hash

    def test_rule_violation_names_param_and_specs(self):
        mesh = mesh3()
        specs = get_policy("tp").param_specs(SHAPES, AXES)
        # misplace the embedding: vocab belongs on 'model', put it on dim 1
        specs = {**specs, "emb": P(None, "model")}
        _register("t/bad", _placed(specs, mesh), AXES, mesh=mesh)
        from tools.tpuaudit.registry import get_entry_points

        findings, reports = run_shard(get_entry_points(),
                                      publish_metrics=False)
        viol = [f for f in findings if f.check == "rule-violation"]
        assert len(viol) == 1 and viol[0].entry == "t/bad"
        assert "['emb']" in viol[0].message
        assert "PartitionSpec('model', None)" in viol[0].message  # expected
        assert "PartitionSpec(None, 'model')" in viol[0].message  # actual
        assert reports[0].rule_violations == 1

    def test_injected_bad_rule_fails_gate(self, capsys):
        """The acceptance seam: a wrong rule (vocab -> wrong mesh axis) on
        the EXPECTATION side makes a clean program fail, naming the entry,
        the parameter and the expected-vs-actual spec — and the CLI gate
        exits 1."""
        mesh = mesh3()
        params = _placed(get_policy("tp").param_specs(SHAPES, AXES), mesh)
        _register("t/clean", params, AXES, mesh=mesh)
        from tools.tpuaudit.registry import get_entry_points

        findings, _ = run_shard(get_entry_points(),
                                rule_overrides={"vocab": "data"},
                                publish_metrics=False)
        viol = [f for f in findings if f.check == "rule-violation"]
        assert viol and viol[0].entry == "t/clean"
        assert "['emb']" in viol[0].message
        assert "PartitionSpec('data', None)" in viol[0].message
        assert "PartitionSpec('model', None)" in viol[0].message

        rc = tpushard_main(["--override-rule", "vocab=data"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "t/clean" in out and "rule-violation" in out

    def test_clean_gate_exits_zero(self, capsys):
        mesh = mesh3()
        params = _placed(get_policy("tp").param_specs(SHAPES, AXES), mesh)
        _register("t/clean", params, AXES, mesh=mesh)
        rc = tpushard_main([])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== sharding ==" in out and "t/clean" in out

    def test_replication_waste(self):
        mesh = mesh3()
        specs = get_policy("tp").param_specs(SHAPES, AXES)
        specs = {**specs, "w": P()}          # 64x256 is tiny; grow it
        shapes = {**SHAPES, "w": sds((1024, 1024))}  # 4 MiB replicated
        params = jax.tree.map(
            lambda x, s: sds(x.shape, x.dtype,
                             sharding=NamedSharding(mesh, s)),
            shapes, specs)
        _register("t/waste", params, AXES, mesh=mesh)
        from tools.tpuaudit.registry import get_entry_points

        findings, reports = run_shard(get_entry_points(),
                                      publish_metrics=False)
        waste = [f for f in findings if f.check == "replication-waste"]
        assert len(waste) == 1 and "['w']" in waste[0].message
        # expected P(None, 'model') over the 2-wide model axis halves it
        assert reports[0].replicated_bytes == 1024 * 1024 * 4 // 2

    def test_implicit_reshard_attribution(self):
        mesh = mesh3()
        specs = get_policy("tp").param_specs(SHAPES, AXES)
        specs = {**specs, "w": P("data", None)}   # violates tp AND forces
        params = _placed(specs, mesh)             # an undeclared all-reduce
        _register("t/reshard", params, AXES, mesh=mesh,
                  fn=lambda p: sum(jnp.sum(a) for a in jax.tree.leaves(p)))
        from tools.tpuaudit.registry import get_entry_points

        findings, reports = run_shard(get_entry_points(),
                                      publish_metrics=False)
        checks = {f.check for f in findings}
        assert "rule-violation" in checks
        assert "implicit-reshard" in checks
        assert reports[0].reshard_collectives > 0

    def test_cross_program_mismatch(self):
        mesh = mesh3()
        good = _placed(get_policy("tp").param_specs(SHAPES, AXES), mesh)
        bad_specs = {**get_policy("tp").param_specs(SHAPES, AXES),
                     "emb": P(None, "model")}
        bad = _placed(bad_specs, mesh)
        _register("t/a", good, AXES, group="pair", mesh=mesh)
        _register("t/b", bad, AXES, group="pair", mesh=mesh)
        from tools.tpuaudit.registry import get_entry_points

        findings, _ = run_shard(get_entry_points(), publish_metrics=False)
        cross = [f for f in findings if f.check == "cross-program-mismatch"]
        assert len(cross) == 1
        assert cross[0].entry == "t/b" and "t/a" in cross[0].message
        assert "['emb']" in cross[0].message

    def test_handoff_geometry_mismatch(self):
        mesh = mesh3()
        export_out = NamedSharding(mesh, P("data", None))
        import_in = NamedSharding(mesh, P(None, "model"))
        register_entry_point(
            "t/kv_export",
            fn=jax.jit(lambda x: (x * 1.0,), out_shardings=(export_out,)),
            args=(sds((8, 64)),), expected_collectives=None, mesh=mesh,
            tags={"handoff": {"role": "export"}})
        register_entry_point(
            "t/kv_import", fn=jax.jit(lambda buf: buf.sum()),
            args=(sds((8, 64), sharding=import_in),),
            expected_collectives=None, mesh=mesh,
            tags={"handoff": {"role": "import", "buffer_args": (0,)}})
        from tools.tpuaudit.registry import get_entry_points

        findings, _ = run_shard(get_entry_points(), publish_metrics=False)
        cross = [f for f in findings if f.check == "cross-program-mismatch"]
        assert len(cross) == 1 and cross[0].entry == "t/kv_export"
        assert "t/kv_import" in cross[0].message

    def test_handoff_clean(self):
        mesh = mesh3()
        shared = NamedSharding(mesh, P("data", None))
        register_entry_point(
            "t/kv_export",
            fn=jax.jit(lambda x: (x * 1.0,), out_shardings=(shared,)),
            args=(sds((8, 64)),), expected_collectives=None, mesh=mesh,
            tags={"handoff": {"role": "export"}})
        register_entry_point(
            "t/kv_import", fn=jax.jit(lambda buf: buf.sum()),
            args=(sds((8, 64), sharding=shared),),
            expected_collectives=None, mesh=mesh,
            tags={"handoff": {"role": "import", "buffer_args": (0,)}})
        from tools.tpuaudit.registry import get_entry_points

        findings, _ = run_shard(get_entry_points(), publish_metrics=False)
        assert findings == []

    def test_untagged_entries_skipped(self):
        register_entry_point("t/plain", fn=jax.jit(lambda x: x + 1),
                             args=(sds((4,)),), expected_collectives=None)
        from tools.tpuaudit.registry import get_entry_points

        findings, reports = run_shard(get_entry_points(),
                                      publish_metrics=False)
        assert findings == [] and reports == []


# ---------------------------------------------------------------------------
# the report section


class TestReportSection:
    def test_summarize_sharding(self):
        from deepspeed_tpu.observability.report import summarize_sharding

        records = [
            {"type": "gauge", "name": "tpushard/train/step/params_total",
             "value": 6},
            {"type": "gauge", "name": "tpushard/train/step/params_checked",
             "value": 6},
            {"type": "gauge", "name": "tpushard/train/step/rule_violations",
             "value": 1},
            {"type": "counter", "name": "tpushard/findings", "value": 1,
             "labels": {"entry": "train/step", "check": "rule-violation"}},
        ]
        out = summarize_sharding(records)
        assert "== sharding ==" in out and "train/step" in out
        assert "6/6" in out
        assert "1 layout finding" in out

    def test_empty_without_records(self):
        from deepspeed_tpu.observability.report import summarize_sharding

        assert summarize_sharding([{"type": "gauge", "name": "x/y",
                                    "value": 1}]) == ""


# ---------------------------------------------------------------------------
# repo-wide gate (tier-1 acceptance)


class TestRepoGate:
    def test_selftest_engines_clean_under_committed_baseline(self, tmp_path):
        """Acceptance gate: every selftest entry carrying a layout contract
        (train, pipeline, inference, serving incl. draft + kv handoff, the
        RLHF flip) audits clean against the rule registry and the committed
        baseline; the dumped metrics render as == sharding ==."""
        jsonl = tmp_path / "shard_metrics.jsonl"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpushard",
             "--config", "tools/tpuaudit/selftest_config.json",
             "--baseline", ".tpushard-baseline.json",
             "--metrics-jsonl", str(jsonl)],
            cwd=REPO, capture_output=True, text=True, timeout=540,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, \
            f"tpushard gate failed:\n{proc.stdout}\n{proc.stderr}"
        assert "== sharding ==" in proc.stdout
        for name in ("train/step", "train/eval", "pipeline/step",
                     "inference/prefill", "inference/decode",
                     "serving/prefill_chunk", "serving/decode",
                     "serving/verify", "serving/draft_decode",
                     "serving/kv_export", "serving/kv_import", "rlhf/flip"):
            assert name in proc.stdout, name

        rep = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.observability", "report",
             str(jsonl)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert rep.returncode == 0, rep.stderr
        assert "== sharding ==" in rep.stdout
        assert "train/step" in rep.stdout

    def test_injected_bad_rule_fails_repo_gate(self):
        """A wrong rule against the real selftest engines exits 1 and names
        the entry, the parameter and the expected-vs-actual spec."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpushard",
             "--config", "tools/tpuaudit/selftest_config.json",
             "--baseline", ".tpushard-baseline.json",
             "--entries", "train/step", "--override-rule", "mlp=data"],
            cwd=REPO, capture_output=True, text=True, timeout=540,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 1, \
            f"expected gate failure:\n{proc.stdout}\n{proc.stderr}"
        assert "train/step" in proc.stdout
        assert "rule-violation" in proc.stdout
        assert "expected" in proc.stdout and "actual" in proc.stdout
        assert "PartitionSpec" in proc.stdout
