"""RLHF subsystem tests — hybrid engine v2 + rollouts through the serving
stack (the ISSUE-13 acceptance bar).

Coverage map:
  * the tier-1 smoke: a 2-iteration GRPO run on a tiny model where
    (a) the weight flip triggers ZERO serving-program recompiles and ZERO
    arena reallocation (recompile-watchdog counter + block-pool identity),
    (b) a candidate group of n=4 costs ONE prefill (prefill-chunk dispatch
    count) with siblings bit-identical to solo submits, and
    (c) ``replay(manifest)`` reproduces every rollout stream bit-exactly
    with speculation toggled OPPOSITE to the recording run;
  * deterministic replay under forced preemption (pool too small) and
    after a NaN→rollback recovery mid-iteration (slow-marked; the
    ``scripts/rlhf.sh`` gate runs them every CI pass);
  * the scoring pass (``serving/score_chunk``) bit-matches a dense
    forward oracle;
  * seed derivation, advantage math, manifest JSON roundtrip, the flip's
    prefix-cache invalidation rule, and the ``== rlhf ==`` report section.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import ObservabilityConfig, RLHFConfig
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, reset_session)
from deepspeed_tpu.rlhf import (ReplayMismatch, RLHFTrainer,
                                RolloutCollector, RolloutManifest,
                                group_advantages, replay, rollout_seed,
                                whitened_advantages)

SERVING = dict(block_size=8, max_seqs=8, max_model_len=48,
               prefill_chunk=8, max_queue=64,
               speculative={"mode": "ngram", "num_draft_tokens": 3})


def build_engine(serving=None, seed=1234, **cfg_extra):
    return deepspeed_tpu.init_rlhf(
        "tiny",
        config={"train_micro_batch_size_per_gpu": 8,
                "steps_per_print": 10 ** 9, "seed": seed,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "rlhf": {"algo": "grpo", "group_n": 4, "temperature": 0.7,
                         "max_new_tokens": 8},
                **cfg_extra},
        serving_config=dict(serving if serving is not None else SERVING))


def mk_prompts(n=2, length=16, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 250, (length,)).astype(np.int32)
            for _ in range(n)]


def reward_fn(_prompt, tokens):
    return float(len(set(tokens)))


@pytest.fixture
def obs_session(tmp_path):
    reset_session()
    sess = configure_observability(ObservabilityConfig(
        enabled=True, output_dir=str(tmp_path / "obs"),
        flight_recorder=False))
    yield sess
    reset_session()


# ---------------------------------------------------------------------------
# host-side units (milliseconds)
# ---------------------------------------------------------------------------


class TestSeedDerivation:
    def test_group_seeds_are_consecutive(self):
        # submit(n=...) gives sibling i seed base+i — the derivation must
        # agree, or forked groups and solo submits would diverge
        base = rollout_seed(3, 7)
        for i in range(8):
            assert rollout_seed(3, 7, i) == base + i

    def test_unique_across_prompts_and_iterations(self):
        seen = set()
        for it in range(4):
            for p in range(16):
                for s in range(4):
                    seen.add(rollout_seed(it, p, s))
        assert len(seen) == 4 * 16 * 4

    def test_sample_index_bound(self):
        with pytest.raises(ValueError):
            rollout_seed(0, 0, 4096)


class TestAdvantages:
    def test_grpo_group_normalized(self):
        adv = group_advantages([[1.0, 2.0, 3.0, 6.0], [5.0, 5.0]])
        a = np.asarray(adv[0])
        assert abs(a.mean()) < 1e-9
        assert a.std() == pytest.approx(1.0, rel=1e-4)
        assert adv[1] == [0.0, 0.0]          # zero-variance group → zeros

    def test_grpo_ranks_preserved(self):
        adv = group_advantages([[0.0, 10.0, 5.0]])[0]
        assert adv[1] > adv[2] > adv[0]

    def test_ppo_whitened_across_batch(self):
        adv = whitened_advantages([[1.0, 2.0], [3.0, 6.0]])
        flat = np.asarray([x for g in adv for x in g])
        assert abs(flat.mean()) < 1e-9
        assert flat.std() == pytest.approx(1.0, rel=1e-4)

    def test_ppo_unwhitened_passthrough(self):
        adv = whitened_advantages([[1.0, 2.0]], whiten=False)
        assert adv == [[1.0, 2.0]]


class TestLoss:
    def test_kl_pad_positions_cannot_poison_loss(self):
        """Masked positions carry fake ref_logp; an absurd value there
        must neither change nor NaN the objective — exp(ref − logp) at a
        pad would otherwise overflow and inf × mask(0) = NaN (the same
        0×nonfinite class the paged read paths guard against)."""
        import jax

        from deepspeed_tpu.models import create_model
        from deepspeed_tpu.rlhf import rlhf_model

        model = rlhf_model(create_model("tiny", dtype=jnp.float32),
                           RLHFConfig(kl_coef=0.1))
        params = model.init(jax.random.PRNGKey(0))
        B, T = 2, 16
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 250, (B, T)).astype(np.int32)
        tgt = np.concatenate([ids[:, 1:], np.zeros((B, 1), np.int32)], 1)
        mask = np.zeros((B, T), np.float32)
        mask[:, 4:10] = 1.0
        base = {"input_ids": ids, "targets": tgt, "loss_mask": mask,
                "advantages": mask * 0.5,
                "old_logp": np.full((B, T), -5.0, np.float32)}
        a = float(model.loss_fn(
            params, {**base, "ref_logp": np.zeros((B, T), np.float32)}))
        ref_absurd = np.where(mask > 0, 0.0, 1000.0).astype(np.float32)
        b = float(model.loss_fn(params, {**base, "ref_logp": ref_absurd}))
        assert np.isfinite(a)
        assert a == b


class TestConfig:
    def test_validates(self):
        RLHFConfig().validate()
        with pytest.raises(ConfigError):
            RLHFConfig(algo="dpo").validate()
        with pytest.raises(ConfigError):
            RLHFConfig(algo="grpo", group_n=1).validate()
        with pytest.raises(ConfigError):
            RLHFConfig(clip_ratio=0.0).validate()
        RLHFConfig(algo="ppo", group_n=1).validate()

    def test_nested_in_root_config(self):
        cfg = deepspeed_tpu.load_config(
            {"train_micro_batch_size_per_gpu": 1,
             "rlhf": {"algo": "ppo", "group_n": 2, "kl_coef": 0.0}})
        assert cfg.rlhf.algo == "ppo" and cfg.rlhf.kl_coef == 0.0


class TestManifest:
    def _manifest(self):
        return RolloutManifest(
            iteration=2, group_n=2, engine_seed=0, temperature=0.7,
            top_k=0, top_p=1.0, max_new_tokens=4, eos_token_id=None,
            prompts=[[1, 2, 3]], seeds=[[10, 11]],
            streams=[[[4, 5, 6, 7], [8, 9, 1, 2]]], spec_mode="ngram")

    def test_json_roundtrip(self, tmp_path):
        m = self._manifest()
        path = str(tmp_path / "m.json")
        m.save(path)
        m2 = RolloutManifest.load(path)
        assert m2 == m

    def test_engine_seed_mismatch_raises(self):
        class FakeCfg:
            seed = 99

        class FakeEngine:
            config = FakeCfg()

        with pytest.raises(ReplayMismatch, match="engine seed"):
            replay(self._manifest(), FakeEngine())


# ---------------------------------------------------------------------------
# the tier-1 acceptance smoke
# ---------------------------------------------------------------------------


class TestSmoke:
    def test_two_iteration_grpo_smoke(self, obs_session, tmp_path):
        """The ISSUE-13 bar, one run: flip-no-recompile + no-realloc,
        group-of-4 = one prefill with fork==solo bit-identity, and
        manifest replay bit-exact with speculation toggled opposite."""
        engine = build_engine()
        trainer = RLHFTrainer(engine, lambda it: mk_prompts(2, 16, it),
                              reward_fn)
        serving = engine.serving_engine()
        alloc_id = id(serving.alloc)
        arena_shape = {k: v.shape for k, v in serving._arena.items()}

        losses = trainer.train(2)
        assert len(losses) == 2 and all(np.isfinite(losses))
        assert len(trainer.manifests) == 2

        # (a) steady-state flip: zero serving recompiles, zero realloc.
        # Train once more so the flip is real (stale params), then flip +
        # roll out again: every compile counter must hold still.
        batch = trainer.data_fn(engine.global_steps)
        engine.train_batch(batch=batch)
        compiles = get_registry().counter("xla/compiles")
        before = {w: compiles.value(where=w)
                  for w in ("serving/prefill_chunk", "serving/decode",
                            "serving/verify", "serving/score_chunk",
                            "rlhf/flip")}
        extra = trainer.data_fn(engine.global_steps + 1)  # flip + rollout
        for where, val in before.items():
            assert compiles.value(where=where) == val, where
        assert id(serving.alloc) == alloc_id
        assert {k: v.shape for k, v in serving._arena.items()} \
            == arena_shape
        assert serving.alloc.capacity == \
            serving.config.pool_blocks()   # pool never re-provisioned
        assert extra["input_ids"].shape == batch["input_ids"].shape

        # (b) one prefill per candidate group + fork == solo bit-identity
        prompt = mk_prompts(1, 16, 99)[0]
        pre = serving.prefill_chunks_run
        hs = serving.submit(prompt, max_new_tokens=8, temperature=0.7,
                            seed=rollout_seed(50, 0), n=4)
        group_streams = [list(h.result()) for h in hs]
        chunks_for_group = serving.prefill_chunks_run - pre
        assert chunks_for_group == 2   # 16 tokens / 8-chunk — ONCE, not ×4
        solo_streams = []
        for i in range(4):
            h = serving.submit(prompt, max_new_tokens=8, temperature=0.7,
                               seed=rollout_seed(50, 0, i))
            solo_streams.append(list(h.result()))
        assert group_streams == solo_streams

        # (c) replay with speculation toggled OPPOSITE (recorded with the
        # ngram drafter → replay plain-decode) — bit-exact. The weights
        # moved since iteration 0/1, so replay the LAST manifest, whose
        # weights are still current.
        step, manifest = trainer.manifests[-1]
        assert manifest.spec_mode == "ngram"
        serving.spec_suspended = True
        try:
            streams = replay(manifest, serving, verify=True)
        finally:
            serving.spec_suspended = False
        assert streams == manifest.streams
        assert get_registry().counter(
            "rlhf/replay_verifications").value() >= 1

    def test_report_section(self, obs_session, tmp_path):
        engine = build_engine()
        trainer = RLHFTrainer(engine, lambda it: mk_prompts(2, 16, it),
                              reward_fn)
        trainer.train(1)
        mpath = obs_session.dump_metrics(str(tmp_path / "metrics.jsonl"))
        from deepspeed_tpu.observability.report import report

        text = report([mpath])
        assert "== rlhf ==" in text
        # the registry is a process singleton — counts are cumulative
        # across the test session, so assert presence, not magnitude
        assert "iterations:" in text
        assert "rollout" in text and "flip" in text
        assert "weight flips" in text


# ---------------------------------------------------------------------------
# flip semantics
# ---------------------------------------------------------------------------


class TestFlip:
    def test_flip_invalidates_prefix_cache(self, obs_session):
        engine = build_engine()
        serving = engine.flip_to_serving()
        prompt = mk_prompts(1, 16, 3)[0]
        serving.submit(prompt, max_new_tokens=2).result()
        assert serving.prefix.cached_blocks > 0
        pre_free = serving.alloc.blocks_free
        engine.train_batch(batch=engine_batch(engine))
        engine.refresh_params()
        # stale content hashes dropped, pinned blocks back in the pool
        assert serving.prefix.cached_blocks == 0
        assert serving.alloc.blocks_free > pre_free
        assert serving.alloc.blocks_in_use == 0

    def test_flip_with_inflight_requests_raises(self, obs_session):
        engine = build_engine()
        serving = engine.flip_to_serving()
        h = serving.submit(mk_prompts(1, 16, 4)[0], max_new_tokens=4)
        serving.step()   # admitted, mid-prefill
        engine.train_batch(batch=engine_batch(engine))
        with pytest.raises(RuntimeError, match="in flight"):
            engine.refresh_params()
        h.result()       # drain; now the flip goes through
        engine.refresh_params()

    def test_rollouts_immune_to_nonfinite_arena_residue(self):
        """Serving output must be a pure function of (weights, seeds,
        requests) — NEVER of leftover arena bytes. KV written under
        briefly-poisoned params (the NaN→rollback scenario) leaves
        nonfinite residue in recycled/scratch blocks; a 0 × NaN leak in
        any read path would let it corrupt later, healthy requests (found
        by the rollback replay test: masked softmax columns multiplied
        NaN v values, and pad queries widened the residency window)."""
        import jax.numpy as jnp

        from deepspeed_tpu.rlhf import RolloutCollector

        engine = build_engine()
        serving = engine.flip_to_serving()
        collector = RolloutCollector(serving, group_n=2, temperature=0.7,
                                     max_new_tokens=8)
        prompts = mk_prompts(2, 16, 7)
        _, before = collector.collect(prompts, 0)
        # worst-case residue: every arena byte nonfinite
        serving._arena = {k: jnp.full_like(v, jnp.nan)
                          for k, v in serving._arena.items()}
        serving.note_weights_updated()
        _, after = collector.collect(prompts, 0)   # verify path
        assert after.streams == before.streams
        serving.spec_suspended = True              # plain decode path
        _, plain = collector.collect(prompts, 0)
        serving.spec_suspended = False
        assert plain.streams == before.streams
        seq = np.concatenate([prompts[0], np.asarray(before.streams[0][0])])
        assert np.isfinite(serving.score_logprobs(seq)).all()

    def test_initial_inference_params_survive_donating_train_step(self):
        """CPU device_put of live train params may alias their buffers
        zero-copy; the donating train step then mutates the inference tree
        in place (the PR-9 resume-corruption class at the hybrid seam) —
        the engine must hand the inference side OWNED buffers."""
        import jax

        engine = build_engine()
        infer = engine._inference_engine()
        before = jax.tree.map(lambda x: np.asarray(x).copy(), infer.params)
        engine.train_batch(batch=engine_batch(engine))   # donates buffers
        for b, a in zip(jax.tree.leaves(before),
                        jax.tree.leaves(infer.params)):
            np.testing.assert_array_equal(b, np.asarray(a))

    def test_flip_to_train_requires_drained_engine(self, obs_session):
        engine = build_engine()
        serving = engine.flip_to_serving()
        h = serving.submit(mk_prompts(1, 16, 5)[0], max_new_tokens=4)
        with pytest.raises(RuntimeError, match="in flight"):
            engine.flip_to_train()
        h.result()
        engine.flip_to_train()


def engine_batch(engine, seed=0):
    import jax

    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    T = 48
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, 250, (gas, gb, T)).astype(np.int32)
    mask = np.ones((gas, gb, T), np.float32)
    tgt = np.concatenate([ids[:, :, 1:], np.zeros((gas, gb, 1), np.int32)],
                         axis=2)
    return {"input_ids": ids, "targets": tgt, "loss_mask": mask,
            "advantages": rng.randn(gas, gb, T).astype(np.float32) * 0.1,
            "old_logp": np.full((gas, gb, T), -5.0, np.float32),
            "ref_logp": np.full((gas, gb, T), -5.0, np.float32)}


# ---------------------------------------------------------------------------
# scoring parity
# ---------------------------------------------------------------------------


class TestScoring:
    def test_score_logprobs_matches_dense_oracle(self):
        import jax

        engine = build_engine()
        serving = engine.flip_to_serving()
        infer = engine._inference_engine()
        toks = np.asarray(mk_prompts(1, 33, 8)[0])
        lp = serving.score_logprobs(toks)
        logits, _ = infer.model.apply(
            infer.params, {"input_ids": jnp.asarray(toks[None], jnp.int32)})
        ref = np.asarray(
            jax.nn.log_softmax(np.asarray(logits, np.float32), axis=-1))[0]
        want = np.array([ref[p, toks[p + 1]] for p in range(toks.size - 1)])
        np.testing.assert_allclose(lp, want, atol=2e-4)
        assert serving.alloc.blocks_in_use == 0   # scratch blocks freed

    def test_reference_params_share_the_program(self, obs_session):
        """Scoring with a different params tree (the frozen reference)
        must reuse the one compiled score program — params are an
        argument, not a capture."""
        engine = build_engine()
        serving = engine.flip_to_serving()
        ref_params = engine._inference_engine().params   # hold pre-update
        toks = np.asarray(mk_prompts(1, 17, 9)[0])
        serving.score_logprobs(toks)                     # compiles
        engine.train_batch(batch=engine_batch(engine))
        engine.refresh_params()
        compiles = get_registry().counter("xla/compiles")
        before = compiles.value(where="serving/score_chunk")
        a = serving.score_logprobs(toks)                    # new policy
        b = serving.score_logprobs(toks, params=ref_params)  # frozen ref
        assert compiles.value(where="serving/score_chunk") == before
        assert not np.allclose(a, b)   # the reference really is frozen


# ---------------------------------------------------------------------------
# deterministic replay, the hard cases (scripts/rlhf.sh runs these every
# CI pass; slow-marked to protect the tier-1 wall budget)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestReplayUnderPressure:
    def test_replay_bit_exact_under_forced_preemption(self):
        """A pool far too small for the load forces preemption/recompute
        mid-rollout; the recorded streams must STILL replay bit-exactly —
        on a comfortable pool AND on the starved one."""
        starved = dict(SERVING, num_blocks=8, max_seqs=4)  # 8 blocks vs
        #   4 rows × 3 blocks each (24-token sequences) — guaranteed
        #   eviction churn
        engine = build_engine(serving=starved)
        serving = engine.flip_to_serving()
        collector = RolloutCollector(serving, group_n=2, temperature=0.7,
                                     max_new_tokens=8)
        prompts = mk_prompts(3, 16, 11)
        batch, manifest = collector.collect(prompts, 0)
        assert serving.sched.preemption_count > 0   # pressure was real
        # replay on the SAME starved engine, speculation toggled off
        serving.spec_suspended = True
        streams = replay(manifest, serving, verify=True)
        assert streams == manifest.streams
        serving.spec_suspended = False
        # and on a fresh, comfortable engine with the same weights+seed:
        # preemption scheduling must leave zero fingerprint on tokens
        roomy = build_engine()   # same config seed → same init weights
        s2 = roomy.flip_to_serving()
        streams2 = replay(manifest, s2, verify=True)
        assert streams2 == manifest.streams

    def test_spec_recorded_replayed_plain_and_back(self):
        """Record WITHOUT speculation, replay WITH the drafter — the
        opposite toggle direction from the smoke."""
        engine = build_engine()
        serving = engine.flip_to_serving()
        serving.spec_suspended = True
        collector = RolloutCollector(serving, group_n=2, temperature=0.7,
                                     max_new_tokens=8)
        batch, manifest = collector.collect(mk_prompts(2, 16, 12), 0)
        assert manifest.spec_mode == "off"
        serving.spec_suspended = False
        streams = replay(manifest, serving, verify=True)
        assert streams == manifest.streams


@pytest.mark.slow
class TestRollbackReplay:
    def test_nan_rollback_replays_iteration_rollouts(self, tmp_path):
        """The resilience bar: a nan_params fault poisons iteration 1; the
        numerics sentinel trips, the TrainingSession rolls back to the
        last verified checkpoint, and data_fn(1) re-runs — rollouts,
        scoring and the step replay deterministically. The recovered
        iteration's manifest must then replay BIT-EXACTLY from a fresh
        engine restored from the same checkpoint the rollback used, with
        speculation toggled opposite — the manifest outlives the
        process."""
        from deepspeed_tpu.observability.faultinject import (Fault,
                                                             FaultInjector)

        reset_session()
        try:
            engine = build_engine(
                observability={"enabled": True,
                               "output_dir": str(tmp_path / "obs"),
                               "flight_recorder": False,
                               "numerics_sentinel": True,
                               "numerics_action": "abort",
                               "numerics_check_steps": 1},
                resilience={"checkpoint_every_steps": 1,
                            "on_numerics": "rollback", "max_rollbacks": 2})
            trainer = RLHFTrainer(engine,
                                  lambda it: mk_prompts(2, 16, 1000 + it),
                                  reward_fn)
            inj = FaultInjector(
                plan=[Fault(kind="nan_params", step=1, rank=0)],
                rank=0, restart=0)
            out = trainer.run(2, save_dir=str(tmp_path / "ck"),
                              injector=inj)
            assert out["completed"] and out["rollbacks"] == 1
            assert out["recoveries"][0]["kind"] == "numerics"
            assert all(np.isfinite(trainer.losses))
            # iteration 1 collected twice: poisoned attempt + clean replay
            steps = [s for s, _ in trainer.manifests]
            assert steps == [0, 1, 1]
            clean = trainer.manifests[-1][1]
            poisoned = trainer.manifests[-2][1]
            # the rollback really re-generated (poisoned streams differ)
            assert clean.streams != poisoned.streams
        finally:
            reset_session()
        # the replay contract across process/engine boundaries: a FRESH
        # engine restored from the rollback's checkpoint (the weights the
        # recovered iteration rolled out from) reproduces its streams
        # bit-exactly, speculation toggled OPPOSITE to the recording run
        engine2 = build_engine()
        engine2.load_checkpoint(str(tmp_path / "ck"), tag="global_step1",
                                verify=True)
        serving2 = engine2.flip_to_serving()
        assert clean.spec_mode == "ngram"
        serving2.spec_suspended = True
        streams = replay(clean, serving2, verify=True)
        assert streams == clean.streams


@pytest.mark.slow
class TestTrainerAlgos:
    def test_ppo_arm_trains(self):
        engine = build_engine(
            rlhf={"algo": "ppo", "group_n": 2, "temperature": 0.7,
                  "max_new_tokens": 8, "kl_coef": 0.0})
        trainer = RLHFTrainer(engine, lambda it: mk_prompts(4, 16, it),
                              reward_fn)
        losses = trainer.train(2)
        assert all(np.isfinite(losses))
        # kl_coef=0 skips the reference pass entirely
        assert trainer._ref_params is None

    def test_gas_divisibility_guard(self):
        engine = build_engine(gradient_accumulation_steps=3,
                              train_micro_batch_size_per_gpu=0,
                              train_batch_size=24)
        trainer = RLHFTrainer(engine, lambda it: mk_prompts(2, 16, it),
                              reward_fn)   # 2 prompts × 4 = 8 samples, gas=3
        with pytest.raises(ValueError, match="divide"):
            trainer.data_fn(0)
