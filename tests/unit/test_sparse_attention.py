"""Block-sparse attention tests — layout properties per config family
(reference tests/unit/ops/sparse_attention concerns) + masked-attention
equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import dot_product_attention
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                LocalSlidingWindowSparsityConfig,
                                                layout_to_token_mask,
                                                sparse_self_attention)


def test_dense_layout_full():
    layout = DenseSparsityConfig(num_heads=4, block=16).make_layout(64)
    assert layout.shape == (4, 4, 4)
    assert (layout == 1).all()


def test_fixed_unidirectional_causal_and_global():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1, attention="unidirectional")
    layout = cfg.make_layout(128)  # 8 blocks
    # strictly-causal: nothing above the diagonal
    assert (np.triu(layout[0], 1) == 0).all()
    # diagonal always attends itself
    assert (np.diag(layout[0]) == 1).all()
    # global stripe: block 1 (last of first window) visible to later rows
    assert (layout[0][2:, 1] == 1).all()


def test_sliding_window_band():
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=3)
    layout = cfg.make_layout(128)[0]
    for i in range(8):
        for j in range(8):
            expect = 1 if (i - 1 <= j <= i) else 0
            assert layout[i, j] == expect, (i, j)


def test_bigbird_has_window_random_global():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=2,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1, seed=0)
    layout = cfg.make_layout(256)[0]     # 16 blocks
    assert (np.diag(layout) == 1).all()
    assert (layout[:, 0] == 1).all() and (layout[0, :] == 1).all()
    density = layout.mean()
    assert 0.1 < density < 0.8           # sparse but not trivial


def test_longformer_global_indices():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     global_block_indices=(0, 3))
    layout = cfg.make_layout(128)[0]
    assert (layout[:, 0] == 1).all() and (layout[3, :] == 1).all()


def test_block_divisibility_enforced():
    with pytest.raises(ValueError, match="divisible"):
        DenseSparsityConfig(num_heads=1, block=16).make_layout(100)


def test_sparse_attention_matches_masked_dense():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              attention="unidirectional")
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (2, 64, 2, 16))
    k = jax.random.normal(ks[1], (2, 64, 2, 16))
    v = jax.random.normal(ks[2], (2, 64, 2, 16))
    out = sparse_self_attention(q, k, v, cfg)
    tok = layout_to_token_mask(cfg.make_layout(64), 16)
    tok = tok * jnp.tril(jnp.ones((64, 64), jnp.int32))[None]  # unidirectional
    for h in range(2):
        ref = dot_product_attention(q[:, :, h:h + 1], k[:, :, h:h + 1],
                                    v[:, :, h:h + 1],
                                    jnp.broadcast_to(tok[h][None], (2, 64, 64)),
                                    causal=False)
        np.testing.assert_allclose(np.asarray(out[:, :, h:h + 1]),
                                   np.asarray(ref), atol=1e-6)


class TestBlockSkipKernel:
    """The Pallas block-skip path must match the dense-mask oracle — forward
    AND gradients (custom VJP with sparse dq/dkv kernels)."""

    def _qkv(self, S=256, N=2, D=64, B=2, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (B, S, N, D)
        return (jax.random.normal(ks[0], shape, jnp.float32),
                jax.random.normal(ks[1], shape, jnp.float32),
                jax.random.normal(ks[2], shape, jnp.float32))

    @pytest.mark.parametrize("cfg", [
        FixedSparsityConfig(num_heads=2, block=32, num_local_blocks=2,
                            attention="unidirectional"),
        FixedSparsityConfig(num_heads=2, block=32, num_local_blocks=2,
                            num_global_blocks=1, attention="bidirectional"),
        BigBirdSparsityConfig(num_heads=2, block=32, num_random_blocks=1,
                              num_sliding_window_blocks=3,
                              num_global_blocks=1),
        LocalSlidingWindowSparsityConfig(num_heads=2, block=64,
                                         num_sliding_window_blocks=3),
    ])
    def test_forward_matches_dense_oracle(self, cfg):
        q, k, v = self._qkv()
        out = sparse_self_attention(q, k, v, cfg, use_kernel=True,
                                    interpret=True)
        ref = sparse_self_attention(q, k, v, cfg, use_kernel=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_gradients_match_dense_oracle(self):
        cfg = FixedSparsityConfig(num_heads=2, block=32, num_local_blocks=2,
                                  num_global_blocks=1,
                                  attention="unidirectional")
        q, k, v = self._qkv(S=256)

        def loss(fn):
            def f(q, k, v):
                return jnp.sum(fn(q, k, v) ** 2)
            return jax.grad(f, argnums=(0, 1, 2))

        sparse_fn = lambda q, k, v: sparse_self_attention(
            q, k, v, cfg, use_kernel=True, interpret=True)
        dense_fn = lambda q, k, v: sparse_self_attention(
            q, k, v, cfg, use_kernel=False)
        gs = loss(sparse_fn)(q, k, v)
        gd = loss(dense_fn)(q, k, v)
        for a, b, name in zip(gs, gd, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_plan_density_and_skip(self):
        from deepspeed_tpu.ops.sparse_attention import tile_plan_for

        cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=64,
                                               num_sliding_window_blocks=3)
        plan = tile_plan_for(cfg, 1024)
        # banded layout: most tiles are skipped
        assert plan.density < 0.5
        assert plan.kidx.shape[2] < 1024 // 128  # A << all tiles
        # plan is cached per (config, S)
        assert tile_plan_for(cfg, 1024) is plan

    @pytest.mark.slow
    def test_empty_layout_row_outputs_zero(self):
        # A q-tile with NO active k-tiles must produce output 0 and zero
        # gradients. The padded slot list still visits the all-zero mask id,
        # and NEG_INF is finite — without the m_new guard the kernel would
        # average visited V tiles instead (advisor finding r2).
        from deepspeed_tpu.ops.block_sparse_attention import (
            block_sparse_attention, build_tile_plan)

        layout = np.zeros((1, 2, 2), np.int64)
        layout[0, 0, 0] = 1          # q-tile 0 → k-tile 0; q-tile 1 → nothing
        plan = build_tile_plan(layout, 128, 256)
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (1, 256, 1, 32), jnp.float32)
        k = jax.random.normal(ks[1], (1, 256, 1, 32), jnp.float32)
        v = jax.random.normal(ks[2], (1, 256, 1, 32), jnp.float32)

        def f(q, k, v):
            return block_sparse_attention(q, k, v, plan, interpret=True)

        out = f(q, k, v)
        # key-less rows: exactly zero
        np.testing.assert_array_equal(np.asarray(out[:, 128:]), 0.0)
        # active rows: match dense attention over the visible 128 keys
        ref = dot_product_attention(q[:, :128], k[:, :128], v[:, :128],
                                    None, causal=False)
        np.testing.assert_allclose(np.asarray(out[:, :128]), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

        dq, dk, dv = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v) ** 2),
                              argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_array_equal(np.asarray(dq[:, 128:]), 0.0)
        ref_g = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
            q, k, v, None, causal=False) ** 2), argnums=(0, 1, 2))(
            q[:, :128], k[:, :128], v[:, :128])
        for got, want, name in zip((dq, dk, dv), ref_g, "qkv"):
            np.testing.assert_allclose(np.asarray(got[:, :128]),
                                       np.asarray(want), atol=5e-5, rtol=5e-5,
                                       err_msg=f"d{name} mismatch")
            np.testing.assert_allclose(np.asarray(got[:, 128:]), 0.0,
                                       atol=5e-6,
                                       err_msg=f"d{name} tail not zero")

    def test_padding_mask_kernel_rejected(self):
        cfg = FixedSparsityConfig(num_heads=2, block=32, num_local_blocks=2)
        q, k, v = self._qkv()
        with pytest.raises(NotImplementedError, match="key_padding_mask"):
            sparse_self_attention(q, k, v, cfg,
                                  key_padding_mask=jnp.ones((2, 256)),
                                  use_kernel=True, interpret=True)


def test_dense_config_equals_causal_attention():
    # dense unidirectional layout == plain causal attention
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              num_global_blocks=1,
                              attention="unidirectional")
    # with window >= nblocks the local part covers everything
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (1, 64, 1, 16))
    k = jax.random.normal(ks[1], (1, 64, 1, 16))
    v = jax.random.normal(ks[2], (1, 64, 1, 16))
    out = sparse_self_attention(q, k, v, cfg)
    ref = dot_product_attention(q, k, v, None, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


class TestVariableSparsityConfig:
    """Reference sparsity_config.py:239 semantics: variable local windows,
    optional random blocks, global indices or ranges."""

    def test_variable_windows_and_tail(self):
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        cfg = VariableSparsityConfig(num_heads=1, block=16,
                                     local_window_blocks=(2, 3),
                                     global_block_indices=())
        layout = cfg.make_layout(16 * 10)[0]     # 10 blocks
        # windows: [0,2), [2,5), then the LAST size (3) repeats: [5,8), [8,10)
        for (s, e) in ((0, 2), (2, 5), (5, 8), (8, 10)):
            assert (layout[s:e, s:e] == 1).all(), (s, e)
        assert layout[0, 2] == 0 and layout[4, 5] == 0 and layout[7, 8] == 0

    def test_global_ranges_and_horizontal(self):
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        cfg = VariableSparsityConfig(num_heads=1, block=16,
                                     local_window_blocks=(2,),
                                     global_block_indices=(1, 4),
                                     global_block_end_indices=(2, 6),
                                     horizontal_global_attention=True)
        layout = cfg.make_layout(16 * 8)[0]
        assert (layout[:, 1] == 1).all() and (layout[:, 4:6] == 1).all()
        assert (layout[1, :] == 1).all() and (layout[4:6, :] == 1).all()

    def test_unidirectional_causal(self):
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        cfg = VariableSparsityConfig(num_heads=1, block=16,
                                     local_window_blocks=(3,),
                                     global_block_indices=(0,),
                                     attention="unidirectional",
                                     num_random_blocks=1)
        layout = cfg.make_layout(16 * 8)[0]
        assert (np.triu(layout, 1) == 0).all()
        assert (np.diag(layout) == 1).all()
        assert (layout[:, 0] == 1).all()         # global col, causal-masked

    def test_unidirectional_matches_reference_oracle_modulo_tril(self):
        """Pin the documented deviation from the reference's
        set_random_layout (sparsity_config.py:303): our unidirectional
        layout equals a reference-structured oracle (random -> local ->
        global, random blocks NOT causal-restricted) with np.tril applied —
        i.e. the ONLY difference is that above-diagonal random blocks are
        dropped, which the kernel could never attend causally anyway."""
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        cfg = VariableSparsityConfig(num_heads=1, block=16,
                                     local_window_blocks=(2, 3),
                                     global_block_indices=(1,),
                                     attention="unidirectional",
                                     num_random_blocks=2, seed=7)
        n = 10
        layout = cfg.make_layout(16 * n)[0]

        # reference-derived oracle: same rng stream as our implementation,
        # reference structure (random rows unrestricted by causality)
        oracle = np.zeros((n, n), dtype=np.int64)
        rng = np.random.RandomState(cfg.seed)
        for i in range(n):                                   # set_random_layout
            oracle[i, rng.choice(n, size=2, replace=False)] = 1
        start, sizes = 0, [2, 3]                             # set_local_layout
        while start < n:
            size = sizes.pop(0) if sizes else 3
            end = min(start + size, n)
            for i in range(start, end):
                oracle[i, start:i + 1] = 1                   # unidirectional
            start = end
        oracle[1:, 1] = 1                                    # set_global_layout

        assert (layout == np.tril(oracle)).all()
        # non-vacuous: the oracle really had above-diagonal random blocks
        assert (np.triu(oracle, 1) == 1).any()

    def test_validation(self):
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        with pytest.raises(ValueError, match="pair 1:1"):
            VariableSparsityConfig(num_heads=1, global_block_indices=(0, 3),
                                   global_block_end_indices=(1,))
        with pytest.raises(ValueError, match="empty"):
            VariableSparsityConfig(num_heads=1, global_block_indices=(3,),
                                   global_block_end_indices=(3,))
        with pytest.raises(ValueError, match="bidirectional"):
            VariableSparsityConfig(num_heads=1, attention="unidirectional",
                                   horizontal_global_attention=True)

    def test_kernel_path_matches_dense_oracle(self):
        from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

        cfg = VariableSparsityConfig(num_heads=2, block=32,
                                     local_window_blocks=(2, 4),
                                     global_block_indices=(0,),
                                     num_random_blocks=1, seed=3)
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q = jax.random.normal(ks[0], (1, 256, 2, 32))
        k = jax.random.normal(ks[1], (1, 256, 2, 32))
        v = jax.random.normal(ks[2], (1, 256, 2, 32))
        out = sparse_self_attention(q, k, v, cfg, use_kernel=True,
                                    interpret=True)
        ref = sparse_self_attention(q, k, v, cfg, use_kernel=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestSparseAttentionImpl:
    """make_sparse_attention_impl — the replace_model_self_attention
    analog: a model trains with block-sparse attention via the
    attention_impl hook."""

    def test_dense_config_matches_plain_model(self):
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      forward, init_params)
        from deepspeed_tpu.ops.sparse_attention import (
            DenseSparsityConfig, make_sparse_attention_impl)

        base = TransformerConfig(vocab_size=128, hidden_size=64,
                                 num_layers=2, num_heads=4, max_seq_len=64)
        params = init_params(jax.random.PRNGKey(0), base)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)))
        want, _, _ = forward(params, ids, base)
        import dataclasses
        # a Fixed layout whose local window spans ALL blocks == full causal
        cfg = dataclasses.replace(base, attention_impl=make_sparse_attention_impl(
            FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                                attention="unidirectional")))
        got, _, _ = forward(params, ids, cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_fixed_config_trains_and_restricts(self):
        """A Fixed (GPT-3-style) layout trains (finite grads) and really
        restricts attention (output differs from dense)."""
        import dataclasses

        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      build_model)
        from deepspeed_tpu.ops.sparse_attention import (
            FixedSparsityConfig, make_sparse_attention_impl)

        base = TransformerConfig(vocab_size=128, hidden_size=64,
                                 num_layers=2, num_heads=4, max_seq_len=64)
        sparse_cfg = dataclasses.replace(
            base, attention_impl=make_sparse_attention_impl(
                FixedSparsityConfig(num_heads=4, block=16,
                                    num_local_blocks=2)))
        model = build_model(sparse_cfg)
        params = model.init(jax.random.PRNGKey(1))
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 64)))
        batch = {"input_ids": ids}
        loss, grads = jax.value_and_grad(model.loss_fn)(params, batch)
        assert np.isfinite(float(loss))
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(grads))
        dense = build_model(base)
        want = dense.loss_fn(params, batch)
        assert abs(float(loss) - float(want)) > 1e-6   # really sparse

    def test_causality_mismatch_rejected(self):
        from deepspeed_tpu.ops.sparse_attention import (
            FixedSparsityConfig, make_sparse_attention_impl)

        impl = make_sparse_attention_impl(
            FixedSparsityConfig(num_heads=1, block=16,
                                attention="unidirectional"))
        q = jnp.zeros((1, 32, 1, 16))
        with pytest.raises(ValueError, match="causality"):
            impl(q, q, q, None, causal=False)
        with pytest.raises(NotImplementedError, match="kwargs"):
            impl(q, q, q, None, causal=True, window=jnp.int32(4))
