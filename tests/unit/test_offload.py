"""Offload config-surface tests (CPU side). The functional validation runs
on real TPU hardware via scripts/validate_offload_tpu.py — XLA CPU cannot
lower host-pinned jit operands, so trajectory/memory checks cannot run on
the virtual mesh."""

import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model


def _cfg(**offload):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0, **offload},
    }


def test_cpu_offload_rejected_on_cpu_backend():
    model = create_model("tiny", dtype=jnp.float32)
    with pytest.raises(ValueError, match="host memory kinds"):
        deepspeed_tpu.initialize(
            model=model,
            config=_cfg(offload_optimizer={"device": "cpu"}))


def test_nvme_offload_gates(tmp_path):
    # nvme offload is implemented (tests/unit/test_nvme_offload.py); the
    # remaining hard gates must still fail loudly
    model = create_model("tiny", dtype=jnp.float32)
    cfg = _cfg(offload_optimizer={"device": "nvme",
                                  "nvme_path": str(tmp_path)})
    cfg["fp16"] = {"enabled": True}
    with pytest.raises(NotImplementedError, match="fp16"):
        deepspeed_tpu.initialize(model=model, config=cfg)
    cfg2 = _cfg(offload_optimizer={"device": "nvme",
                                   "nvme_path": str(tmp_path)})
    cfg2["optimizer"] = {"type": "sgd", "params": {"lr": 1e-2}}
    with pytest.raises(ValueError, match="Adam family"):
        deepspeed_tpu.initialize(model=model, config=cfg2)


def test_param_offload_requires_stage3():
    # param offload is implemented (tests/unit/test_param_offload.py); the
    # stage gate must still fail loudly
    model = create_model("tiny", dtype=jnp.float32)
    with pytest.raises(ValueError, match="stage 3"):
        deepspeed_tpu.initialize(
            model=model, config=_cfg(offload_param={"device": "cpu"}))
