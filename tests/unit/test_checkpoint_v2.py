"""Sharded/async checkpoint tests (format 2) — the reference's
tests/unit/checkpoint suite concerns (zero shards per rank, reshape across
topologies, latest-tag semantics) plus async-commit ordering."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.checkpoint import (load_checkpoint,
                                              read_latest_tag,
                                              save_checkpoint, wait_pending)


@pytest.fixture
def mesh8(devices8):
    return Mesh(np.array(devices8), ("data",))


def _sharded(mesh, arr, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def test_sharded_leaves_write_per_shard_files(tmp_path, mesh8):
    params = {
        "w": _sharded(mesh8, jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                      P("data", None)),
        "b": _sharded(mesh8, jnp.ones((4,), jnp.float32), P()),
    }
    save_checkpoint(str(tmp_path), "t1", params)
    files = sorted(os.path.basename(f) for f in
                   glob.glob(str(tmp_path / "t1" / "arrays" / "*.npy")))
    w_files = [f for f in files if "w" in f and "b" not in f]
    assert len(w_files) == 8, files           # one file per unique shard
    # each shard file holds 1/8 of the array, in global coords per metadata
    meta = json.load(open(tmp_path / "t1" / "metadata.json"))
    info = meta["arrays"]["params##w"]
    assert len(info["shards"]) == 8
    assert info["shards"][0]["bounds"] == [[0, 1], [0, 8]]
    # replicated leaf collapses to ONE file
    b_files = [f for f in files if "##b" in f]
    assert len(b_files) == 1


def test_roundtrip_resharded(tmp_path, mesh8):
    src = jnp.arange(128, dtype=jnp.float32).reshape(16, 8)
    params = {"w": _sharded(mesh8, src, P("data", None))}
    save_checkpoint(str(tmp_path), "t1", params)
    # load under a DIFFERENT sharding (model-dim split) and dtype
    target = {"w": jnp.zeros((16, 8), jnp.bfloat16)}
    shardings = {"w": NamedSharding(mesh8, P(None, "data"))}
    out, _, _ = load_checkpoint(str(tmp_path), "t1",
                                params_template=(target, shardings))
    np.testing.assert_allclose(np.asarray(out["w"], np.float32),
                               np.asarray(src))
    assert out["w"].sharding.spec == P(None, "data")
    assert out["w"].dtype == jnp.bfloat16


def test_bf16_and_scalar_leaves(tmp_path, mesh8):
    params = {
        "w": _sharded(mesh8, jnp.full((8, 4), 1.5, jnp.bfloat16),
                      P("data", None)),
        "count": jnp.int32(7),
    }
    save_checkpoint(str(tmp_path), "t1", params)
    tmpl = {"w": jnp.zeros((8, 4), jnp.bfloat16), "count": jnp.int32(0)}
    sh = {"w": NamedSharding(mesh8, P("data", None)),
          "count": NamedSharding(mesh8, P())}
    out, _, _ = load_checkpoint(str(tmp_path), "t1",
                                params_template=(tmpl, sh))
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), 1.5)
    assert int(out["count"]) == 7


def test_async_save_commits_latest_after_writes(tmp_path, mesh8):
    params = {"w": _sharded(mesh8, jnp.ones((8, 128), jnp.float32),
                            P("data", None))}
    save_checkpoint(str(tmp_path), "a1", params, async_save=True)
    wait_pending()
    assert read_latest_tag(str(tmp_path)) == "a1"
    out, _, _ = load_checkpoint(
        str(tmp_path), "a1",
        params_template=({"w": jnp.zeros((8, 128))},
                         {"w": NamedSharding(mesh8, P("data", None))}))
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_async_save_snapshot_isolated_from_donation(tmp_path, mesh8):
    """The D2H copy happens before save returns, so mutating (donating) the
    array afterwards cannot corrupt the checkpoint."""
    w = _sharded(mesh8, jnp.ones((8, 64), jnp.float32), P("data", None))
    save_checkpoint(str(tmp_path), "a1", {"w": w}, async_save=True)
    w2 = jax.jit(lambda x: x * 0.0, donate_argnums=0)(w)  # clobber buffer
    del w2
    wait_pending()
    out, _, _ = load_checkpoint(
        str(tmp_path), "a1",
        params_template=({"w": jnp.zeros((8, 64))},
                         {"w": NamedSharding(mesh8, P("data", None))}))
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_partial_coverage_rejected(tmp_path, mesh8):
    params = {"w": _sharded(mesh8, jnp.ones((8, 8)), P("data", None))}
    save_checkpoint(str(tmp_path), "t1", params)
    # delete one shard file -> load must fail loudly, not zero-fill
    victim = glob.glob(str(tmp_path / "t1" / "arrays" / "*.s3.npy"))[0]
    os.remove(victim)
    with pytest.raises((ValueError, FileNotFoundError)):
        load_checkpoint(
            str(tmp_path), "t1",
            params_template=({"w": jnp.zeros((8, 8))},
                             {"w": NamedSharding(mesh8, P("data", None))}))


def test_consolidate_zero_to_fp32(tmp_path, mesh8):
    """Offline zero_to_fp32 analog: sharded checkpoint -> consolidated
    fp32 flat file preferring the optimizer's fp32 master, no engine or
    devices needed at conversion time."""
    from deepspeed_tpu.runtime.checkpoint import (consolidate_checkpoint,
                                                  load_flat_weights)

    rng = np.random.RandomState(0)
    master = rng.randn(8, 8).astype(np.float32)
    params = {"w": _sharded(mesh8, jnp.asarray(master, jnp.bfloat16),
                            P("data", None)),
              "b": _sharded(mesh8, jnp.ones((4,), jnp.bfloat16), P())}
    opt = {"master": {"w": _sharded(mesh8, jnp.asarray(master),
                                    P("data", None)),
                      "b": _sharded(mesh8, jnp.ones((4,), jnp.float32), P())},
           "count": jnp.int32(3)}
    save_checkpoint(str(tmp_path), "t1", params, opt_state=opt)
    out = consolidate_checkpoint(str(tmp_path), str(tmp_path / "fp32.npz"))
    flat = load_flat_weights(out)
    assert set(flat) == {"w", "b"}
    assert flat["w"].dtype == np.float32
    # EXACT fp32 master, not the bf16-rounded param
    np.testing.assert_array_equal(flat["w"], master)
    assert np.abs(np.asarray(flat["w"], np.float32)
                  - np.asarray(params["w"], np.float32)).max() > 0
    # --no-master: bf16 params cast to fp32
    out2 = consolidate_checkpoint(str(tmp_path), str(tmp_path / "p.npz"),
                                  prefer_master=False)
    flat2 = load_flat_weights(out2)
    np.testing.assert_array_equal(
        flat2["w"], np.asarray(params["w"], np.float32))
