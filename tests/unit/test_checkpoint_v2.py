"""Sharded/async checkpoint tests (format 2) — the reference's
tests/unit/checkpoint suite concerns (zero shards per rank, reshape across
topologies, latest-tag semantics) plus async-commit ordering, and the
durability layer (atomic tmp-dir+rename saves, per-shard crc32 checksums,
verified load with previous-good-tag fallback)."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.checkpoint import (CheckpointCorruption,
                                              find_verified_tag,
                                              list_tags, load_checkpoint,
                                              read_latest_tag,
                                              save_checkpoint,
                                              verify_checkpoint,
                                              wait_pending)


@pytest.fixture
def mesh8(devices8):
    return Mesh(np.array(devices8), ("data",))


def _sharded(mesh, arr, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def test_sharded_leaves_write_per_shard_files(tmp_path, mesh8):
    params = {
        "w": _sharded(mesh8, jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                      P("data", None)),
        "b": _sharded(mesh8, jnp.ones((4,), jnp.float32), P()),
    }
    save_checkpoint(str(tmp_path), "t1", params)
    files = sorted(os.path.basename(f) for f in
                   glob.glob(str(tmp_path / "t1" / "arrays" / "*.npy")))
    w_files = [f for f in files if "w" in f and "b" not in f]
    assert len(w_files) == 8, files           # one file per unique shard
    # each shard file holds 1/8 of the array, in global coords per metadata
    meta = json.load(open(tmp_path / "t1" / "metadata.json"))
    info = meta["arrays"]["params##w"]
    assert len(info["shards"]) == 8
    assert info["shards"][0]["bounds"] == [[0, 1], [0, 8]]
    # replicated leaf collapses to ONE file
    b_files = [f for f in files if "##b" in f]
    assert len(b_files) == 1


def test_roundtrip_resharded(tmp_path, mesh8):
    src = jnp.arange(128, dtype=jnp.float32).reshape(16, 8)
    params = {"w": _sharded(mesh8, src, P("data", None))}
    save_checkpoint(str(tmp_path), "t1", params)
    # load under a DIFFERENT sharding (model-dim split) and dtype
    target = {"w": jnp.zeros((16, 8), jnp.bfloat16)}
    shardings = {"w": NamedSharding(mesh8, P(None, "data"))}
    out, _, _ = load_checkpoint(str(tmp_path), "t1",
                                params_template=(target, shardings))
    np.testing.assert_allclose(np.asarray(out["w"], np.float32),
                               np.asarray(src))
    assert out["w"].sharding.spec == P(None, "data")
    assert out["w"].dtype == jnp.bfloat16


def test_bf16_and_scalar_leaves(tmp_path, mesh8):
    params = {
        "w": _sharded(mesh8, jnp.full((8, 4), 1.5, jnp.bfloat16),
                      P("data", None)),
        "count": jnp.int32(7),
    }
    save_checkpoint(str(tmp_path), "t1", params)
    tmpl = {"w": jnp.zeros((8, 4), jnp.bfloat16), "count": jnp.int32(0)}
    sh = {"w": NamedSharding(mesh8, P("data", None)),
          "count": NamedSharding(mesh8, P())}
    out, _, _ = load_checkpoint(str(tmp_path), "t1",
                                params_template=(tmpl, sh))
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), 1.5)
    assert int(out["count"]) == 7


def test_async_save_commits_latest_after_writes(tmp_path, mesh8):
    params = {"w": _sharded(mesh8, jnp.ones((8, 128), jnp.float32),
                            P("data", None))}
    save_checkpoint(str(tmp_path), "a1", params, async_save=True)
    wait_pending()
    assert read_latest_tag(str(tmp_path)) == "a1"
    out, _, _ = load_checkpoint(
        str(tmp_path), "a1",
        params_template=({"w": jnp.zeros((8, 128))},
                         {"w": NamedSharding(mesh8, P("data", None))}))
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_async_save_snapshot_isolated_from_donation(tmp_path, mesh8):
    """The D2H copy happens before save returns, so mutating (donating) the
    array afterwards cannot corrupt the checkpoint."""
    w = _sharded(mesh8, jnp.ones((8, 64), jnp.float32), P("data", None))
    save_checkpoint(str(tmp_path), "a1", {"w": w}, async_save=True)
    w2 = jax.jit(lambda x: x * 0.0, donate_argnums=0)(w)  # clobber buffer
    del w2
    wait_pending()
    out, _, _ = load_checkpoint(
        str(tmp_path), "a1",
        params_template=({"w": jnp.zeros((8, 64))},
                         {"w": NamedSharding(mesh8, P("data", None))}))
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_partial_coverage_rejected(tmp_path, mesh8):
    params = {"w": _sharded(mesh8, jnp.ones((8, 8)), P("data", None))}
    save_checkpoint(str(tmp_path), "t1", params)
    # delete one shard file -> load must fail loudly, not zero-fill
    victim = glob.glob(str(tmp_path / "t1" / "arrays" / "*.s3.npy"))[0]
    os.remove(victim)
    with pytest.raises((ValueError, FileNotFoundError)):
        load_checkpoint(
            str(tmp_path), "t1",
            params_template=({"w": jnp.zeros((8, 8))},
                             {"w": NamedSharding(mesh8, P("data", None))}))


class TestDurability:
    """Atomic saves + content checksums + verified load with fallback —
    the rollback-target guarantees the self-healing session leans on."""

    def _params(self, mesh8, value=1.0):
        return {"w": _sharded(mesh8,
                              jnp.full((8, 8), value, jnp.float32),
                              P("data", None)),
                "b": _sharded(mesh8, jnp.ones((4,), jnp.float32), P())}

    def test_atomic_save_leaves_no_staging_dir(self, tmp_path, mesh8):
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8))
        assert (tmp_path / "t1" / "metadata.json").exists()
        assert not (tmp_path / ".t1.tmp").exists()
        # every shard carries a content checksum in the format-2 meta
        meta = json.load(open(tmp_path / "t1" / "metadata.json"))
        for info in meta["arrays"].values():
            for shard in info["shards"]:
                assert isinstance(shard["crc32"], int)

    def test_crash_mid_save_never_published(self, tmp_path, mesh8,
                                            monkeypatch):
        """A save that dies before the rename leaves only the staging dir:
        `latest` still names the previous good tag and the next save
        recovers the staging path."""
        save_checkpoint(str(tmp_path), "good", self._params(mesh8))
        calls = {"n": 0}
        real_save = np.save

        def dying_save(path, data, **kw):
            calls["n"] += 1
            if calls["n"] == 3:
                raise OSError("disk died mid-save")
            return real_save(path, data, **kw)

        monkeypatch.setattr(np, "save", dying_save)
        with pytest.raises(OSError, match="disk died"):
            save_checkpoint(str(tmp_path), "torn",
                            self._params(mesh8, 2.0))
        monkeypatch.setattr(np, "save", real_save)
        assert read_latest_tag(str(tmp_path)) == "good"
        assert not (tmp_path / "torn").exists()   # nothing half-published
        assert list_tags(str(tmp_path)) == ["good"]
        # the interrupted staging dir does not break the next save
        save_checkpoint(str(tmp_path), "torn", self._params(mesh8, 3.0))
        assert read_latest_tag(str(tmp_path)) == "torn"
        assert not verify_checkpoint(str(tmp_path), "torn")

    def test_truncated_shard_fails_verification(self, tmp_path, mesh8):
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8))
        assert verify_checkpoint(str(tmp_path), "t1") == []
        victim = glob.glob(str(tmp_path / "t1" / "arrays" / "*w*.s3.npy"))[0]
        size = os.path.getsize(victim)
        with open(victim, "r+b") as fh:
            fh.truncate(size // 2)
        problems = verify_checkpoint(str(tmp_path), "t1")
        assert problems and "w" in problems[0]

    def test_bitflip_fails_verification(self, tmp_path, mesh8):
        """Same length, different bytes — the case a size/existence check
        cannot catch but the crc does (the SDC scenario)."""
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8))
        victim = glob.glob(str(tmp_path / "t1" / "arrays" / "*w*.s0.npy"))[0]
        with open(victim, "r+b") as fh:
            fh.seek(os.path.getsize(victim) - 2)
            byte = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([byte[0] ^ 0xFF]))
        problems = verify_checkpoint(str(tmp_path), "t1")
        assert problems and "checksum mismatch" in problems[0]

    def test_verified_load_falls_back_to_previous_good_tag(self, tmp_path,
                                                           mesh8):
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8, 1.0))
        save_checkpoint(str(tmp_path), "t2", self._params(mesh8, 2.0))
        assert read_latest_tag(str(tmp_path)) == "t2"
        victim = glob.glob(str(tmp_path / "t2" / "arrays" / "*w*.s0.npy"))[0]
        with open(victim, "r+b") as fh:
            fh.truncate(4)
        assert find_verified_tag(str(tmp_path)) == "t1"
        tmpl = ({"w": jnp.zeros((8, 8)), "b": jnp.zeros((4,))},
                {"w": NamedSharding(mesh8, P("data", None)),
                 "b": NamedSharding(mesh8, P())})
        out, _, client = load_checkpoint(str(tmp_path),
                                         params_template=tmpl, verify=True)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)   # t1, not t2
        assert client["_checkpoint_tag"] == "t1"
        # unverified load would have walked into the corrupt latest
        with pytest.raises((ValueError, OSError)):
            load_checkpoint(str(tmp_path), params_template=tmpl)

    def test_all_tags_corrupt_raises(self, tmp_path, mesh8):
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8))
        for victim in glob.glob(str(tmp_path / "t1" / "arrays" / "*.npy")):
            with open(victim, "r+b") as fh:
                fh.truncate(2)
        with pytest.raises(CheckpointCorruption, match="no checkpoint tag"):
            load_checkpoint(
                str(tmp_path), verify=True,
                params_template=({"w": jnp.zeros((8, 8))},
                                 {"w": NamedSharding(mesh8,
                                                     P("data", None))}))

    def test_interrupted_swap_recovered_on_read(self, tmp_path, mesh8):
        """Crash between the two publish renames (old tree moved aside, new
        tree not yet in place): read_latest_tag restores the old good tree
        from <tag>.replaced.tmp instead of leaving `latest` dangling."""
        import shutil

        save_checkpoint(str(tmp_path), "t1", self._params(mesh8, 1.0))
        shutil.move(str(tmp_path / "t1"),
                    str(tmp_path / "t1.replaced.tmp"))
        assert read_latest_tag(str(tmp_path)) == "t1"
        assert (tmp_path / "t1" / "metadata.json").exists()
        assert not (tmp_path / "t1.replaced.tmp").exists()
        assert verify_checkpoint(str(tmp_path), "t1") == []

    def test_resave_same_tag_swaps_atomically(self, tmp_path, mesh8):
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8, 1.0))
        save_checkpoint(str(tmp_path), "t1", self._params(mesh8, 5.0))
        assert not (tmp_path / "t1.replaced.tmp").exists()
        tmpl = ({"w": jnp.zeros((8, 8)), "b": jnp.zeros((4,))},
                {"w": NamedSharding(mesh8, P("data", None)),
                 "b": NamedSharding(mesh8, P())})
        out, _, _ = load_checkpoint(str(tmp_path), "t1",
                                    params_template=tmpl, verify=True)
        np.testing.assert_allclose(np.asarray(out["w"]), 5.0)


def test_consolidate_zero_to_fp32(tmp_path, mesh8):
    """Offline zero_to_fp32 analog: sharded checkpoint -> consolidated
    fp32 flat file preferring the optimizer's fp32 master, no engine or
    devices needed at conversion time."""
    from deepspeed_tpu.runtime.checkpoint import (consolidate_checkpoint,
                                                  load_flat_weights)

    rng = np.random.RandomState(0)
    master = rng.randn(8, 8).astype(np.float32)
    params = {"w": _sharded(mesh8, jnp.asarray(master, jnp.bfloat16),
                            P("data", None)),
              "b": _sharded(mesh8, jnp.ones((4,), jnp.bfloat16), P())}
    opt = {"master": {"w": _sharded(mesh8, jnp.asarray(master),
                                    P("data", None)),
                      "b": _sharded(mesh8, jnp.ones((4,), jnp.float32), P())},
           "count": jnp.int32(3)}
    save_checkpoint(str(tmp_path), "t1", params, opt_state=opt)
    out = consolidate_checkpoint(str(tmp_path), str(tmp_path / "fp32.npz"))
    flat = load_flat_weights(out)
    assert set(flat) == {"w", "b"}
    assert flat["w"].dtype == np.float32
    # EXACT fp32 master, not the bf16-rounded param
    np.testing.assert_array_equal(flat["w"], master)
    assert np.abs(np.asarray(flat["w"], np.float32)
                  - np.asarray(params["w"], np.float32)).max() > 0
    # --no-master: bf16 params cast to fp32
    out2 = consolidate_checkpoint(str(tmp_path), str(tmp_path / "p.npz"),
                                  prefer_master=False)
    flat2 = load_flat_weights(out2)
    np.testing.assert_array_equal(
        flat2["w"], np.asarray(params["w"], np.float32))
