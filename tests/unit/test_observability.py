"""Unit tests for ``deepspeed_tpu/observability/`` — span tracer, metrics
registry, recompile watchdog, memory gauges, comm instrumentation, report CLI
and the engine-level smoke (the acceptance path: a CPU train run with
observability enabled produces a loadable Chrome trace + metrics JSONL that
``python -m deepspeed_tpu.observability report`` can summarize; disabled —
the default — writes nothing).

All CPU-safe: collectives run on the 8-virtual-device mesh, memory gauges hit
the stat-less CPU backend's no-op branch, and the watchdog forces a re-trace
by changing a static arg."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu import observability as obs_mod
from deepspeed_tpu.config.config import ObservabilityConfig
from deepspeed_tpu.models import simple_model
from deepspeed_tpu.observability import (Observability, configure_observability,
                                         get_registry, get_session,
                                         reset_session)
from deepspeed_tpu.observability.memory import record_memory
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.observability.recompile import install as install_watchdog
from deepspeed_tpu.observability.report import report as render_report
from deepspeed_tpu.observability.spans import SpanTracer
from deepspeed_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _obs_isolation():
    """The registry, session and watchdog are process-globals; every test in
    this module starts and ends clean."""
    reset_session()
    get_registry().reset()
    yield
    reset_session()
    get_registry().reset()


# ---------------------------------------------------------------------------
# spans


class TestSpans:
    def test_nesting_depth_and_parent(self):
        tr = SpanTracer(process_index=0)
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        recs = {r["name"]: r for r in tr.snapshot()}
        assert recs["outer"]["depth"] == 0 and "parent" not in recs["outer"]
        assert recs["inner"]["depth"] == 1
        assert recs["inner"]["parent"] == "outer"
        # inner closed first (JSONL order), and nests inside outer's interval
        assert recs["inner"]["dur_us"] <= recs["outer"]["dur_us"]

    def test_chrome_trace_round_trip(self, tmp_path):
        tr = SpanTracer(process_index=0)
        with tr.span("fwd", step=3):
            pass
        path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as fh:
            doc = json.load(fh)
        (ev,) = doc["traceEvents"]
        assert ev["ph"] == "X" and ev["name"] == "fwd"
        assert ev["dur"] >= 0 and ev["args"]["step"] == 3

    def test_jsonl_written_as_spans_close(self, tmp_path):
        """Tail safety: records land in the JSONL at close time, before any
        flush/close call — a killed run keeps what it measured."""
        path = str(tmp_path / "t.jsonl")
        tr = SpanTracer(jsonl_path=path, process_index=0)
        with tr.span("a"):
            pass
        with open(path) as fh:
            lines = [json.loads(l) for l in fh if l.strip()]
        assert [l["name"] for l in lines] == ["a"]
        tr.close()

    def test_disabled_tracer_measures_but_records_nothing(self):
        tr = SpanTracer(enabled=False, process_index=0)
        with tr.span("x") as s:
            pass
        assert s.duration_s >= 0          # callers deriving TTFT stay correct
        assert tr.snapshot() == []

    def test_rank_gating(self, tmp_path):
        tr = SpanTracer(jsonl_path=str(tmp_path / "r.jsonl"), process_index=1)
        with tr.span("x"):
            pass
        assert tr.snapshot() == []
        assert not os.path.exists(tmp_path / "r.jsonl")
        tr_all = SpanTracer(all_ranks=True, process_index=1)
        with tr_all.span("x"):
            pass
        assert tr_all.snapshot()[0]["pid"] == 1

    def test_decorator(self):
        tr = SpanTracer(process_index=0)

        @tr.trace("work")
        def f(a):
            return a + 1

        assert f(1) == 2
        assert tr.snapshot()[0]["name"] == "work"

    def test_non_lexical_begin_end(self):
        tr = SpanTracer(process_index=0)
        s = tr.span("profile").begin()
        assert tr.current_name() == "profile"
        s.end()
        assert tr.current_name() is None
        assert tr.snapshot()[0]["name"] == "profile"


# ---------------------------------------------------------------------------
# metrics registry


class TestMetricsRegistry:
    def test_counter_semantics(self):
        reg = MetricsRegistry()
        c = reg.counter("comm/bytes")
        c.inc(100, op="all_reduce")
        c.inc(50, op="all_reduce")
        c.inc(7, op="all_gather")
        assert c.value(op="all_reduce") == 150
        assert c.value(op="all_gather") == 7
        assert c.value(op="missing") == 0
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_last_write_wins(self):
        reg = MetricsRegistry()
        g = reg.gauge("loss")
        g.set(2.0)
        g.set(1.5)
        assert g.value() == 1.5
        assert g.value(other="label") is None

    def test_histogram_running_stats(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms")
        for v in (1.0, 3.0, 2.0):
            h.observe(v, op="x")
        st = h.stats(op="x")
        assert st == {"count": 3, "sum": 6.0, "min": 1.0, "max": 3.0}
        (rec,) = h.records()
        assert rec["mean"] == 2.0

    def test_memoized_by_name_and_kind_conflict(self):
        reg = MetricsRegistry()
        assert reg.counter("n") is reg.counter("n")
        with pytest.raises(TypeError):
            reg.gauge("n")

    def test_exporter_fan_out(self):
        class FakeWriter:
            def __init__(self):
                self.events = []

            def write_events(self, events):
                self.events.extend(events)

        reg = MetricsRegistry()
        w = FakeWriter()
        reg.attach_exporter(w)
        reg.gauge("loss").set(0.5)
        reg.counter("steps").inc()
        events = reg.publish(step=7)
        assert w.events == events
        assert ("loss", 0.5, 7) in w.events and ("steps", 1.0, 7) in w.events
        # names filter restricts the snapshot
        w.events.clear()
        reg.publish(step=8, names=["loss"])
        assert w.events == [("loss", 0.5, 8)]
        reg.detach_exporter(w)
        w.events.clear()
        reg.publish(step=9)
        assert w.events == []

    def test_dump_jsonl_round_trip(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc(2, op="x")
        reg.histogram("h").observe(1.0)
        path = reg.dump_jsonl(str(tmp_path / "m.jsonl"), extra={"run": "t"})
        with open(path) as fh:
            recs = [json.loads(l) for l in fh]
        assert recs[0]["type"] == "meta" and recs[0]["run"] == "t"
        by_name = {r["name"]: r for r in recs[1:]}
        assert by_name["c"]["value"] == 2 and by_name["c"]["labels"] == {"op": "x"}
        assert by_name["h"]["count"] == 1


# ---------------------------------------------------------------------------
# recompile watchdog


class TestRecompileWatchdog:
    def test_static_arg_retrace_records_miss(self):
        reg = MetricsRegistry()
        wd = install_watchdog(registry=reg)

        f = jax.jit(lambda x, n: x * n, static_argnums=1)
        f(jnp.ones(4), 2).block_until_ready()
        first = wd.compile_count
        assert first >= 1
        f(jnp.ones(4), 3).block_until_ready()  # static-arg change => re-trace
        assert wd.compile_count > first
        assert reg.counter("xla/compiles").value(where="<untraced>") >= 2
        assert wd.compile_seconds > 0
        rep = wd.report()
        assert rep["compiles"] == wd.compile_count
        assert rep["per_site"]["<untraced>"]["count"] >= 2

    def test_compile_attributed_to_open_span(self):
        reg = MetricsRegistry()
        tr = SpanTracer(process_index=0)
        wd = install_watchdog(registry=reg, tracer=tr)
        with tr.span("train_batch"):
            jax.jit(lambda x: x + jnp.float32(17))(jnp.ones(3)).block_until_ready()
        assert wd.per_site.get("train_batch", {}).get("count", 0) >= 1
        assert reg.counter("xla/compiles").value(where="train_batch") >= 1

    def test_steady_state_recompile_warns(self, caplog):
        from deepspeed_tpu.utils.logging import logger as ds_logger

        reg = MetricsRegistry()
        wd = install_watchdog(registry=reg, steady_state_step=5)
        wd.note_step(6)
        # the package logger does not propagate; hook caplog's handler on it
        ds_logger.addHandler(caplog.handler)
        try:
            # a site's FIRST post-threshold compile is a legitimately new
            # function — no warning...
            jax.jit(lambda x: x - jnp.float32(23))(jnp.ones(3)).block_until_ready()
            assert wd.steady_state_compiles == 0
            assert not caplog.records
            # ...a REPEAT compile at the same site is a re-specialization
            jax.jit(lambda x: x - jnp.float32(31))(jnp.ones(3)).block_until_ready()
        finally:
            ds_logger.removeHandler(caplog.handler)
        assert wd.steady_state_compiles >= 1
        assert reg.counter("xla/steady_state_recompiles").value(
            where="<untraced>") >= 1
        assert any("steady-state recompilation" in r.message
                   for r in caplog.records)

    def test_uninstall_stops_counting(self):
        reg = MetricsRegistry()
        wd = install_watchdog(registry=reg)
        obs_mod.uninstall_watchdog()
        jax.jit(lambda x: x * jnp.float32(29))(jnp.ones(3)).block_until_ready()
        assert wd.compile_count == 0


# ---------------------------------------------------------------------------
# memory gauges


class TestMemory:
    def test_cpu_no_op_device_side_host_rss_recorded(self):
        reg = MetricsRegistry()
        # the CPU backend reports no allocator stats => device side no-ops
        assert record_memory(reg) is False
        rss = reg.gauge("mem/host_rss_bytes").value()
        assert rss is not None and rss > 0
        assert not any(m.name.startswith("mem/device/") for m in reg.metrics())


# ---------------------------------------------------------------------------
# comm instrumentation (CPU mesh)


class TestCommInstrumentation:
    def test_traced_collectives_publish_census(self, devices8, tmp_path):
        from deepspeed_tpu import comm
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.parallel import mesh as mesh_mod

        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        reg = get_session().registry
        m = mesh_mod.build_mesh(ParallelConfig())
        x = jnp.arange(8.0)
        f = shard_map(lambda v: comm.all_reduce(v, axis="data"),
                      mesh=m, in_specs=P("data"), out_specs=P())
        np.testing.assert_allclose(np.asarray(f(x)), [28.0])
        # census: recorded once per compiled program, with message bytes
        assert reg.counter("comm/ops").value(op="all_reduce") >= 1
        assert reg.counter("comm/bytes").value(op="all_reduce") > 0

    def test_disabled_session_records_nothing(self, devices8):
        from deepspeed_tpu import comm
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.parallel import mesh as mesh_mod

        reg = get_registry()
        m = mesh_mod.build_mesh(ParallelConfig())
        f = shard_map(lambda v: comm.all_gather(v, axis="data"),
                      mesh=m, in_specs=P("data"), out_specs=P("data"))
        f(jnp.arange(8.0)).block_until_ready()
        assert reg.counter("comm/ops").value(op="all_gather") == 0


# ---------------------------------------------------------------------------
# monitor writers as registry exporters + CSV lifecycle


class TestMonitorExport:
    def _csv_master(self, tmp_path):
        from deepspeed_tpu.config.config import MonitorConfig
        from deepspeed_tpu.monitor.monitor import MonitorMaster

        cfg = MonitorConfig.from_dict({
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                            "job_name": "job"}})
        return MonitorMaster(cfg)

    def test_registry_publish_reaches_csv(self, tmp_path):
        master = self._csv_master(tmp_path)
        reg = MetricsRegistry()
        reg.attach_exporter(master)
        reg.gauge("Train/Samples/train_loss").set(0.25)
        reg.publish(step=3)
        master.close()
        csv_path = tmp_path / "job" / "Train_Samples_train_loss.csv"
        rows = csv_path.read_text().strip().splitlines()
        assert rows[0].startswith("step,")
        assert rows[1] == "3,0.25"

    def test_csv_handles_flushed_and_closed(self, tmp_path):
        from deepspeed_tpu.monitor.monitor import CSVMonitor

        master = self._csv_master(tmp_path)
        csv_writer = next(w for w in master.writers
                          if isinstance(w, CSVMonitor))
        master.write_events([("m", 1.0, 1), ("m", 2.0, 2)])
        # write_events flushes: rows are on disk without close
        rows = (tmp_path / "job" / "m.csv").read_text().strip().splitlines()
        assert len(rows) == 3
        master.close()
        assert csv_writer._files == {}
        assert not csv_writer.enabled
        # close() is terminal: a late write_events is a silent no-op
        master.write_events([("m", 3.0, 3)])
        rows = (tmp_path / "job" / "m.csv").read_text().strip().splitlines()
        assert rows[-1] == "2,2.0"


# ---------------------------------------------------------------------------
# report CLI


class TestReportCli:
    def test_report_summarizes_spans_metrics_recompiles(self, tmp_path):
        path = tmp_path / "mix.jsonl"
        recs = [
            {"type": "span", "name": "fwd", "ts_us": 0, "dur_us": 1000,
             "depth": 1},
            {"type": "span", "name": "fwd", "ts_us": 2000, "dur_us": 3000,
             "depth": 1},
            {"type": "counter", "name": "comm/bytes",
             "labels": {"op": "all_reduce"}, "value": 4096},
            {"type": "gauge", "name": "loss", "labels": {}, "value": 0.5},
            {"type": "histogram", "name": "lat", "labels": {}, "count": 2,
             "sum": 3.0, "min": 1.0, "max": 2.0, "mean": 1.5},
            {"type": "counter", "name": "xla/compiles",
             "labels": {"where": "train_batch"}, "value": 2},
        ]
        path.write_text("".join(json.dumps(r) + "\n" for r in recs))
        out = render_report([str(path)])
        assert "== spans ==" in out and "fwd" in out and "2" in out
        assert "== counters ==" in out and "op=all_reduce" in out
        assert "== gauges ==" in out and "loss" in out
        assert "== histograms ==" in out
        assert "== recompiles ==" in out and "train_batch" in out

    def test_cli_entry(self, tmp_path):
        import subprocess
        import sys

        path = tmp_path / "t.jsonl"
        path.write_text(json.dumps(
            {"type": "span", "name": "s", "ts_us": 0, "dur_us": 10,
             "depth": 0}) + "\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.observability", "report",
             str(path)],
            capture_output=True, text=True, cwd="/root/repo", env=env)
        assert r.returncode == 0 and "== spans ==" in r.stdout

    def test_report_empty(self, tmp_path):
        path = tmp_path / "e.jsonl"
        path.write_text("")
        assert "no span or metric records" in render_report([str(path)])


# ---------------------------------------------------------------------------
# session + config gating


class TestSessionGating:
    def test_default_session_is_disabled_and_shared(self):
        s = get_session()
        assert not s.enabled
        assert get_session() is s
        assert s.metrics_path() is None and s.chrome_trace_path() is None

    def test_disabled_config_leaves_current_session_alone(self, tmp_path):
        live = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        assert get_session() is live
        off = configure_observability(ObservabilityConfig(enabled=False))
        assert not off.enabled
        assert get_session() is live   # telemetry-free engine kept the trace

    def test_replacing_enabled_session_closes_the_old_one(self, tmp_path):
        old = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "a")))
        new = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "b")))
        assert get_session() is new
        # the replaced session is closed: its JSONL handle is released and
        # its (LIFO-last) atexit close can no longer overwrite live exports
        assert old._closed and old.tracer._fh is None
        assert not new._closed

    def test_dump_metrics_rank_gated(self, tmp_path):
        sess = Observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)), process_index=1)
        sess.registry.counter("c").inc()
        assert sess.dump_metrics() is None     # all_ranks=False, rank 1
        assert not os.path.exists(tmp_path / "metrics.jsonl")
        sess.close(export=False)

    def test_host_timed_comm_metrics_separate_series(self, tmp_path):
        from deepspeed_tpu.comm.comm import _record_comm_metrics

        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        reg = get_session().registry
        _record_comm_metrics("all_reduce", "ckpt", 1024, latency_s=0.002)
        # host-timed calls must not pollute the per-compile census series
        assert reg.counter("comm/ops").value(op="all_reduce") == 0
        assert reg.counter("comm/host_ops").value(op="all_reduce") == 1
        assert reg.counter("comm/host_bytes").value(op="all_reduce") == 1024
        assert reg.histogram("comm/latency_ms").stats(op="ckpt")["count"] == 1

    def test_dump_jsonl_truncates_by_default(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        path = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(path)
        reg.dump_jsonl(path)                  # snapshot: second dump replaces
        assert len(open(path).readlines()) == 1
        reg.dump_jsonl(path, append=True)     # trajectory mode is opt-in
        assert len(open(path).readlines()) == 2

    def test_config_validation(self):
        from deepspeed_tpu.config.base import ConfigError

        with pytest.raises(ConfigError):
            ObservabilityConfig.from_dict({"max_spans": 0})
        with pytest.raises(ConfigError):
            ObservabilityConfig.from_dict({"memory_poll_steps": 0})


# ---------------------------------------------------------------------------
# engine smoke (the acceptance path)


def _obs_engine(tmp_path, enabled=True):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "steps_per_print": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "observability": {"enabled": enabled,
                             "output_dir": str(tmp_path / "obs")}}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model(hidden_dim=10),
                                          config=cfg)
    return engine


class TestEngineSmoke:
    def test_enabled_run_produces_trace_and_metrics(self, tmp_path, devices8):
        from deepspeed_tpu import comm
        from deepspeed_tpu.models.simple import random_batches

        engine = _obs_engine(tmp_path)
        obs = engine._obs
        assert obs.enabled and get_session() is obs
        batches = random_batches(jax.random.PRNGKey(0), 4,
                                 engine.train_batch_size())
        it = iter(batches)
        for _ in range(2):
            engine.train_batch(data_iter=it)
        # fwd/bwd/step API spans
        engine.forward(next(it))
        engine.backward()
        engine.step()
        # one traced collective so the comm census lands in the same run
        m = engine.mesh
        shard_map(lambda v: comm.all_reduce(v, axis="data"), mesh=m,
                  in_specs=P("data"), out_specs=P())(jnp.arange(8.0))

        metrics_path = obs.dump_metrics()
        chrome_path = obs.export_chrome_trace()
        obs.flush()

        # span JSONL has the step phases
        with open(obs.tracer.jsonl_path) as fh:
            names = {json.loads(l)["name"] for l in fh if l.strip()}
        assert {"train_batch", "fwd", "bwd", "step"} <= names

        # chrome trace is loadable and non-empty
        with open(chrome_path) as fh:
            doc = json.load(fh)
        assert any(ev["ph"] == "X" for ev in doc["traceEvents"])

        # metrics JSONL: loss gauge, comm census, memory gauge, >=1 compile
        with open(metrics_path) as fh:
            recs = [json.loads(l) for l in fh if l.strip()]
        by = {(r.get("name"), r["type"]): r for r in recs}
        assert ("Train/Samples/train_loss", "gauge") in by
        assert by[("comm/ops", "counter")]["value"] >= 1
        assert by[("comm/bytes", "counter")]["value"] > 0
        assert ("mem/host_rss_bytes", "gauge") in by
        compile_recs = [r for r in recs if r.get("name") == "xla/compiles"]
        assert sum(r["value"] for r in compile_recs) >= 1
        meta = recs[0]
        assert meta["type"] == "meta"
        assert meta["recompile_report"]["compiles"] >= 1

        # the report CLI summarizes the pair
        out = render_report([obs.tracer.jsonl_path, metrics_path])
        assert "train_batch" in out and "== recompiles ==" in out

    def test_disabled_run_writes_nothing(self, tmp_path):
        from deepspeed_tpu.models.simple import random_batches

        engine = _obs_engine(tmp_path, enabled=False)
        assert not engine._obs.enabled
        batches = random_batches(jax.random.PRNGKey(0), 1,
                                 engine.train_batch_size())
        engine.train_batch(data_iter=iter(batches))
        assert not os.path.exists(tmp_path / "obs")
        assert engine._obs.dump_metrics() is None
        assert engine._obs.export_chrome_trace() is None

    def test_profile_double_start_guarded(self, tmp_path):
        engine = _obs_engine(tmp_path, enabled=False)
        engine._profiling = True   # simulate an active trace
        with pytest.raises(RuntimeError, match="already"):
            engine.start_profile()
        engine._profiling = False
        engine.stop_profile()      # no active trace: warns, does not raise

    def test_profile_dir_from_config(self, tmp_path):
        engine = _obs_engine(tmp_path, enabled=False)
        assert engine.config.observability.profile_dir == "/tmp/dstpu_trace"
        cfg = ObservabilityConfig.from_dict({"profile_dir": "/tmp/elsewhere"})
        assert cfg.profile_dir == "/tmp/elsewhere"
