"""Spatial (diffusers-family) model blocks — the UNet/VAE consumer of
ops/spatial.py (reference module_inject/containers/{unet,vae}.py +
replace_policy generic_policies). Oracles: torch functional ops (GroupNorm /
conv2d / scaled-dot-product attention) and the pure-jnp path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.spatial import (attention_block, init_mid_block,
                                          mid_block, resnet_block)

GROUPS = 8


def _params_and_input(C=32, HW=8, B=2, seed=0):
    p = init_mid_block(jax.random.PRNGKey(seed), C)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (B, HW, HW, C),
                          jnp.float32)
    return p, x


def _torch_mid_block(p, x_nhwc):
    """Independent oracle: the same module built from torch functional ops
    (diffusers ResnetBlock2D / AttentionBlock semantics)."""
    torch = pytest.importorskip("torch")
    F = torch.nn.functional

    def t(a):
        return torch.tensor(np.asarray(a))

    def gn(x, n):   # x NCHW
        return F.group_norm(x, GROUPS, t(n["scale"]), t(n["bias"]), eps=1e-6)

    def conv(x, c):
        w = t(c["w"]).permute(3, 2, 0, 1)      # HWIO -> OIHW
        return F.conv2d(x, w, t(c["b"]), padding=1)

    def resnet(x, rp):
        h = conv(F.silu(gn(x, rp["norm1"])), rp["conv1"])
        h = conv(F.silu(gn(h, rp["norm2"])), rp["conv2"])
        return x + h

    def attn(x, ap):
        B, C, H, W = x.shape
        h = gn(x, ap["norm"])
        tokens = h.reshape(B, C, H * W).transpose(1, 2)     # (B, HW, C)
        q = tokens @ t(ap["q"]["w"]) + t(ap["q"]["b"])
        k = tokens @ t(ap["k"]["w"]) + t(ap["k"]["b"])
        v = tokens @ t(ap["v"]["w"]) + t(ap["v"]["b"])
        o = F.scaled_dot_product_attention(q[:, None], k[:, None],
                                           v[:, None])[:, 0]
        o = o @ t(ap["proj"]["w"]) + t(ap["proj"]["b"])
        return x + o.transpose(1, 2).reshape(B, C, H, W)

    with pytest.importorskip("torch").no_grad():
        x = t(x_nhwc).permute(0, 3, 1, 2)     # NHWC -> NCHW
        x = resnet(x, p["resnet1"])
        x = attn(x, p["attn"])
        x = resnet(x, p["resnet2"])
        return x.permute(0, 2, 3, 1).numpy()  # -> NHWC


def test_mid_block_matches_torch_oracle():
    p, x = _params_and_input()
    ours = np.asarray(mid_block(x, p, GROUPS, use_kernel=False))
    want = _torch_mid_block(p, np.asarray(x))
    np.testing.assert_allclose(ours, want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_mid_block_kernel_path_matches_jnp():
    """The Pallas spatial kernels (fused GroupNorm + flash attention) must
    reproduce the jnp path bit-for-bit-ish on the same weights."""
    p, x = _params_and_input(C=64, HW=16)
    ref = np.asarray(mid_block(x, p, GROUPS, use_kernel=False))
    kern = np.asarray(mid_block(x, p, GROUPS, interpret=True))
    np.testing.assert_allclose(kern, ref, atol=5e-4, rtol=5e-4)


def test_resnet_block_shortcut():
    p, x = _params_and_input()
    rp = dict(p["resnet1"])
    # channel-changing shortcut path
    C = x.shape[-1]
    rp["shortcut"] = {"w": jnp.eye(C)[None, None] * 0.5,
                      "b": jnp.zeros((C,))}
    out = resnet_block(x, rp, GROUPS, use_kernel=False)
    base = resnet_block(x, p["resnet1"], GROUPS, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out - base),
                               np.asarray(0.5 * x - x), atol=1e-5)


def test_attention_block_is_residual():
    p, x = _params_and_input()
    ap = jax.tree.map(jnp.zeros_like, p["attn"])
    ap["norm"]["scale"] = p["attn"]["norm"]["scale"]
    # zero qkv/proj weights => attention contributes exactly 0
    out = attention_block(x, ap, GROUPS, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)
