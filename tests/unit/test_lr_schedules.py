"""LR-schedule tests — analog of reference tests/unit/runtime/test_lr_schedulers.py."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupLR,
                                                WarmupCosineLR, WarmupDecayLR,
                                                build_lr_schedule)


def test_warmup_lr_linear():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10,
                 warmup_type="linear")
    assert float(s.lr_at(0)) == pytest.approx(0.0)
    assert float(s.lr_at(5)) == pytest.approx(0.05)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(100)) == pytest.approx(0.1)  # constant after warmup


def test_warmup_lr_log():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=100,
                 warmup_type="log")
    vals = [float(s.lr_at(t)) for t in [0, 10, 50, 99, 200]]
    assert vals == sorted(vals)
    assert vals[-1] == pytest.approx(0.1, rel=1e-2)


def test_warmup_decay():
    s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.0, warmup_max_lr=0.1,
                      warmup_num_steps=10, warmup_type="linear")
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(55)) == pytest.approx(0.05)
    assert float(s.lr_at(100)) == pytest.approx(0.0)
    assert float(s.lr_at(150)) == pytest.approx(0.0)  # clamped


def test_warmup_cosine():
    s = WarmupCosineLR(total_num_steps=100, warmup_max_lr=0.1, warmup_num_steps=10,
                       cos_min_ratio=0.1)
    assert float(s.lr_at(10)) == pytest.approx(0.1, rel=1e-5)
    assert float(s.lr_at(100)) == pytest.approx(0.01, rel=1e-4)
    mid = float(s.lr_at(55))
    assert 0.01 < mid < 0.1


def test_one_cycle():
    s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10,
                 decay_lr_rate=0.5, decay_step_size=10)
    assert float(s.lr_at(0)) == pytest.approx(0.01)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(20)) == pytest.approx(0.01)
    assert float(s.lr_at(40)) < 0.01  # decay phase


def test_lr_range_test():
    s = LRRangeTest(lr_range_test_min_lr=0.001, lr_range_test_step_size=10,
                    lr_range_test_step_rate=1.0)
    assert float(s.lr_at(0)) == pytest.approx(0.001)
    assert float(s.lr_at(10)) == pytest.approx(0.002)
    st = LRRangeTest(lr_range_test_min_lr=0.001, lr_range_test_step_size=10,
                     lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    assert float(st.lr_at(9)) == pytest.approx(0.001)
    assert float(st.lr_at(10)) == pytest.approx(0.002)


def test_stateful_interface():
    s = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear")
    s.step()
    s.step()
    assert s.last_batch_iteration == 1
    sd = s.state_dict()
    s2 = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear")
    s2.load_state_dict(sd)
    assert s2.get_lr() == s.get_lr()


def test_build_by_name():
    s = build_lr_schedule("WarmupDecayLR", {"total_num_steps": 100,
                                            "warmup_num_steps": 10,
                                            "warmup_max_lr": 0.01})
    assert float(s.lr_at(10)) == pytest.approx(0.01)
    with pytest.raises(ValueError):
        build_lr_schedule("Nope", {})
