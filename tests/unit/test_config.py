"""Config-system tests — analog of reference tests/unit/runtime/test_ds_config_dict.py
and test_ds_config_model.py."""

import json

import pytest

from deepspeed_tpu.config import Config, ConfigError, load_config
from deepspeed_tpu.config.config import ZeroConfig


def test_defaults():
    cfg = Config()
    assert cfg.zero_optimization.stage == 0
    assert not cfg.fp16.enabled
    assert cfg.precision_dtype == "float32"


def test_from_dict_nested():
    cfg = load_config({
        "train_batch_size": 16,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "sub_group_size": 1000},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    })
    assert cfg.train_batch_size == 16
    assert cfg.bf16.enabled
    assert cfg.precision_dtype == "bfloat16"
    assert cfg.zero_optimization.stage == 3
    assert cfg.zero_optimization.sub_group_size == 1000
    assert cfg.optimizer.params["lr"] == 1e-3


def test_unknown_key_rejected():
    with pytest.raises(ConfigError, match="unknown config key"):
        load_config({"zero_optimization": {"stagee": 2}})


def test_type_validation():
    with pytest.raises(ConfigError):
        load_config({"train_batch_size": "four"})
    with pytest.raises(ConfigError):
        load_config({"fp16": {"enabled": "maybe"}})


def test_deprecated_key_migration():
    cfg = ZeroConfig.from_dict({"stage3_gather_fp16_weights_on_model_save": True})
    assert cfg.stage3_gather_16bit_weights_on_model_save is True


def test_fp16_bf16_exclusive():
    with pytest.raises(ConfigError):
        load_config({"fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_invalid_zero_stage():
    with pytest.raises(ConfigError):
        load_config({"zero_optimization": {"stage": 5}})


def test_config_from_file(tmp_path):
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps({"train_micro_batch_size_per_gpu": 4,
                                "gradient_accumulation_steps": 2}))
    cfg = load_config(str(path))
    assert cfg.train_micro_batch_size_per_gpu == 4


# batch triad resolution — mirrors reference runtime/config.py:888 semantics
@pytest.mark.parametrize("given,dp,expect", [
    ({"train_batch_size": 32}, 4, (32, 8, 1)),
    ({"train_micro_batch_size_per_gpu": 2}, 4, (8, 2, 1)),
    ({"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 4}, 2, (16, 2, 4)),
    ({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4}, 4, (32, 4, 2)),
    ({"train_batch_size": 32, "gradient_accumulation_steps": 2}, 4, (32, 4, 2)),
    ({"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4,
      "gradient_accumulation_steps": 4}, 4, (64, 4, 4)),
])
def test_batch_triad(given, dp, expect):
    cfg = load_config(given).resolve_batch_sizes(dp)
    assert (cfg.train_batch_size, cfg.train_micro_batch_size_per_gpu,
            cfg.gradient_accumulation_steps) == expect


def test_batch_triad_inconsistent():
    with pytest.raises(ConfigError):
        load_config({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
                     "gradient_accumulation_steps": 4}).resolve_batch_sizes(4)


def test_batch_triad_missing():
    with pytest.raises(ConfigError):
        load_config({}).resolve_batch_sizes(4)


def test_roundtrip_to_dict():
    cfg = load_config({"bf16": {"enabled": True}, "gradient_clipping": 1.0})
    d = cfg.to_dict()
    assert d["bf16"]["enabled"] is True
    cfg2 = load_config({k: v for k, v in d.items() if v is not None})
    assert cfg2.gradient_clipping == 1.0
