"""ZeRO-Infinity tier: NVMe optimizer-state swapper — trajectory equivalence
vs the resident optimizer (the reference's gold standard for offload:
tests/unit/runtime/zero/test_zero_offload correctness semantics) + checkpoint
round-trip through swap-file snapshots."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.ops.aio import aio_compatible

pytestmark = [pytest.mark.skipif(not aio_compatible(),
                                 reason="aio extension needs g++"),
              pytest.mark.slow]


def _cfg(tmp_path, nvme: bool, clip=0.0):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 2,
           "steps_per_print": 1000,
           "optimizer": {"type": "adamw",
                         "params": {"lr": 1e-2, "weight_decay": 0.01}},
           "gradient_clipping": clip,
           "zero_optimization": {"stage": 0,
                                 # tiny sub-groups => several swap files
                                 "sub_group_size": 4000}}
    if nvme:
        cfg["zero_optimization"]["offload_optimizer"] = {
            "device": "nvme", "nvme_path": str(tmp_path)}
    return cfg


def _run(tmp_path, nvme, steps=4, clip=0.0):
    model = create_model("tiny")
    engine, *_ = deepspeed_tpu.initialize(model=model,
                                          config=_cfg(tmp_path, nvme, clip))
    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    losses = []
    for i in range(steps):
        ids = jax.random.randint(jax.random.PRNGKey(i), (gas, gb, 16), 0,
                                 model.config.vocab_size)
        losses.append(float(engine.train_batch(batch={"input_ids": ids})))
    final = jax.tree.map(lambda p: np.asarray(jax.device_get(p)),
                         engine.params)
    return losses, final, engine


class TestNVMeOffload:
    def test_trajectory_matches_resident(self, tmp_path):
        l_res, p_res, _ = _run(tmp_path / "a", nvme=False)
        l_nvme, p_nvme, eng = _run(tmp_path / "b", nvme=True)
        assert len(eng._nvme_swapper.groups) > 1  # swap actually partitioned
        np.testing.assert_allclose(l_res, l_nvme, rtol=2e-4)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32), atol=2e-4),
            p_res, p_nvme)

    def test_two_process_partitioned_swap(self, tmp_path):
        """VERDICT r3 #2: multi-process NVMe swap over addressable shards.
        Two jax.distributed CPU processes under ZeRO-2 (grads sharded over
        'data') each swap only their OWN state regions — roughly half the
        bytes — and the trajectory matches a single-process run."""
        import re
        import subprocess
        import sys

        worker = tmp_path / "worker.py"
        worker.write_text(f"""
import sys
idx = int(sys.argv[1])
import jax
jax.distributed.initialize("localhost:12991", num_processes=2,
                           process_id=idx)
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models import create_model

model = create_model("tiny")
cfg = {{"train_micro_batch_size_per_gpu": 1,
       "gradient_accumulation_steps": 1, "steps_per_print": 1000,
       "optimizer": {{"type": "adamw",
                     "params": {{"lr": 1e-2, "weight_decay": 0.01}}}},
       "zero_optimization": {{"stage": 2, "sub_group_size": 4000,
           "offload_optimizer": {{"device": "nvme",
                                  "nvme_path": {str(tmp_path)!r}}}}}}}
engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
sw = engine._nvme_swapper
local = sum(sw._group_size(i) for i in range(len(sw.groups)))
total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(engine.params))
losses = []
for i in range(3):
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(i), (1, 8, 16),
                                        0, model.config.vocab_size))
    local_ids = ids[:, 4 * idx:4 * idx + 4]
    losses.append(float(engine.train_batch(batch={{"input_ids": local_ids}})))
print("MP-NVME", idx, local, total, losses, flush=True)
""")
        import os
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    "PALLAS_AXON_POOL_IPS": "",
                    "PYTHONPATH": os.getcwd()})
        procs = [subprocess.Popen([sys.executable, str(worker), str(i)],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
                 for i in range(2)]
        outs = [p.communicate(timeout=600)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs[0] + outs[1]
        results = {}
        for out in outs:
            m = re.search(r"MP-NVME (\d) (\d+) (\d+) \[([^\]]*)\]", out)
            assert m, out
            results[int(m.group(1))] = (
                int(m.group(2)), int(m.group(3)),
                [float(x) for x in m.group(4).split(",")])
        # partitioned: each process swaps a strict subset of the state
        # (sharded leaves split; tiny replicated leaves are duplicated)
        for local, total, _ in results.values():
            assert local < total, (local, total)
        np.testing.assert_allclose(results[0][2], results[1][2], rtol=1e-6)

        # single-process oracle, same global batches
        model = create_model("tiny")
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1, "steps_per_print": 1000,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 1e-2, "weight_decay": 0.01}},
            "zero_optimization": {
                "stage": 2, "sub_group_size": 4000,
                "offload_optimizer": {"device": "nvme",
                                      "nvme_path": str(tmp_path / "o")}}})
        oracle = []
        for i in range(3):
            ids = jax.random.randint(jax.random.PRNGKey(i), (1, 8, 16), 0,
                                     model.config.vocab_size)
            oracle.append(float(engine.train_batch(batch={"input_ids": ids})))
        np.testing.assert_allclose(results[0][2], oracle, rtol=2e-4)

    def test_trajectory_with_clipping(self, tmp_path):
        l_res, p_res, _ = _run(tmp_path / "a", nvme=False, clip=0.1)
        l_nvme, p_nvme, _ = _run(tmp_path / "b", nvme=True, clip=0.1)
        np.testing.assert_allclose(l_res, l_nvme, rtol=2e-4)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32), atol=2e-4),
            p_res, p_nvme)

    def test_checkpoint_roundtrip(self, tmp_path):
        model = create_model("tiny")
        cfg = _cfg(tmp_path / "swap", nvme=True)
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        gas, gb = 2, engine.train_batch_size() // 2
        ids = jax.random.randint(jax.random.PRNGKey(0), (gas, gb, 16), 0,
                                 model.config.vocab_size)
        engine.train_batch(batch={"input_ids": ids})
        ckpt = str(tmp_path / "ckpt")
        engine.save_checkpoint(ckpt)
        assert os.path.isdir(os.path.join(
            ckpt, f"global_step{engine.global_steps}", "nvme_state_p0"))
        # continue training the original
        engine.train_batch(batch={"input_ids": ids})
        ref_params = jax.tree.map(np.asarray, engine.params)

        # fresh engine, restore, take the same step
        from deepspeed_tpu.parallel import mesh as mesh_mod

        mesh_mod.reset_mesh()
        model2 = create_model("tiny")
        engine2, *_ = deepspeed_tpu.initialize(
            model=model2, config=_cfg(tmp_path / "swap2", nvme=True))
        engine2.load_checkpoint(ckpt)
        assert engine2.global_steps == 1
        engine2.train_batch(batch={"input_ids": ids})
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=2e-4),
            ref_params, engine2.params)

    def test_state_arrays_roundtrip(self, tmp_path):
        _, _, eng = _run(tmp_path, nvme=True, steps=2)
        sw = eng._nvme_swapper
        state = sw.state_arrays()
        assert set(state) == {"master", "exp_avg", "exp_avg_sq"}
        n_leaves = len(jax.tree.leaves(eng.params))
        assert len(state["master"]) == n_leaves
        sw.load_state_arrays(state, step=sw.step_count)
        state2 = sw.state_arrays()
        for kind in state:
            for key in state[kind]:
                np.testing.assert_array_equal(state[kind][key],
                                              state2[kind][key])

    def test_rejects_non_adam(self, tmp_path):
        model = create_model("tiny")
        cfg = _cfg(tmp_path, nvme=True)
        cfg["optimizer"] = {"type": "sgd", "params": {"lr": 1e-2}}
        with pytest.raises(ValueError, match="Adam family"):
            deepspeed_tpu.initialize(model=model, config=cfg)
