"""Loss-scaler tests — analog of reference
tests/unit/runtime/half_precision/test_dynamic_loss_scale.py."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.loss_scaler import (DynamicLossScaler, LossScaler,
                                               create_loss_scaler, has_overflow)


def test_static_scaler():
    s = LossScaler(128.0)
    st = s.init()
    assert float(st.scale) == 128.0
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 128.0  # static never changes


def test_overflow_detection():
    assert not bool(has_overflow({"a": jnp.ones(3)}))
    assert bool(has_overflow({"a": jnp.array([1.0, jnp.inf])}))
    assert bool(has_overflow({"a": jnp.ones(2), "b": jnp.array([jnp.nan])}))


def test_dynamic_backoff_and_growth():
    s = DynamicLossScaler(init_scale=2.0 ** 8, scale_factor=2.0, scale_window=3,
                          min_scale=1.0, delayed_shift=1)
    st = s.init()
    # overflow → halve
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 2.0 ** 7
    assert int(st.good_steps) == 0
    # 3 clean steps → double
    for _ in range(3):
        st = s.update(st, jnp.asarray(False))
    assert float(st.scale) == 2.0 ** 8


def test_dynamic_min_scale():
    s = DynamicLossScaler(init_scale=2.0, scale_factor=2.0, min_scale=1.0)
    st = s.init()
    for _ in range(5):
        st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 1.0


def test_hysteresis():
    """delayed_shift=2: first overflow consumes hysteresis, second backs off
    (reference DynamicLossScaler delayed_shift semantics)."""
    s = DynamicLossScaler(init_scale=2.0 ** 8, delayed_shift=2)
    st = s.init()
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 2.0 ** 8  # tolerated
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 2.0 ** 7  # now backs off


def test_scale_unscale_roundtrip():
    s = DynamicLossScaler(init_scale=1024.0)
    st = s.init()
    loss = jnp.asarray(2.0)
    assert float(s.scale_loss(loss, st)) == 2048.0
    grads = {"w": jnp.full((3,), 1024.0)}
    un = s.unscale_grads(grads, st)
    np.testing.assert_allclose(np.asarray(un["w"]), 1.0)


def test_create_from_config():
    s = create_loss_scaler(fp16_enabled=False)
    assert isinstance(s, LossScaler) and s.cur_scale == 1.0
    s = create_loss_scaler(fp16_enabled=True, dynamic=True, initial_scale_power=10)
    assert isinstance(s, DynamicLossScaler)
    assert s.init_scale == 1024.0
    s = create_loss_scaler(fp16_enabled=True, dynamic=False, static_scale=64.0)
    assert float(s.init().scale) == 64.0
