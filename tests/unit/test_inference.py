"""Inference stack tests — analog of reference tests/unit/inference/
test_inference.py (HF model × dtype matrix) and the KV-cache/generate
correctness checks the CUDA kernels get via ds_attention tests.

Key oracles:
  * generate() greedy == naive no-cache argmax loop (KV-cache correctness)
  * our forward == HuggingFace torch forward after state-dict import
    (the injection-policy/auto-TP parity check, per family)
  * tp=2 == tp=1 generation on the virtual mesh
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.models import create_model


def naive_greedy(model, params, prompt, n_new):
    """Oracle: recompute the full forward for every generated token."""
    ids = jnp.asarray(prompt, jnp.int32)
    out = []
    for _ in range(n_new):
        logits, _ = model.apply(params, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out.append(nxt)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.parametrize("preset", ["tiny", "tiny-llama", "tiny-bloom",
                                    "tiny-opt", "tiny-gptj", "tiny-gptneox"])
@pytest.mark.slow
def test_cache_logits_match_full_forward(preset):
    """Teacher-forced KV-cache correctness: prefill + per-token decode steps
    must reproduce the full-forward logits at every position."""
    from deepspeed_tpu.inference import kv_cache
    from deepspeed_tpu.models.transformer import forward

    engine = init_inference(preset, dtype=jnp.float32, max_out_tokens=128)
    cfg = engine.model.config
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 250, size=(2, 20)), jnp.int32)
    S_prompt = 12

    full, _, _ = forward(engine.params, ids, cfg)
    cache = kv_cache.init_cache(cfg, 2, 128, jnp.float32)
    valid = jnp.zeros((2, 128), jnp.int32).at[:, :S_prompt].set(1)
    lg, cache, _ = forward(engine.params, ids[:, :S_prompt], cfg,
                           attention_mask=valid, cache=cache, start_pos=0)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, :S_prompt]),
                               atol=1e-4, rtol=1e-4)
    for pos in range(S_prompt, 20):
        valid = valid.at[:, pos].set(1)
        lg, cache, _ = forward(engine.params, ids[:, pos:pos + 1], cfg,
                               attention_mask=valid, cache=cache,
                               start_pos=pos)
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, pos]),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"decode step at pos {pos}")


@pytest.mark.slow
def test_generate_matches_naive_loop():
    """Greedy generate == naive full-recompute loop. Token mismatches are
    accepted only at genuine fp32 near-ties (top-2 gap < 1e-4), after which
    the prefixes legitimately diverge and comparison stops."""
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 250, size=(2, 12))
    n_new = 8
    got = np.asarray(engine.generate(prompt, max_new_tokens=n_new))
    for b in range(prompt.shape[0]):
        ids = jnp.asarray(prompt[b:b + 1], jnp.int32)
        for i in range(n_new):
            logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
            row = np.asarray(logits[0, -1], np.float32)
            best = int(row.argmax())
            if got[b, i] != best:
                top2 = np.sort(row)[-2:]
                assert top2[1] - row[got[b, i]] < 1e-4, (
                    f"batch {b} step {i}: got {got[b, i]} want {best} "
                    f"(gap {top2[1] - row[got[b, i]]:.2e} — not a tie)")
                break
            ids = jnp.concatenate([ids, jnp.asarray([[best]], jnp.int32)], 1)


@pytest.mark.slow
def test_generate_positions_not_bucket_shifted():
    """Decoded tokens must take positions from the TRUE prompt length, not
    the compile bucket (regression: prompt 12 bucketed to 64 gave the first
    generated token position 64). Amplified position embeddings make any
    offset flip the argmax."""
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    engine.params = dict(engine.params)
    engine.params["pos"] = engine.params["pos"] * 50.0
    prompt = np.random.RandomState(7).randint(0, 250, (1, 12))
    got = np.asarray(engine.generate(prompt, max_new_tokens=5))
    ids = jnp.asarray(prompt, jnp.int32)
    want = []
    for _ in range(5):
        logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        want.append(int(nxt[0]))
        ids = jnp.concatenate([ids, nxt[:, None]], 1)
    np.testing.assert_array_equal(got[0], want)


def test_generate_ragged_prompts_right_padded():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    rng = np.random.RandomState(1)
    full = rng.randint(0, 250, size=(2, 10))
    mask = np.ones((2, 10), np.int32)
    mask[1, 6:] = 0  # second prompt is 6 tokens long
    got = engine.generate(full, attention_mask=mask, max_new_tokens=4)
    # row 1 must match generating from the unpadded 6-token prompt, provided
    # positions agree: re-run with the short prompt right-padded the same way
    short = engine.generate(full[1:2, :10] * mask[1:2],
                            attention_mask=mask[1:2], max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(got[1:2]), np.asarray(short))


@pytest.mark.parametrize("preset", ["tiny", "tiny-llama", "tiny-bloom"])
# learned + rope + alibi (per-row key positions in the bias)
def test_generate_ragged_matches_solo_prompt(preset):
    """Exact ragged positions: a short row in a ragged batch must generate
    the SAME tokens as serving that prompt alone at its true width — decode
    positions are per-row (len_b, len_b+1, ...), not the padded array
    width."""
    engine = init_inference(preset, dtype=jnp.float32, max_out_tokens=128)
    rng = np.random.RandomState(3)
    full = rng.randint(0, 250, size=(2, 10)).astype(np.int64)
    mask = np.ones((2, 10), np.int32)
    mask[1, 6:] = 0
    full[1, 6:] = 0
    got = np.asarray(engine.generate(full, attention_mask=mask,
                                     max_new_tokens=4))
    solo = np.asarray(engine.generate(full[1:2, :6], max_new_tokens=4))
    np.testing.assert_array_equal(got[1:2], solo)


def test_arena_allocated_once_and_reused(monkeypatch):
    """The KV arena is engine-owned: repeated generate() calls at the same
    batch size must not re-allocate it (reference InferenceContext
    workspace discipline)."""
    from deepspeed_tpu.inference import kv_cache

    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    calls = []
    orig = kv_cache.init_cache

    def counting(*a, **kw):
        calls.append(a)
        return orig(*a, **kw)

    monkeypatch.setattr(kv_cache, "init_cache", counting)
    prompt = np.arange(8)[None]
    a = np.asarray(engine.generate(prompt, max_new_tokens=4))
    b = np.asarray(engine.generate(prompt, max_new_tokens=4))
    c = np.asarray(engine.generate(prompt, max_new_tokens=4))
    assert len(calls) == 1, f"arena allocated {len(calls)} times"
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(b, c)
    assert 1 in engine._arena


def test_generate_eos_stops():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    prompt = np.arange(8)[None]
    toks = engine.generate(prompt, max_new_tokens=12, eos_token_id=None)
    # pick the first generated token as a fake EOS — regenerate with it
    eos = int(np.asarray(toks)[0, 0])
    toks2 = np.asarray(engine.generate(prompt, max_new_tokens=12,
                                       eos_token_id=eos))
    hit = np.where(toks2[0] == eos)[0]
    assert hit.size > 0
    # after the first EOS everything is EOS
    assert (toks2[0, hit[0]:] == eos).all()


@pytest.mark.slow
def test_generate_temperature_reproducible():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    prompt = np.arange(8)[None]
    a = engine.generate(prompt, max_new_tokens=6, temperature=0.8, top_k=20, seed=3)
    b = engine.generate(prompt, max_new_tokens=6, temperature=0.8, top_k=20, seed=3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).shape == (1, 6)


@pytest.mark.slow
def test_ttft_reported():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    out, ttft = engine.generate(np.arange(8)[None], max_new_tokens=2,
                                return_ttft=True)
    assert ttft > 0.0
    assert np.asarray(out).shape == (1, 2)


@pytest.mark.slow
def test_tensor_parallel_generation_matches(devices8):
    prompt = np.arange(10)[None]
    e1 = init_inference("tiny-llama", dtype=jnp.float32, max_out_tokens=128)
    t1 = e1.generate(prompt, max_new_tokens=6)
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    e2 = init_inference("tiny-llama", dtype=jnp.float32, max_out_tokens=128,
                        tensor_parallel=2)
    # same weights: re-shard e1's params onto e2's mesh
    e2.params = jax.tree.map(
        lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
        e2.param_shardings)
    t2 = e2.generate(prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))


@pytest.mark.parametrize("preset", ["tiny", "tiny-llama"])
@pytest.mark.slow
def test_kernel_prefill_decode_branches(preset, monkeypatch):
    """Drive the Pallas prefill/decode cache branches on CPU via interpret
    mode (on TPU they are the default; CPU normally takes the jnp path)."""
    import deepspeed_tpu.models.transformer as T
    from deepspeed_tpu.inference import kv_cache
    from deepspeed_tpu.models.transformer import forward

    engine = init_inference(preset, dtype=jnp.float32, max_out_tokens=128)
    cfg = engine.model.config
    full_ref, _, _ = forward(engine.params,
                             jnp.asarray(np.arange(20)[None] % 250, jnp.int32),
                             cfg)

    import importlib

    fa = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    da = importlib.import_module("deepspeed_tpu.ops.decode_attention")
    monkeypatch.setattr(T, "_kernels_active", lambda: True)
    monkeypatch.setattr(T, "default_attention_impl",
                        lambda: fa.make_attention_impl(interpret=True))
    monkeypatch.setattr(da, "decode_attention",
                        lambda *a, **k: _DA_ORIG(*a, **{**k, "interpret": True}))
    nrm = importlib.import_module("deepspeed_tpu.ops.normalization")
    monkeypatch.setattr(nrm, "fused_layer_norm",
                        lambda x, s, b, eps=1e-5, rms=False: _FLN_ORIG(
                            x, s, b, eps, rms, True))

    ids = jnp.asarray(np.arange(20)[None] % 250, jnp.int32)
    cache = kv_cache.init_cache(cfg, 1, 128, jnp.float32)
    valid = jnp.zeros((1, 128), jnp.int32).at[:, :12].set(1)
    lg, cache, _ = forward(engine.params, ids[:, :12], cfg,
                           attention_mask=valid, cache=cache, start_pos=0)
    np.testing.assert_allclose(np.asarray(lg[:, :12]),
                               np.asarray(full_ref[:, :12]),
                               atol=1e-3, rtol=1e-3)
    for pos in range(12, 16):
        valid = valid.at[:, pos].set(1)
        lg, cache, _ = forward(engine.params, ids[:, pos:pos + 1], cfg,
                               attention_mask=valid, cache=cache,
                               start_pos=pos)
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full_ref[:, pos]),
                                   atol=1e-3, rtol=1e-3,
                                   err_msg=f"kernel decode at pos {pos}")


# original kernel entries, captured before any monkeypatching
from deepspeed_tpu.ops.decode_attention import decode_attention as _DA_ORIG  # noqa: E402
from deepspeed_tpu.ops.normalization import fused_layer_norm as _FLN_ORIG  # noqa: E402


# ---------------------------------------------------------------------------
# HF parity (the reference's per-architecture container/policy correctness)
# ---------------------------------------------------------------------------


def _hf_logits(hf_model, ids):
    import torch

    with torch.no_grad():
        return hf_model(torch.tensor(ids)).logits.float().numpy()


def _ours_logits(preset, hf_model, ids):
    engine = init_inference(preset, dtype=jnp.float32, max_out_tokens=128,
                            hf_model=hf_model)
    return np.asarray(engine.forward(ids))


@pytest.mark.slow
def test_hf_import_gpt2():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(10)
    cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()
    ids = np.random.RandomState(0).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


def test_hf_import_llama():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(11)
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, attention_dropout=0.0)
    hf = transformers.LlamaForCausalLM(cfg).eval()
    ids = np.random.RandomState(1).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-llama", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


def test_hf_import_opt():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(12)
    cfg = transformers.OPTConfig(
        vocab_size=256, hidden_size=64, ffn_dim=256, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=128,
        word_embed_proj_dim=64, do_layer_norm_before=True, dropout=0.0)
    hf = transformers.OPTForCausalLM(cfg).eval()
    ids = np.random.RandomState(2).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-opt", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_hf_import_gptj():
    """GPT-J: parallel residual + partial INTERLEAVED rotary (converted to
    rotate-half at import) + biased untied head."""
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(14)
    cfg = transformers.GPTJConfig(
        vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        rotary_dim=8, attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    hf = transformers.GPTJForCausalLM(cfg).eval()
    ids = np.random.RandomState(4).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-gptj", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_hf_import_gptneo():
    """GPT-Neo: alternating global/LOCAL (sliding-window) attention, and
    UNSCALED attention scores — seq 16 > window 8 so the local mask
    actually binds in this test."""
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(17)
    cfg = transformers.GPTNeoConfig(
        vocab_size=256, max_position_embeddings=128, hidden_size=64,
        num_layers=2, num_heads=4, intermediate_size=256,
        attention_types=[[["global", "local"], 1]], window_size=8,
        attention_dropout=0.0, embed_dropout=0.0, resid_dropout=0.0)
    hf = transformers.GPTNeoForCausalLM(cfg).eval()
    ids = np.random.RandomState(6).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-gptneo", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)
    # generation parity (decode path windows over true positions)
    engine = init_inference("tiny-gptneo", dtype=jnp.float32,
                            max_out_tokens=128, hf_model=hf)
    import torch

    with torch.no_grad():
        want = hf.generate(torch.tensor(ids[:1, :12]), max_new_tokens=6,
                           do_sample=False).numpy()[:, 12:]
    got = np.asarray(engine.generate(ids[:1, :12], max_new_tokens=6))
    np.testing.assert_array_equal(got[:, :6], want)


@pytest.mark.slow
def test_hf_import_clip_text():
    """CLIP text encoder (the Stable Diffusion text tower the reference's
    clip container injects): pre-LN CAUSAL encoder with quick_gelu.
    Hidden-state parity via the tied-embedding inversion (bert pattern)."""
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(18)
    cfg = transformers.CLIPTextConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=77, attention_dropout=0.0,
        hidden_act="quick_gelu")
    hf = transformers.CLIPTextModel(cfg).eval()
    ids = np.random.RandomState(7).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-clip", hf, ids),
                               _encoder_expected(hf, ids),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_hf_import_gptneox():
    """GPT-NeoX: fused per-head qkv interleave + parallel residual with its
    own post-attention LN + 25% rotate-half rotary."""
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(15)
    cfg = transformers.GPTNeoXConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256, rotary_pct=0.25,
        max_position_embeddings=128, use_parallel_residual=True,
        hidden_dropout=0.0, attention_dropout=0.0)
    hf = transformers.GPTNeoXForCausalLM(cfg).eval()
    ids = np.random.RandomState(5).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-gptneox", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


def _encoder_expected(hf, ids, **kw):
    """HF encoder last_hidden_state mapped through the shared embedding —
    the linear map our tied 'logits' apply, so hidden parity <=> logit
    parity."""
    import torch

    with torch.no_grad():
        hidden = hf(torch.tensor(ids), **kw).last_hidden_state
        E = hf.get_input_embeddings().weight
        return (hidden @ E.T).float().numpy()


@pytest.mark.slow
def test_hf_import_bert():
    """BERT: the NON-CAUSAL post-LN encoder path end to end — bidirectional
    attention, token-type embeddings, LN after each residual, no final
    norm."""
    transformers = pytest.importorskip("transformers")
    torch = __import__("torch")
    torch.manual_seed(16)
    cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=128, type_vocab_size=2, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    hf = transformers.BertModel(cfg).eval()
    ids = np.random.RandomState(6).randint(0, 256, (2, 16))
    ours = _ours_logits("tiny-bert", hf, ids)
    np.testing.assert_allclose(ours, _encoder_expected(hf, ids),
                               atol=2e-3, rtol=2e-3)
    # bidirectionality probe: flipping a LATER token must change EARLIER
    # positions' outputs (a causal model would leave them untouched)
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % 256
    ours2 = _ours_logits("tiny-bert", hf, ids2)
    assert np.abs(ours2[:, 0] - ours[:, 0]).max() > 1e-4
    # token types flow through
    engine = init_inference("tiny-bert", dtype=jnp.float32,
                            max_out_tokens=128, hf_model=hf)
    tti = np.zeros_like(ids)
    tti[:, 8:] = 1
    from deepspeed_tpu.models.transformer import forward as fwd

    got = np.asarray(fwd(engine.params, jnp.asarray(ids), engine.model.config,
                         token_type_ids=jnp.asarray(tti))[0])
    np.testing.assert_allclose(
        got, _encoder_expected(hf, ids, token_type_ids=torch.tensor(tti)),
        atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_hf_import_distilbert():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(17)
    cfg = transformers.DistilBertConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, hidden_dim=256,
        max_position_embeddings=128, dropout=0.0, attention_dropout=0.0,
        activation="gelu", sinusoidal_pos_embds=False)
    hf = transformers.DistilBertModel(cfg).eval()
    ids = np.random.RandomState(7).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-distilbert", hf, ids),
                               _encoder_expected(hf, ids),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_hf_import_bloom():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(13)
    cfg = transformers.BloomConfig(
        vocab_size=256, hidden_size=64, n_layer=2, n_head=4,
        attention_dropout=0.0, hidden_dropout=0.0)
    hf = transformers.BloomForCausalLM(cfg).eval()
    ids = np.random.RandomState(3).randint(0, 256, (2, 16))
    np.testing.assert_allclose(_ours_logits("tiny-bloom", hf, ids),
                               _hf_logits(hf, ids), atol=2e-3, rtol=2e-3)


def test_hf_import_generate_end_to_end():
    transformers = pytest.importorskip("transformers")
    __import__("torch").manual_seed(14)
    cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128,
                            hf_model=hf)
    prompt = np.random.RandomState(4).randint(0, 256, (1, 8))
    ours = np.asarray(engine.generate(prompt, max_new_tokens=6))

    import torch

    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(prompt), max_new_tokens=6,
                             do_sample=False, pad_token_id=0)
    np.testing.assert_array_equal(ours[0], hf_out[0, 8:].numpy())


def test_checkpoint_roundtrip_into_inference(tmp_path):
    """save_16bit_model output loads into init_inference (reference
    checkpoint-sharded load path, test_checkpoint_sharding.py analog)."""
    model = create_model("tiny", dtype=jnp.float32)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0}})
    path = engine.save_16bit_model(str(tmp_path), "weights.npz")
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    inf = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128,
                         checkpoint=path)
    ids = np.arange(8)[None]
    got = np.asarray(inf.forward(ids))
    want = np.asarray(jax.jit(lambda p, b: model.apply(p, b)[0])(
        engine.params, {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_top_p_restricts_support():
    from deepspeed_tpu.inference.engine import _sample

    # peaked distribution: token 0 has ~92% mass; top_p=0.5 must always pick it
    logits = jnp.asarray([[5.0, 2.0, 1.0, 0.0]])
    picks = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 0.5)[0])
             for i in range(20)}
    assert picks == {0}
    # near-flat top-3: the nucleus must contain MORE than the argmax
    # (regression: a max-instead-of-min cutoff made any top_p<1 greedy)
    logits = jnp.asarray([[2.0, 1.9, 1.8, -5.0]])
    picks = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 0.95)[0])
             for i in range(200)}
    assert picks == {0, 1, 2}, picks
    # top_p=1.0 with high temperature samples beyond token 0
    picks = {int(_sample(logits, jax.random.PRNGKey(i), 5.0, 0, 1.0)[0])
             for i in range(50)}
    assert len(picks) > 1


@pytest.mark.slow
def test_generate_top_p_runs():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)
    out = engine.generate(np.arange(8)[None], max_new_tokens=5,
                          temperature=0.8, top_p=0.9, seed=1)
    assert np.asarray(out).shape == (1, 5)


def test_top_p_zero_is_greedy():
    from deepspeed_tpu.inference.engine import _sample

    logits = jnp.asarray([[5.0, 2.0, 1.0, 0.0]])
    picks = {int(_sample(logits, jax.random.PRNGKey(i), 5.0, 0, 0.0)[0])
             for i in range(20)}
    assert picks == {0}


class TestMoEInference:
    """MoE expert-parallel inference (reference DeepSpeedMoEInference,
    ops/transformer/inference/moe_inference.py:160, and the ep groups built
    in inference/engine.py:274): gate+dispatch run inside prefill/decode,
    expert banks shard over the mesh 'expert' axis, and cache-mode routing
    is exact (no capacity drops, no RTS)."""

    def test_forward_matches_training_model(self):
        """Parity: InferenceEngine.forward == the training model's apply on
        a moe-tiny (same cache=None code path, same routing)."""
        engine = init_inference("moe-tiny", dtype=jnp.float32,
                                max_out_tokens=128)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 250, (2, 16)),
                          jnp.int32)
        got = engine.forward(ids)
        want, _ = engine.model.apply(engine.params, {"input_ids": ids})
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("overrides", [
        {},                                           # top-2 default
        {"moe_top_k": 1},                             # switch-style top-1
        {"moe_use_residual": True},                   # PR-MoE
    ])
    def test_cache_logits_match_full_forward(self, overrides):
        """Teacher-forced KV-cache correctness on an MoE model: prefill +
        decode steps reproduce full-forward logits at every position.
        moe_drop_tokens=False so the no-cache oracle routes exactly too."""
        from deepspeed_tpu.inference import kv_cache
        from deepspeed_tpu.models.transformer import forward

        engine = init_inference("moe-tiny", dtype=jnp.float32,
                                max_out_tokens=128, moe_drop_tokens=False,
                                **overrides)
        cfg = engine.model.config
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 250, (2, 18)),
                          jnp.int32)
        S_prompt = 10
        full, _, _ = forward(engine.params, ids, cfg)
        cache = kv_cache.init_cache(cfg, 2, 128, jnp.float32)
        valid = jnp.zeros((2, 128), jnp.int32).at[:, :S_prompt].set(1)
        lg, cache, _ = forward(engine.params, ids[:, :S_prompt], cfg,
                               attention_mask=valid, cache=cache, start_pos=0)
        np.testing.assert_allclose(np.asarray(lg),
                                   np.asarray(full[:, :S_prompt]),
                                   atol=1e-4, rtol=1e-4)
        for pos in range(S_prompt, 18):
            valid = valid.at[:, pos].set(1)
            lg, cache, _ = forward(engine.params, ids[:, pos:pos + 1], cfg,
                                   attention_mask=valid, cache=cache,
                                   start_pos=pos)
            np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                       np.asarray(full[:, pos]),
                                       atol=1e-4, rtol=1e-4,
                                       err_msg=f"decode step at pos {pos}")

    def test_prefill_nodrop_even_when_training_drops(self):
        """Cache-mode routing must ignore the model's training-time capacity
        limit: an over-capacity prompt token still gets its expert output
        (full forward with drops != prefill without — they must differ on a
        config where drops actually occur, and prefill must equal the
        no-drop oracle)."""
        import dataclasses

        from deepspeed_tpu.inference import kv_cache
        from deepspeed_tpu.models.transformer import forward

        engine = init_inference("moe-tiny", dtype=jnp.float32,
                                max_out_tokens=128,
                                moe_capacity_factor=0.25, moe_min_capacity=1)
        cfg = engine.model.config
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 250, (2, 32)),
                          jnp.int32)
        cache = kv_cache.init_cache(cfg, 2, 128, jnp.float32)
        valid = jnp.zeros((2, 128), jnp.int32).at[:, :32].set(1)
        prefill, _, _ = forward(engine.params, ids, cfg,
                                attention_mask=valid, cache=cache,
                                start_pos=0)
        nodrop_cfg = dataclasses.replace(cfg, moe_drop_tokens=False)
        oracle, _, _ = forward(engine.params, ids, nodrop_cfg)
        np.testing.assert_allclose(np.asarray(prefill), np.asarray(oracle),
                                   atol=1e-4, rtol=1e-4)
        dropped, _, _ = forward(engine.params, ids, cfg)   # training path
        assert np.abs(np.asarray(prefill) - np.asarray(dropped)).max() > 1e-3

    @pytest.mark.slow
    def test_generate_greedy_matches_naive(self):
        engine = init_inference("moe-tiny", dtype=jnp.float32,
                                max_out_tokens=128, moe_drop_tokens=False)
        prompt = np.random.RandomState(3).randint(0, 250, (2, 10))
        got = np.asarray(engine.generate(prompt, max_new_tokens=6))
        want = np.asarray(naive_greedy(engine.model, engine.params,
                                       prompt, 6))
        np.testing.assert_array_equal(got, want)

    def test_ep2_generation_matches_single(self, devices8):
        """Expert-parallel generate == single-device generate, and the
        expert banks really shard over the 'expert' axis."""
        from deepspeed_tpu.parallel import mesh as mesh_mod

        prompt = np.arange(10)[None] % 250
        e1 = init_inference("moe-tiny", dtype=jnp.float32, max_out_tokens=128)
        t1 = e1.generate(prompt, max_new_tokens=6)
        mesh_mod.reset_mesh()
        e2 = init_inference("moe-tiny", dtype=jnp.float32, max_out_tokens=128,
                            expert_parallel=2)
        spec = e2.param_shardings["layers"]["mlp"]["w_up"].spec
        assert mesh_mod.EXPERT_AXIS in spec, spec
        e2.params = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
            e2.param_shardings)
        t2 = e2.generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))

    @pytest.mark.slow
    def test_ep2_tp2_generation_matches_single(self, devices8):
        """ep=2 x tp=2 over 4 devices — the MoE analog of auto-TP."""
        from deepspeed_tpu.parallel import mesh as mesh_mod

        prompt = np.arange(12)[None] % 250
        e1 = init_inference("moe-tiny", dtype=jnp.float32, max_out_tokens=128)
        t1 = e1.generate(prompt, max_new_tokens=5)
        mesh_mod.reset_mesh()
        e2 = init_inference("moe-tiny", dtype=jnp.float32, max_out_tokens=128,
                            expert_parallel=2, tensor_parallel=2)
        e2.params = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
            e2.param_shardings)
        t2 = e2.generate(prompt, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))

    def test_ep_validation(self):
        with pytest.raises(ValueError, match="requires an MoE"):
            init_inference("tiny", expert_parallel=2)
        with pytest.raises(ValueError, match="must divide"):
            init_inference("moe-tiny", expert_parallel=3)

    def test_ep2_int8_expert_banks_sharded(self, devices8):
        """Quantized MoE load must keep the expert banks SHARDED over the
        'expert' axis (regression: tp==1 gating replicated them, losing
        exactly the EP memory scaling)."""
        from deepspeed_tpu.parallel import mesh as mesh_mod

        mesh_mod.reset_mesh()
        e = init_inference("moe-tiny", dtype="int8", max_out_tokens=128,
                           expert_parallel=2, moe_drop_tokens=False)
        w_up = e.params["layers"]["mlp"]["w_up"]
        assert "expert" in getattr(w_up.sharding, "spec", ())
        # really partitioned: each device holds half the experts
        shard_elems = w_up.addressable_shards[0].data.size
        assert shard_elems == w_up.size // 2
        out = e.generate(np.arange(8)[None] % 250, max_new_tokens=3)
        assert np.asarray(out).shape == (1, 3)

    @pytest.mark.slow
    def test_moe_composes_with_int8_weights(self):
        """MoE + weight-only int8: dense projections quantize, expert banks
        stay dense (quantize_model_weights contract) and generation stays
        self-consistent."""
        e = init_inference("moe-tiny", dtype="int8", max_out_tokens=128,
                           moe_drop_tokens=False)
        # expert banks dense, attention projections quantized
        l = e.params["layers"]
        assert isinstance(l["attn"]["wq"], dict) and "q8" in l["attn"]["wq"]
        assert not isinstance(l["mlp"]["w_up"], dict)
        prompt = np.random.RandomState(5).randint(0, 250, (1, 10))
        out = np.asarray(e.generate(prompt, max_new_tokens=5))
        # greedy self-consistency against the engine's own full forward
        ids = jnp.asarray(prompt, jnp.int32)
        for i in range(3):
            logits = e.forward(ids)
            nxt = int(jnp.argmax(logits[0, -1].astype(jnp.float32)))
            assert nxt == out[0, i]
            ids = jnp.concatenate([ids, jnp.asarray([[nxt]], jnp.int32)], 1)


class TestW8A8:
    """dtype='w8a8': int8 weights + dynamic int8 activation quantization on
    decode-shaped GEMMs (s8xs8 MXU). Storage identical to int8 weight-only;
    only the decode compute path differs."""

    def test_config_normalisation_and_validation(self):
        from deepspeed_tpu.inference.engine import InferenceConfig

        cfg = InferenceConfig(dtype="w8a8")
        assert cfg.quantize_bits == 8 and cfg.quantize_activations
        assert cfg.dtype == jnp.bfloat16
        cfg4 = InferenceConfig(dtype="w4a8")
        assert cfg4.quantize_bits == 4 and cfg4.quantize_activations
        with pytest.raises(ValueError, match="W8A8/W4A8"):
            InferenceConfig(dtype="bf16", quantize_activations=True)

    @pytest.mark.slow
    def test_generate_engine_path(self):
        """Same weights served w8a8 vs int8 weight-only through the engine.
        On CPU the s8 kernel gate never engages (kernel numerics are pinned
        in tests/kernels TestInt8A8Matmul), so the two engines must produce
        IDENTICAL tokens here — this checks the engine plumbing (config
        threading, per-engine isolation), not the kernel."""
        e_int8 = init_inference("tiny", dtype="int8", max_out_tokens=128)
        e_a8 = init_inference("tiny", dtype="w8a8", max_out_tokens=128)
        assert e_a8.model.config.a8_decode is True
        assert e_int8.model.config.a8_decode is False   # per-engine config
        e_a8.params = e_int8.params
        prompt = np.random.RandomState(0).randint(0, 250, (1, 12))
        out8 = np.asarray(e_int8.generate(prompt, max_new_tokens=4))
        outa = np.asarray(e_a8.generate(prompt, max_new_tokens=4))
        np.testing.assert_array_equal(out8, outa)

    def test_w8a8_tp_rejected(self, devices8):
        with pytest.raises(NotImplementedError, match="W8A8"):
            init_inference("tiny-llama", dtype="w8a8", tensor_parallel=2)


@pytest.mark.slow
class TestInt8WeightOnly:
    """Weight-only quantized inference (reference init_inference dtype=int8
    kernel-injection mode): storage halves, logits stay close, generate is
    self-consistent (greedy == its own full-forward argmax)."""

    def test_logits_close_and_storage_halved(self):
        from deepspeed_tpu.models.core import tree_bytes

        e16 = init_inference("tiny", dtype=jnp.bfloat16, max_out_tokens=128)
        e8 = init_inference("tiny", dtype="int8", max_out_tokens=128)
        assert e8.config.quantize_bits == 8
        # same underlying weights for a fair numeric comparison
        from deepspeed_tpu.models.transformer import quantize_model_weights

        e8.params = jax.jit(quantize_model_weights)(e16.params)

        prompt = np.random.RandomState(0).randint(0, 250, size=(2, 16))
        l16 = np.asarray(e16.forward(prompt), np.float32)
        l8 = np.asarray(e8.forward(prompt), np.float32)
        cos = (l16.ravel() @ l8.ravel()) / (
            np.linalg.norm(l16) * np.linalg.norm(l8))
        assert cos > 0.99, f"cosine {cos}"

        def matmul_bytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree))

        w16 = matmul_bytes(e16.params["layers"]["attn"])
        w8 = matmul_bytes(e8.params["layers"]["attn"])
        assert w8 < 0.62 * w16          # int8 + scales + bf16 biases

    def test_generate_self_consistent(self):
        engine = init_inference("tiny", dtype="int8", max_out_tokens=128)
        prompt = np.random.RandomState(1).randint(0, 250, size=(1, 12))
        got = np.asarray(engine.generate(prompt, max_new_tokens=6))
        ids = jnp.asarray(prompt, jnp.int32)
        for i in range(6):
            logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
            best = int(np.asarray(logits[0, -1], np.float32).argmax())
            assert got[0, i] == best, f"step {i}"
            ids = jnp.concatenate([ids, jnp.asarray([[best]], jnp.int32)], 1)

    @pytest.mark.slow
    def test_int8_tp_matches_single(self, devices8):
        """Quantized auto-TP: q8/scale leaves shard per the dense weight's
        TP rules; tp=2 generation matches tp=1 (same quantized weights)."""
        from deepspeed_tpu.parallel import mesh as mesh_mod

        prompt = np.arange(10)[None]
        e1 = init_inference("tiny-llama", dtype="int8", max_out_tokens=128)
        t1 = np.asarray(e1.generate(prompt, max_new_tokens=6))
        mesh_mod.reset_mesh()
        e2 = init_inference("tiny-llama", dtype="int8", tensor_parallel=2,
                            max_out_tokens=128)
        e2.params = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
            e2._quantized_shardings())
        t2 = np.asarray(e2.generate(prompt, max_new_tokens=6))
        np.testing.assert_array_equal(t1, t2)
        # the packed weight really is sharded over the model axis
        wq = e2.params["layers"]["attn"]["wq"]["q8"]
        assert "model" in str(wq.sharding.spec)


@pytest.mark.slow
class TestInt4WeightOnly:
    """4-bit weight-only inference (reference 4-bit groupwise quantizer
    kernels, csrc/includes/quantization_utils.h:468): storage quarters,
    logits stay close, generate is self-consistent."""

    def test_logits_close_and_storage_quartered(self):
        e16 = init_inference("tiny", dtype=jnp.bfloat16, max_out_tokens=128)
        e4 = init_inference("tiny", dtype="int4", max_out_tokens=128,
                            config={"quantize_groups": 32, "dtype": "int4"})
        assert e4.config.quantize_bits == 4
        from deepspeed_tpu.models.transformer import quantize_model_weights

        e4.params = jax.jit(lambda p: quantize_model_weights(
            p, bits=4, group_size=32))(e16.params)

        prompt = np.random.RandomState(0).randint(0, 250, size=(2, 16))
        l16 = np.asarray(e16.forward(prompt), np.float32)
        l4 = np.asarray(e4.forward(prompt), np.float32)
        cos = (l16.ravel() @ l4.ravel()) / (
            np.linalg.norm(l16) * np.linalg.norm(l4))
        assert cos > 0.97, f"cosine {cos}"

        def matmul_bytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree))

        w16 = matmul_bytes(e16.params["layers"]["attn"])
        w4 = matmul_bytes(e4.params["layers"]["attn"])
        assert w4 < 0.40 * w16          # packed nibbles + scales + biases

    def test_generate_self_consistent(self):
        engine = init_inference("tiny", dtype="int4", max_out_tokens=128)
        prompt = np.random.RandomState(1).randint(0, 250, size=(1, 12))
        got = np.asarray(engine.generate(prompt, max_new_tokens=6))
        ids = jnp.asarray(prompt, jnp.int32)
        for i in range(6):
            logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
            best = int(np.asarray(logits[0, -1], np.float32).argmax())
            assert got[0, i] == best, f"step {i}"
            ids = jnp.concatenate([ids, jnp.asarray([[best]], jnp.int32)], 1)

    @pytest.mark.slow
    def test_int4_tp_matches_single(self, devices8):
        from deepspeed_tpu.parallel import mesh as mesh_mod

        prompt = np.arange(10)[None]
        e1 = init_inference("tiny-llama", dtype="int4", max_out_tokens=128)
        t1 = np.asarray(e1.generate(prompt, max_new_tokens=6))
        mesh_mod.reset_mesh()
        e2 = init_inference("tiny-llama", dtype="int4", tensor_parallel=2,
                            max_out_tokens=128)
        e2.params = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
            e2._quantized_shardings())
        t2 = np.asarray(e2.generate(prompt, max_new_tokens=6))
        np.testing.assert_array_equal(t1, t2)

    def test_groups_require_int4(self):
        from deepspeed_tpu.inference.engine import InferenceConfig

        with pytest.raises(ValueError, match="int4"):
            InferenceConfig(dtype="int8", quantize_groups=64)


def test_tp_world_reads_ambient_mesh(devices8):
    """The quantized-GEMM kernel gate must see the mesh context the engines
    trace under — NOT the module-global mesh the inference engine never sets
    (regression: a global-mesh read returned 1 under tp=2). The probe reads
    the framework's ambient tracker (public API — the deprecated
    pxla.thread_resources read is gone); outside any framework mesh context
    it must fail SAFE by disabling the single-shard kernel route."""
    import numpy as _np
    from jax.sharding import Mesh

    from deepspeed_tpu.models.transformer import _tp_world
    from deepspeed_tpu.parallel import mesh as mesh_mod

    assert _tp_world() > 1  # no ambient mesh: kernel route disabled (safe)
    mesh = Mesh(_np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    with mesh_mod.ambient(mesh):
        assert _tp_world() == 2
    tp1 = Mesh(_np.array(jax.devices()).reshape(8, 1), ("data", "model"))
    with mesh_mod.ambient(tp1):
        assert _tp_world() == 1
    assert _tp_world() > 1
