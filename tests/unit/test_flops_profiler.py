"""Flops profiler tests — analog of reference tests/unit/profiling/
flops_profiler/test_flops_profiler.py (known-model MAC counts) with the
compiled-program cost cross-check XLA gives us for free."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import create_model
from deepspeed_tpu.profiling import (compiled_cost, flops_string,
                                     get_model_profile, number_string,
                                     transformer_breakdown)


def test_param_count_matches_real_model():
    model = create_model("tiny", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, batch_size=2, seq_len=32)
    params = model.init(jax.random.PRNGKey(0))
    real = sum(int(p.size) for p in jax.tree.leaves(params))
    # analytic count excludes tiny bias terms; must agree within 2%
    assert abs(prof.total_params - real) / real < 0.02


def test_flops_close_to_compiled_cost():
    model = create_model("tiny", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, batch_size=2, seq_len=64)

    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 64), jnp.int32)
    compiled = jax.jit(lambda p, b: model.apply(p, b)[0]).lower(
        params, {"input_ids": ids}).compile()
    xla = compiled_cost(compiled)
    if not xla.get("flops"):
        return  # backend without cost analysis — analytic-only
    # same order of magnitude (XLA counts fusions/softmax etc. differently)
    ratio = prof.total_flops / xla["flops"]
    assert 0.3 < ratio < 3.0, (prof.total_flops, xla["flops"])


def test_gpt2_125m_known_flops():
    model = create_model("gpt2-125m", dtype=jnp.float32)
    flops, macs, params = get_model_profile(model, batch_size=1, seq_len=1024)
    assert abs(params - 124.4e6) / 124.4e6 < 0.03
    # ~2*N flops/token for the matmul params + attention + lm_head
    per_token = flops / 1024
    assert 2 * 85e6 < per_token < 2 * 220e6


def test_table_renders():
    model = create_model("tiny-llama", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, 2, 32)
    table = prof.table(step_time=0.1, peak_flops=1e12)
    assert "attention" in table and "mlp" in table and "MFU" in table


def test_format_helpers():
    assert number_string(1.5e9) == "1.50 G"
    assert flops_string(2e12) == "2.00 TFLOPs"


def test_measured_profile_tree():
    """print_model_profile analog (reference profiler.py:239): measured
    per-module latency tree — every layer block appears at depth 2 with a
    positive measured latency and flops; group totals add up."""
    from deepspeed_tpu.profiling import measured_model_profile

    model = create_model("tiny", dtype=jnp.float32, num_layers=3)
    mp = measured_model_profile(model, batch_size=2, seq_len=32,
                                repeats=3, warmup=1)
    names = [m.name for m in mp.modules]
    assert names[0] == "model" and "embedding" in names
    layer_rows = [m for m in mp.modules if m.name.startswith("layer.")]
    assert len(layer_rows) == 3
    assert all(m.depth == 2 and m.latency_s > 0 for m in layer_rows)
    assert all(m.flops > 0 for m in layer_rows)
    root = mp.modules[0]
    parts = [m for m in mp.modules if m.depth == 1]
    assert abs(sum(m.latency_s for m in parts) - root.latency_s) < 1e-9
    table = mp.table()
    assert "layer.2" in table and "% time" in table
    # the get_model_profile(measured=True) path returns flops computed from
    # the XLA-counted segments
    flops, macs, params = get_model_profile(model, 2, 32, measured=True)
    assert flops > 0 and macs == flops / 2 and params > 0


def test_measured_profile_moe_model():
    """The measured tree must also run MoE layer blocks (gate+dispatch in
    the segment program)."""
    from deepspeed_tpu.profiling import measured_model_profile

    model = create_model("moe-tiny", dtype=jnp.float32)
    mp = measured_model_profile(model, batch_size=2, seq_len=32,
                                repeats=2, warmup=1)
    layer_rows = [m for m in mp.modules if m.name.startswith("layer.")]
    assert len(layer_rows) == model.config.num_layers
    assert all(m.latency_s > 0 for m in layer_rows)


def test_engine_print_model_profile(capsys):
    """Engine-level print_model_profile (reference FlopsProfiler hook)."""
    import deepspeed_tpu

    model = create_model("tiny", dtype=jnp.float32, num_layers=2)
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    })
    engine.print_model_profile(batch_size=2, seq_len=32)
    out = capsys.readouterr().out
    assert "measured model profile" in out and "layer.1" in out
