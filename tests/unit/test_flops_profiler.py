"""Flops profiler tests — analog of reference tests/unit/profiling/
flops_profiler/test_flops_profiler.py (known-model MAC counts) with the
compiled-program cost cross-check XLA gives us for free."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import create_model
from deepspeed_tpu.profiling import (compiled_cost, flops_string,
                                     get_model_profile, number_string,
                                     transformer_breakdown)


def test_param_count_matches_real_model():
    model = create_model("tiny", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, batch_size=2, seq_len=32)
    params = model.init(jax.random.PRNGKey(0))
    real = sum(int(p.size) for p in jax.tree.leaves(params))
    # analytic count excludes tiny bias terms; must agree within 2%
    assert abs(prof.total_params - real) / real < 0.02


def test_flops_close_to_compiled_cost():
    model = create_model("tiny", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, batch_size=2, seq_len=64)

    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 64), jnp.int32)
    compiled = jax.jit(lambda p, b: model.apply(p, b)[0]).lower(
        params, {"input_ids": ids}).compile()
    xla = compiled_cost(compiled)
    if not xla.get("flops"):
        return  # backend without cost analysis — analytic-only
    # same order of magnitude (XLA counts fusions/softmax etc. differently)
    ratio = prof.total_flops / xla["flops"]
    assert 0.3 < ratio < 3.0, (prof.total_flops, xla["flops"])


def test_gpt2_125m_known_flops():
    model = create_model("gpt2-125m", dtype=jnp.float32)
    flops, macs, params = get_model_profile(model, batch_size=1, seq_len=1024)
    assert abs(params - 124.4e6) / 124.4e6 < 0.03
    # ~2*N flops/token for the matmul params + attention + lm_head
    per_token = flops / 1024
    assert 2 * 85e6 < per_token < 2 * 220e6


def test_table_renders():
    model = create_model("tiny-llama", dtype=jnp.float32)
    prof = transformer_breakdown(model.config, 2, 32)
    table = prof.table(step_time=0.1, peak_flops=1e12)
    assert "attention" in table and "mlp" in table and "MFU" in table


def test_format_helpers():
    assert number_string(1.5e9) == "1.50 G"
    assert flops_string(2e12) == "2.00 TFLOPs"
