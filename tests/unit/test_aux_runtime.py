"""Aux runtime parity tests: eigenvalue power iteration, progressive layer
drop schedule, tensor-fragment access (reference runtime/eigenvalue.py,
progressive_layer_drop.py, utils/tensor_fragment.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue, hvp
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.utils.tensor_fragment import (safe_get_full_fp32_param,
                                                 safe_get_full_grad,
                                                 safe_get_full_optimizer_state,
                                                 safe_get_full_param)


class TestEigenvalue:
    def test_quadratic_exact(self):
        # loss = 0.5 x^T A x -> top eigenvalue of A
        A = jnp.diag(jnp.asarray([5.0, 2.0, 1.0]))

        def loss(params, batch):
            x = params["x"]
            return 0.5 * x @ A @ x

        ev = Eigenvalue(max_iter=200, tol=1e-5)
        top = ev.compute_eigenvalue(loss, {"x": jnp.ones(3)}, None,
                                    jax.random.PRNGKey(0))
        assert abs(top - 5.0) < 1e-2

    def test_hvp_matches_full_hessian(self):
        def loss(p, _):
            x = p["x"]
            return jnp.sum(x ** 4) + jnp.sum(x[0] * x[1])

        x0 = {"x": jnp.asarray([1.0, 2.0, 3.0])}
        v = {"x": jnp.asarray([1.0, 0.0, 0.0])}
        got = hvp(loss, x0, None, v)["x"]
        H = jax.hessian(lambda x: loss({"x": x}, None))(x0["x"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(H @ v["x"]),
                                   rtol=1e-5)

    def test_block_eigenvalues(self):
        def loss(p, _):
            return 3.0 * jnp.sum(p["a"] ** 2) + 0.5 * jnp.sum(p["b"] ** 2)

        ev = Eigenvalue(max_iter=100)
        out = ev.compute_block_eigenvalues(
            loss, {"a": jnp.ones(4), "b": jnp.ones(4)}, None,
            jax.random.PRNGKey(1))
        assert abs(out["a"] - 6.0) < 0.1      # d2/dx2 of 3x^2
        assert abs(out["b"] - 1.0) < 0.1


class TestProgressiveLayerDrop:
    def test_theta_ramp(self):
        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        assert pld.update_state(0) == 1.0
        mid = pld.update_state(100)
        late = pld.update_state(10_000)
        assert 0.5 < mid < 1.0
        assert abs(late - 0.5) < 1e-3
        # deeper layers drop more
        pld.update_state(10_000)
        assert pld.layer_keep_prob(0, 12) > pld.layer_keep_prob(11, 12)


@pytest.mark.slow
class TestTensorFragment:
    def _engine(self, zero=3):
        model = create_model("tiny", dtype=jnp.bfloat16)
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {"stage": zero},
                    "parallel": {"data_parallel_size": 8}})
        return engine

    def test_full_fp32_param_from_zero3(self):
        engine = self._engine(zero=3)
        w = safe_get_full_fp32_param(engine, "layers/attn/wq")
        assert w.dtype == np.float32
        assert w.shape == tuple(engine.params["layers"]["attn"]["wq"].shape)
        # matches the bf16 param it shadows
        np.testing.assert_allclose(
            w, np.asarray(jax.device_get(
                engine.params["layers"]["attn"]["wq"]), np.float32),
            atol=1e-2)

    def test_optimizer_state_access(self):
        engine = self._engine()
        gb = engine.train_batch_size()
        ids = jax.random.randint(jax.random.PRNGKey(0), (1, gb, 16), 0, 250)
        engine.train_batch(batch={"input_ids": ids})
        mu = safe_get_full_optimizer_state(engine, "layers/attn/wq", "exp_avg")
        assert mu is not None and float(np.abs(mu).sum()) > 0

    def test_grad_access_via_staged_protocol(self):
        engine = self._engine(zero=0)
        assert safe_get_full_grad(engine, "layers/attn/wq") is None
        gb = engine.train_batch_size()
        ids = jax.random.randint(jax.random.PRNGKey(0), (gb, 16), 0, 250)
        engine.forward({"input_ids": ids})
        engine.backward()
        g = safe_get_full_grad(engine, "layers/attn/wq")
        assert g is not None and float(np.abs(g).sum()) > 0
        full = safe_get_full_param(engine, "embed/tokens")
        assert full.shape[0] == 256


@pytest.mark.slow
class TestPLDIntegration:
    def _engine(self, enabled, theta=0.5, gamma=0.0):
        from deepspeed_tpu.parallel import mesh as mesh_mod

        mesh_mod.reset_mesh()
        model = create_model("tiny", dtype=jnp.float32, num_layers=4)
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "steps_per_print": 1000,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "progressive_layer_drop": {"enabled": enabled,
                                          "theta": theta, "gamma": gamma}}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        return engine

    def _batch(self, engine, seed=0):
        gb = engine.train_batch_size()
        ids = jax.random.randint(jax.random.PRNGKey(seed), (1, gb, 16), 0, 250)
        return {"input_ids": ids}

    def test_theta_one_matches_baseline(self):
        # gamma=0, theta=1 -> keep prob 1 everywhere: must equal plain model
        e1 = self._engine(False)
        e2 = self._engine(True, theta=1.0)
        b = self._batch(e1)
        l1 = [float(e1.train_batch(batch=b)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=b)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_dropping_trains_and_differs(self):
        e1 = self._engine(False)
        e2 = self._engine(True, theta=0.3, gamma=10.0)  # theta~0.3 from step 1
        b = self._batch(e1)
        l1 = [float(e1.train_batch(batch=b)) for _ in range(5)]
        l2 = [float(e2.train_batch(batch=b)) for _ in range(5)]
        assert all(np.isfinite(l2))
        assert l2[-1] < l2[0]                  # still learns
        assert not np.allclose(l1, l2, rtol=1e-5)  # drop really happens
