"""Serving-layer tests — continuous batching over the paged KV arena.

Coverage map (the ISSUE-6 checklist):
  * block allocator alloc/free/eviction invariants (no double free,
    occupancy accounting exact);
  * scheduler admission / multi-tenant fairness / deadline ordering with an
    injectable clock (sleep-free, per the hangdetect.py convention);
  * chunked-prefill equivalence — chunked prefill produces a bit-identical
    first token (and continuation) vs whole-prompt prefill on CPU;
  * streaming / cancellation lifecycle + backpressure;
  * jit stability — the decode program compiles exactly once across
    varying batch occupancy (recompile-watchdog counter);
  * the acceptance smoke: 16 concurrent requests, staggered arrivals and
    mixed prompt lengths, every output bit-identical to a sequential
    ``generate()``, decode compiled once, and peak arena blocks strictly
    under the sum of per-request T_max rows (paging actually shares HBM).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import ObservabilityConfig, ServingConfig
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.observability import (configure_observability, get_registry,
                                         reset_session)
from deepspeed_tpu.serving import (BlockAllocator, BlockAllocatorError,
                                   QueueFull, Request, RequestCancelled,
                                   Scheduler, ServingEngine)
from deepspeed_tpu.serving.scheduler import DECODE, PREFILL, QUEUED


class FakeClock:
    """Injectable scheduler clock (sleep-free tests)."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


def serving(tiny_engine, clock=None, **cfg):
    defaults = dict(block_size=16, num_blocks=32, max_seqs=4,
                    max_model_len=128, prefill_chunk=16, max_queue=64)
    defaults.update(cfg)
    return ServingEngine(tiny_engine, ServingConfig(**defaults),
                        **({"clock": clock} if clock else {}))


# ---------------------------------------------------------------------------
# block allocator invariants
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_occupancy_accounting_exact(self):
        a = BlockAllocator(10)
        ids1 = a.alloc(3)
        ids2 = a.alloc(4)
        assert a.blocks_in_use == 7 and a.blocks_free == 3
        assert a.blocks_in_use + a.blocks_free == a.capacity
        a.free(ids1)
        assert a.blocks_in_use == 4 and a.blocks_free == 6
        a.free(ids2)
        assert a.blocks_in_use == 0 and a.blocks_free == 10

    def test_ids_unique_nonzero_in_range(self):
        a = BlockAllocator(8)
        ids = a.alloc(8)
        assert sorted(ids) == list(range(1, 9))  # 0 is the scratch block

    def test_exhaustion_returns_none_no_partial(self):
        a = BlockAllocator(4)
        a.alloc(3)
        before = (a.blocks_in_use, a.blocks_free)
        assert a.alloc(2) is None
        assert (a.blocks_in_use, a.blocks_free) == before  # nothing leaked

    def test_double_free_raises(self):
        a = BlockAllocator(4)
        ids = a.alloc(2)
        a.free(ids)
        with pytest.raises(BlockAllocatorError):
            a.free(ids)

    def test_foreign_block_free_raises(self):
        a = BlockAllocator(4)
        with pytest.raises(BlockAllocatorError):
            a.free([3])

    def test_no_block_handed_out_twice(self):
        a = BlockAllocator(6)
        ids = a.alloc(4)
        a.free(ids[:2])
        more = a.alloc(2)
        held = set(ids[2:]) | set(more)
        assert len(held) == 4  # freed ids may recycle; live ids never collide

    def test_peak_tracking(self):
        a = BlockAllocator(10)
        ids = a.alloc(6)
        a.free(ids)
        a.alloc(2)
        assert a.peak_in_use == 6


# ---------------------------------------------------------------------------
# scheduler policy (device-free, injectable clock)
# ---------------------------------------------------------------------------


def mk_sched(clock, **cfg):
    defaults = dict(block_size=4, num_blocks=16, max_seqs=2,
                    max_model_len=32, prefill_chunk=4, max_queue=8)
    defaults.update(cfg)
    return Scheduler(ServingConfig(**defaults), clock=clock)


def mk_req(rid, n=6, tenant="default", deadline=None, max_new=4):
    return Request(rid=rid, prompt=np.arange(n) % 7, max_new_tokens=max_new,
                   tenant=tenant, deadline_s=deadline)


class TestSchedulerPolicy:
    def test_fcfs_admission_order(self):
        clk = FakeClock()
        s = mk_sched(clk, fairness="fcfs", max_seqs=4)
        for rid in (0, 1, 2):
            s.submit(mk_req(rid))
            clk.advance(1.0)
        s.admit()
        assert list(s.admitted_log) == [0, 1, 2]

    def test_fair_least_service_tenant_first(self):
        clk = FakeClock()
        s = mk_sched(clk, max_seqs=1)
        # tenant A floods first; B arrives later
        for rid in range(3):
            s.submit(mk_req(rid, tenant="A"))
            clk.advance(0.1)
        s.submit(mk_req(10, tenant="B"))
        s.admit()                      # one row: A wins the empty ledger tie
        assert list(s.admitted_log) == [0]
        req = s.running[0]
        s.note_service(req, 100)       # A has now consumed service
        s.finish(req)
        s.admit()                      # B is the least-served tenant
        assert list(s.admitted_log) == [0, 10]

    def test_deadline_edf_within_tenant(self):
        clk = FakeClock()
        s = mk_sched(clk, max_seqs=4)
        s.submit(mk_req(0, deadline=30.0))
        s.submit(mk_req(1, deadline=10.0))
        s.submit(mk_req(2, deadline=20.0))
        s.admit()
        assert list(s.admitted_log) == [1, 2, 0]

    def test_no_deadline_sorts_after_deadlines(self):
        clk = FakeClock()
        s = mk_sched(clk, max_seqs=4)
        s.submit(mk_req(0))                     # no deadline
        s.submit(mk_req(1, deadline=50.0))
        s.admit()
        assert list(s.admitted_log) == [1, 0]

    def test_backpressure_queue_full(self):
        s = mk_sched(FakeClock(), max_queue=2)
        s.submit(mk_req(0))
        s.submit(mk_req(1))
        with pytest.raises(QueueFull):
            s.submit(mk_req(2))

    def test_budget_overflow_rejected(self):
        s = mk_sched(FakeClock())
        with pytest.raises(ValueError):
            s.submit(mk_req(0, n=30, max_new=10))   # 40 > max_model_len=32

    def test_admission_allocates_first_chunk_blocks(self):
        s = mk_sched(FakeClock())
        s.submit(mk_req(0, n=6))
        (req,) = s.admit()
        assert req.state == PREFILL and req.row is not None
        assert len(req.blocks) == 1        # first chunk = 4 tokens = 1 block
        assert s.alloc.blocks_in_use == 1

    def test_admission_never_preempts(self):
        s = mk_sched(FakeClock(), num_blocks=8, max_seqs=2)
        s.submit(mk_req(0, n=6))
        (a,) = s.admit()
        a.state = DECODE
        assert s.ensure_blocks(a, 32)      # a takes the whole pool
        s.submit(mk_req(1, n=6))
        assert s.admit() == []             # pool dry: no eviction for entry
        assert a.state == DECODE and s.queue_depth() == 1

    def test_preemption_lifo_victim_recompute_state(self):
        clk = FakeClock()
        s = mk_sched(clk, num_blocks=8, max_seqs=3)
        s.submit(mk_req(0, n=4)); s.submit(mk_req(1, n=4))
        a, b = s.admit()
        for r in (a, b):
            r.state = DECODE
            r.length = 4
            r.generated = [5, 6]
            r.pending_token = 6
        assert s.ensure_blocks(a, 28)      # 7 blocks for a (+1 b's): 8/8
        assert s.alloc.blocks_free == 0
        # growing a further must evict b (most recently admitted)
        assert s.ensure_blocks(a, 32)
        assert b.state == QUEUED and b.blocks == [] and b.row is None
        assert b.resume and b.pending_token == 6
        # recompute source: prompt + generated-minus-pending
        np.testing.assert_array_equal(
            b.prompt, np.concatenate([np.arange(4) % 7, [5]]))
        assert b.prefill_pos == 0 and b.length == 0
        assert s.preemption_count == 1 and b.preemptions == 1

    def test_ensure_blocks_fails_with_no_victim(self):
        s = mk_sched(FakeClock(), num_blocks=8, max_seqs=1)
        s.submit(mk_req(0, n=4))
        (a,) = s.admit()
        a.state = DECODE
        assert s.ensure_blocks(a, 32)
        assert not s.ensure_blocks(a, 36)  # nothing else to evict

    def test_cancel_releases_row_and_blocks(self):
        s = mk_sched(FakeClock())
        s.submit(mk_req(0)); s.submit(mk_req(1))
        (a, b) = s.admit()
        assert s.cancel(a)
        assert s.alloc.blocks_in_use == len(b.blocks)
        assert a.row is None and not s.cancel(a)   # second cancel no-ops
        s.submit(mk_req(2))
        s.cancel(s.queued[0])                       # cancel while queued
        assert s.queue_depth() == 0

    def test_cancel_queued_with_blocks_frees_them(self):
        """A request evicted mid-iteration can transiently be QUEUED while
        holding blocks — cancelling it must not leak them."""
        s = mk_sched(FakeClock())
        r = mk_req(0)
        s.submit(r)
        r.blocks = s.alloc.alloc(2)
        assert s.cancel(r)
        assert s.alloc.blocks_in_use == 0 and r.blocks == []

    def test_max_new_tokens_must_be_positive(self):
        s = mk_sched(FakeClock())
        with pytest.raises(ValueError):
            s.submit(mk_req(0, max_new=0))
        with pytest.raises(ValueError):
            s.submit(mk_req(1, max_new=-3))

    def test_ttft_tpot_clock_math(self):
        clk = FakeClock()
        s = mk_sched(clk)
        req = mk_req(0, max_new=3)
        s.submit(req)
        clk.advance(2.0)
        req.first_token_s = clk()
        req.generated = [1, 2, 3]
        clk.advance(4.0)
        s.running[0] = req; req.row = 0
        s.finish(req)
        assert req.ttft_s == pytest.approx(2.0)
        assert req.tpot_s == pytest.approx(2.0)    # 4s / (3-1) tokens


# ---------------------------------------------------------------------------
# serving config validation
# ---------------------------------------------------------------------------


class TestServingConfig:
    def test_block_divisibility_enforced(self):
        with pytest.raises(ConfigError):
            ServingConfig(block_size=16, max_model_len=100).validate()

    def test_chunk_block_alignment_enforced(self):
        with pytest.raises(ConfigError):
            ServingConfig(block_size=16, max_model_len=128,
                          prefill_chunk=24).validate()

    def test_pool_must_hold_one_sequence(self):
        with pytest.raises(ConfigError):
            ServingConfig(block_size=16, max_model_len=128,
                          num_blocks=4).validate()

    def test_unknown_fairness_rejected(self):
        with pytest.raises(ConfigError):
            ServingConfig(fairness="lottery").validate()

    def test_full_provisioning_default(self):
        cfg = ServingConfig(block_size=16, max_model_len=128, max_seqs=4)
        cfg.validate()
        assert cfg.pool_blocks() == 4 * 8


# ---------------------------------------------------------------------------
# paged-path satellites
# ---------------------------------------------------------------------------


class TestKvCacheSatellites:
    def test_init_cache_dtype_is_mandatory(self):
        """The dtype-plumbing satellite: no bf16 default to silently
        mismatch an fp32 engine's arena."""
        from deepspeed_tpu.inference import kv_cache
        from deepspeed_tpu.models import create_model

        cfg = create_model("tiny", dtype=jnp.float32).config
        with pytest.raises(TypeError):
            kv_cache.init_cache(cfg, 1, 64)    # noqa — missing dtype
        c = kv_cache.init_cache(cfg, 1, 64, jnp.float32)
        assert c["k"].dtype == jnp.float32

    def test_paged_block_divisibility_asserted(self):
        from deepspeed_tpu.inference import kv_cache

        with pytest.raises(ValueError):
            kv_cache.assert_block_divisible(100, 16)
        assert kv_cache.assert_block_divisible(128, 16) == 8

    def test_engine_bucket_unified_with_block_size(self, tiny_engine):
        """The _bucket satellite: wrapping an engine pins its prompt bucket
        to the serving block size, so generate() buckets no longer imply
        arena blocks the true prompt can't use."""
        srv = serving(tiny_engine, block_size=16)
        assert tiny_engine.config.prompt_bucket == 16
        tiny_engine.generate(np.arange(5)[None], max_new_tokens=2)
        assert (1, 16) in tiny_engine._prefill_cache   # not (1, 64)
        del srv


# ---------------------------------------------------------------------------
# end-to-end serving (tiny model, CPU)
# ---------------------------------------------------------------------------


class TestServingEngine:
    def test_single_request_matches_generate(self, tiny_engine):
        srv = serving(tiny_engine)
        prompt = np.random.RandomState(0).randint(0, 250, (11,))
        got = srv.submit(prompt, max_new_tokens=8).result()
        want = np.asarray(tiny_engine.generate(prompt[None],
                                               max_new_tokens=8))[0]
        np.testing.assert_array_equal(got, want)

    def test_chunked_prefill_bit_identical_first_token(self, tiny_engine):
        """Chunked-prefill equivalence: a 40-token prompt prefilled in
        16-token chunks produces the SAME first token (and continuation) as
        the whole-prompt prefill inside generate()."""
        srv = serving(tiny_engine, prefill_chunk=16)
        prompt = np.random.RandomState(1).randint(0, 250, (40,))
        got = srv.submit(prompt, max_new_tokens=6).result()
        want = np.asarray(tiny_engine.generate(prompt[None],
                                               max_new_tokens=6))[0]
        assert got[0] == want[0]
        np.testing.assert_array_equal(got, want)

    def test_prompt_shorter_than_chunk(self, tiny_engine):
        srv = serving(tiny_engine, prefill_chunk=32)
        prompt = np.random.RandomState(2).randint(0, 250, (5,))
        got = srv.submit(prompt, max_new_tokens=4).result()
        want = np.asarray(tiny_engine.generate(prompt[None],
                                               max_new_tokens=4))[0]
        np.testing.assert_array_equal(got, want)

    def test_eos_stops_early_and_frees(self, tiny_engine):
        srv = serving(tiny_engine)
        prompt = np.arange(8)
        ref = srv.submit(prompt, max_new_tokens=10).result()
        eos = int(ref[2])
        got = srv.submit(prompt, max_new_tokens=10,
                         eos_token_id=eos).result()
        assert got[-1] == eos and len(got) <= 10
        assert srv.alloc.blocks_in_use == 0      # everything released

    def test_temperature_deterministic_per_engine_stream(self, tiny_engine):
        p = np.arange(9)
        a = serving(tiny_engine).submit(p, max_new_tokens=6,
                                        temperature=0.8, top_k=20).result()
        b = serving(tiny_engine).submit(p, max_new_tokens=6,
                                        temperature=0.8, top_k=20).result()
        np.testing.assert_array_equal(a, b)
        assert len(a) == 6

    def test_per_request_seed_schedule_independent(self, tiny_engine):
        """Sampling draws depend on (engine seed, request seed, token
        index) only: the same request re-submitted later on the SAME
        engine (different scheduler iterations) reproduces its stream, and
        a different seed diverges."""
        srv = serving(tiny_engine)
        p = np.arange(9)
        a = srv.submit(p, max_new_tokens=6, temperature=1.0, seed=1).result()
        b = srv.submit(p, max_new_tokens=6, temperature=1.0, seed=2).result()
        c = srv.submit(p, max_new_tokens=6, temperature=1.0, seed=1).result()
        np.testing.assert_array_equal(a, c)
        assert not np.array_equal(a, b)

    def test_finished_handles_pruned(self, tiny_engine):
        """Server-lifetime memory: the engine drops its handle reference
        when a request reaches a terminal state (the client keeps its own)."""
        srv = serving(tiny_engine)
        h = srv.submit(np.arange(5), max_new_tokens=3)
        h.result()
        assert srv._handles == {}
        h2 = srv.submit(np.arange(5), max_new_tokens=30)
        srv.step()
        h2.cancel()
        assert srv._handles == {}

    def test_streaming_yields_incrementally(self, tiny_engine):
        srv = serving(tiny_engine)
        h = srv.submit(np.arange(6), max_new_tokens=5)
        seen = []
        for tok in h.stream():
            seen.append(tok)
            assert len(h.tokens) >= len(seen)
        assert seen == h.tokens and len(seen) == 5
        assert h.state == "finished"

    def test_cancel_mid_flight_releases_and_raises(self, tiny_engine):
        srv = serving(tiny_engine)
        h = srv.submit(np.arange(6), max_new_tokens=50)
        for _ in range(5):
            srv.step()
        assert 0 < len(h.tokens) < 50
        assert h.cancel()
        assert srv.alloc.blocks_in_use == 0 and srv.in_flight() == 0
        with pytest.raises(RequestCancelled):
            h.result()
        assert list(h.stream()) == h.tokens     # stream drains, then ends

    def test_backpressure_raises_queuefull(self, tiny_engine):
        srv = serving(tiny_engine, max_queue=2)
        srv.submit(np.arange(4), max_new_tokens=4)
        srv.submit(np.arange(4), max_new_tokens=4)
        with pytest.raises(QueueFull):
            srv.submit(np.arange(4), max_new_tokens=4)
        srv.run()

    def test_preemption_recompute_bit_identical(self, tiny_engine):
        """Pool far too small for the load: eviction + recompute must not
        change any output (greedy). prefix_cache=False isolates the
        preemption machinery — with sharing on, cache eviction relieves
        most of the pressure before any request is preempted (tested
        separately in TestPrefixSharing)."""
        srv = serving(tiny_engine, num_blocks=10, max_seqs=4,
                      prefix_cache=False)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 250, (rng.randint(20, 60),))
                   for _ in range(6)]
        handles = [srv.submit(p, max_new_tokens=10) for p in prompts]
        srv.run()
        assert srv.sched.preemption_count > 0    # pressure actually happened
        for p, h in zip(prompts, handles):
            want = np.asarray(tiny_engine.generate(p[None],
                                                   max_new_tokens=10))[0]
            np.testing.assert_array_equal(h.result(), want)
        assert srv.alloc.blocks_in_use == 0

    def test_threaded_driver(self, tiny_engine):
        srv = serving(tiny_engine)
        srv.start()
        try:
            h = srv.submit(np.arange(7), max_new_tokens=5)
            got = h.result(timeout_s=60.0)
            assert len(got) == 5
        finally:
            srv.stop()
        want = np.asarray(tiny_engine.generate(np.arange(7)[None],
                                               max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# jit stability + the acceptance smoke
# ---------------------------------------------------------------------------


@pytest.fixture
def obs_session(tmp_path):
    reset_session()
    sess = configure_observability(ObservabilityConfig(
        enabled=True, output_dir=str(tmp_path / "obs"),
        flight_recorder=False))
    yield sess
    reset_session()


class TestServingJit:
    def test_decode_compiles_once_across_occupancy(self, tiny_engine,
                                                   obs_session):
        """Varying batch occupancy, request mix and sampling settings are
        DATA: the decode program must compile exactly once (the CUDA-graph
        discipline as a jit-cache assertion, measured by the recompile
        watchdog's per-span compile counter)."""
        compiles = get_registry().counter("xla/compiles")
        before = compiles.value(where="serving/decode")
        srv = serving(tiny_engine, max_seqs=4)
        rng = np.random.RandomState(4)
        handles = []
        for i in range(7):   # staggered → occupancy 1..4, mixed sampling
            handles.append(srv.submit(
                rng.randint(0, 250, (rng.randint(3, 30),)),
                max_new_tokens=5, temperature=0.0 if i % 2 else 0.5,
                top_k=0 if i % 3 else 7))
            srv.step()
        srv.run()
        [h.result() for h in handles if h.state == "finished"]
        assert compiles.value(where="serving/decode") - before == 1
        steady = get_registry().counter("xla/steady_state_recompiles")
        assert steady.value(where="serving/decode") == 0


class TestServingSmoke:
    def test_sixteen_concurrent_requests_acceptance(self, tiny_engine,
                                                    obs_session, tmp_path):
        """The ISSUE-6 acceptance smoke: >= 16 concurrent requests with
        staggered arrivals and mixed prompt lengths; every output
        bit-identical to a sequential generate(); decode compiled exactly
        once; peak arena blocks allocated strictly under the sum of
        per-request T_max rows; serving metrics flow through the registry
        and render in the report CLI."""
        compiles = get_registry().counter("xla/compiles")
        before = compiles.value(where="serving/decode")
        srv = serving(tiny_engine, block_size=16, num_blocks=64, max_seqs=8,
                      max_model_len=128, prefill_chunk=16, max_queue=64)
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 250, (rng.randint(4, 40),))
                   for _ in range(16)]
        handles = []
        for i, p in enumerate(prompts):          # staggered arrivals
            handles.append(srv.submit(p, max_new_tokens=8,
                                      tenant=f"tenant{i % 3}"))
            if i % 4 == 3:
                srv.step()
        srv.run()

        # 1) bit-identical to sequential offline generation
        for i, (p, h) in enumerate(zip(prompts, handles)):
            want = np.asarray(tiny_engine.generate(p[None],
                                                   max_new_tokens=8))[0]
            np.testing.assert_array_equal(
                h.result(), want, err_msg=f"request {i} diverged")

        # 2) ONE decode program across the whole run
        assert compiles.value(where="serving/decode") - before == 1

        # 3) paging shares HBM: peak blocks strictly under the sum of
        #    per-request full T_max rows the flat arena would reserve
        flat_blocks = len(prompts) * (128 // 16)
        assert 0 < srv.alloc.peak_in_use < flat_blocks

        # 4) metrics flow through the registry ...
        reg = get_registry()
        # the registry is process-global: scope the count to THIS test's
        # tenant labels (earlier serving tests observe under 'default')
        ttft_n = sum(r["count"]
                     for r in reg.histogram("serving/ttft_ms").records()
                     if str(r["labels"].get("tenant", "")
                            ).startswith("tenant"))
        assert ttft_n == 16
        assert reg.gauge("serving/kv_blocks_peak").value() \
            == srv.alloc.peak_in_use
        assert reg.gauge("serving/queue_depth").value() == 0
        srv.close()   # publishes the percentile gauges
        assert reg.gauge("serving/ttft_p50_ms").value() is not None

        # ... and render in the report CLI
        from deepspeed_tpu.observability.report import report

        path = str(tmp_path / "metrics.jsonl")
        reg.dump_jsonl(path)
        out = report([path])
        assert "== serving ==" in out
        assert "ttft_ms" in out and "tokens_per_sec" in out


@pytest.mark.slow
def test_tensor_parallel_serving_matches(devices8):
    """tp=2 serving == tp=1 serving on the virtual mesh (same weights):
    the paged programs partition under GSPMD without changing tokens."""
    import jax

    from deepspeed_tpu.parallel import mesh as mesh_mod

    scfg = dict(block_size=16, num_blocks=24, max_seqs=2,
                max_model_len=64, prefill_chunk=16)
    e1 = init_inference("tiny-llama", dtype=jnp.float32, max_out_tokens=64)
    s1 = ServingEngine(e1, ServingConfig(**scfg))
    p = np.arange(10)
    t1 = s1.submit(p, max_new_tokens=6).result()
    mesh_mod.reset_mesh()
    e2 = init_inference("tiny-llama", dtype=jnp.float32, max_out_tokens=64,
                        tensor_parallel=2)
    e2.params = jax.tree.map(
        lambda x, s: jax.device_put(np.asarray(x), s), e1.params,
        e2.param_shardings)
    s2 = ServingEngine(e2, ServingConfig(**scfg))
    t2 = s2.submit(p, max_new_tokens=6).result()
    np.testing.assert_array_equal(t1, t2)


# ---------------------------------------------------------------------------
# audit integration
# ---------------------------------------------------------------------------


class TestServingAudit:
    def test_serving_entries_registered_and_clean(self, tiny_engine):
        from tools.tpuaudit.core import run_audit
        from tools.tpuaudit.registry import get_entry_points

        srv = serving(tiny_engine)
        eps = get_entry_points(["serving/prefill_chunk", "serving/decode",
                                "serving/cow_copy"])
        assert [ep.name for ep in eps] == ["serving/prefill_chunk",
                                           "serving/decode",
                                           "serving/cow_copy"]
        assert all(ep.donate_argnums == (1,) for ep in eps[:2])  # arena
        assert eps[2].donate_argnums == (0,)
        findings = run_audit(eps, publish_metrics=False)
        assert findings == [], [f"{f.entry}:{f.check}" for f in findings]
        del srv


# ---------------------------------------------------------------------------
# prefix sharing: refcounts, COW, prefix-hit admission
# ---------------------------------------------------------------------------


class TestRefcountedAllocator:
    def test_incref_free_lifecycle(self):
        a = BlockAllocator(4)
        ids = a.alloc(2)
        a.incref(ids)                      # a second holder appears
        assert a.blocks_shared == 2
        a.free(ids)                        # first holder drops out
        assert a.blocks_in_use == 2 and a.blocks_shared == 0
        a.free(ids)                        # LAST reference → recycled
        assert a.blocks_in_use == 0 and a.blocks_free == 4
        with pytest.raises(BlockAllocatorError):
            a.free([ids[0]])               # double free still raises

    def test_incref_unallocated_raises(self):
        a = BlockAllocator(2)
        with pytest.raises(BlockAllocatorError):
            a.incref([1])

    def test_occupancy_invariant_under_sharing(self):
        a = BlockAllocator(6)
        ids = a.alloc(3)
        a.incref(ids[:2])
        a.free(ids)
        a.incref(ids[:1])
        assert a.blocks_in_use + a.blocks_free == 6
        a.free(ids[:2])
        a.free(ids[:1])
        assert a.blocks_in_use == 0 and a.blocks_free == 6


class TestPrefixCacheHost:
    def _cache(self, cap=8, bs=4):
        from deepspeed_tpu.serving import PrefixCache

        alloc = BlockAllocator(cap)
        return alloc, PrefixCache(alloc, bs)

    def test_match_insert_chain(self):
        alloc, pc = self._cache()
        prompt = np.arange(12)             # 3 full blocks of 4
        ids = alloc.alloc(3)
        for i in range(3):
            assert pc.insert(prompt, i, ids[i])
        assert alloc.refcount(ids[0]) == 2   # owner + cache pin
        got, n = pc.match(prompt)
        assert got == ids and n == 11        # capped at len(prompt) - 1
        # a different first token shares nothing (chain hash)
        other = np.concatenate([[99], np.arange(1, 12)])
        assert pc.match(other) == ([], 0)
        # divergence after two blocks → only those two shared
        part = np.concatenate([np.arange(8), [77, 77, 77, 77]])
        got3, n3 = pc.match(part)
        assert got3 == ids[:2] and n3 == 8

    def test_insert_is_idempotent(self):
        alloc, pc = self._cache()
        prompt = np.arange(4)
        ids = alloc.alloc(1)
        assert pc.insert(prompt, 0, ids[0])
        assert not pc.insert(prompt, 0, ids[0])   # no double pin
        assert alloc.refcount(ids[0]) == 2

    def test_evict_respects_pinned_blocks(self):
        alloc, pc = self._cache(cap=4)
        ids = alloc.alloc(2)
        prompt = np.arange(8)
        pc.insert(prompt, 0, ids[0])
        pc.insert(prompt, 1, ids[1])
        alloc.free([ids[1]])     # owner gone → cache is sole holder
        # ids[0] still request-owned (refcount 2) → pinned, never evicted
        assert pc.evict(5) == 1
        assert alloc.refcount(ids[1]) == 0
        assert alloc.refcount(ids[0]) == 2
        assert pc.cached_blocks == 1


class TestPrefixSharing:
    def test_second_request_skips_shared_chunks(self, tiny_engine):
        """The acceptance criterion: an identical cached prompt prefix
        consumes ZERO new prefill chunks for the shared blocks — only the
        capped final token re-prefills (and its shared block goes COW)."""
        srv = serving(tiny_engine)
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, 250, (48,))       # exactly 3 full blocks
        h1 = srv.submit(prompt, max_new_tokens=6)
        srv.run()
        assert srv.prefix.cached_blocks == 3
        h2 = srv.submit(prompt, max_new_tokens=6)
        req = srv.sched.admit()[0]
        # all shared blocks skipped: prefill restarts at the LAST prompt
        # token (its logits seed the first sampled token)
        assert req.prefill_pos == 47
        assert srv.sched.prefix_hit_tokens == 47
        assert srv.sched.prefix_hits == 1
        prefill_steps = 0
        while req.state == PREFILL:
            assert srv._step_prefill()
            prefill_steps += 1
        assert prefill_steps == 1                  # 1 chunk, not 3
        assert srv._cow_copies >= 1                # shared block was copied
        srv.run()
        want = np.asarray(tiny_engine.generate(prompt[None],
                                               max_new_tokens=6))[0]
        np.testing.assert_array_equal(h1.result(), want)
        np.testing.assert_array_equal(h2.result(), want)

    def test_partial_tail_block_stays_private(self, tiny_engine):
        """A prompt with a partial tail block shares only the FULL blocks;
        the tail re-prefills into a fresh private block — no COW needed."""
        srv = serving(tiny_engine)
        rng = np.random.RandomState(8)
        prompt = rng.randint(0, 250, (40,))       # 2 full blocks + 8
        h1 = srv.submit(prompt, max_new_tokens=4)
        srv.run()
        assert srv.prefix.cached_blocks == 2
        h2 = srv.submit(prompt, max_new_tokens=4)
        req = srv.sched.admit()[0]
        assert req.prefill_pos == 32
        cow_before = srv._cow_copies
        srv.run()
        assert srv._cow_copies == cow_before
        want = np.asarray(tiny_engine.generate(prompt[None],
                                               max_new_tokens=4))[0]
        np.testing.assert_array_equal(h1.result(), want)
        np.testing.assert_array_equal(h2.result(), want)

    def test_cancel_releases_shared_blocks_exactly_once(self, tiny_engine):
        srv = serving(tiny_engine)
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, 250, (48,))
        srv.submit(prompt, max_new_tokens=4)
        srv.run()
        h2 = srv.submit(prompt, max_new_tokens=4)
        srv.step()                                 # admit + first chunk
        shared = [b for b in h2._req.blocks if srv.alloc.refcount(b) > 1]
        assert shared                              # really sharing
        before = {b: srv.alloc.refcount(b) for b in shared}
        assert h2.cancel()
        for b in shared:
            assert srv.alloc.refcount(b) == before[b] - 1   # exactly once
        assert not h2.cancel()                     # second cancel: no-op
        # cache pins survive the cancel; no block was force-freed
        assert srv.alloc.blocks_in_use == srv.prefix.cached_blocks

    def test_shared_pressure_stress_outputs_exact(self, tiny_engine):
        """Six requests sharing a 2-block prefix through a pool too small
        to hold them privately: cache eviction + preemption + COW all fire
        and every output stays bit-identical to offline generate()."""
        srv = serving(tiny_engine, num_blocks=14, max_seqs=4)
        rng = np.random.RandomState(10)
        shared = rng.randint(0, 250, (32,))
        prompts = [np.concatenate([shared,
                                   rng.randint(0, 250,
                                               (rng.randint(1, 16),))])
                   for _ in range(6)]
        handles = [srv.submit(p, max_new_tokens=6) for p in prompts]
        srv.run()
        for i, (p, h) in enumerate(zip(prompts, handles)):
            want = np.asarray(tiny_engine.generate(p[None],
                                                   max_new_tokens=6))[0]
            np.testing.assert_array_equal(h.result(), want,
                                          err_msg=f"request {i} diverged")
        # every request reference released — only cache pins remain
        assert srv.alloc.blocks_in_use == srv.prefix.cached_blocks

    def test_prefix_metrics_published(self, tiny_engine, obs_session):
        srv = serving(tiny_engine)
        rng = np.random.RandomState(11)
        prompt = rng.randint(0, 250, (48,))
        srv.submit(prompt, max_new_tokens=4)
        srv.run()
        srv.submit(prompt, max_new_tokens=4)
        srv.run()
        reg = get_registry()
        assert reg.gauge("serving/prefix_hit_rate").value() > 0
        assert reg.gauge("serving/prefix_cache_blocks").value() >= 3
        assert reg.counter("serving/cow_copies").value() >= 1


class TestPagedKernelAB:
    def test_gather_vs_paged_outputs_identical(self, tiny_engine):
        """The --paged-kernel A/B: the dense gather view
        (paged_kernel='off') and the paged read path ('auto': Pallas
        kernels on TPU, the GQA-native jnp reference here) produce
        identical greedy outputs — the 16-request acceptance smoke re-run
        on both paths."""
        rng = np.random.RandomState(12)
        prompts = [rng.randint(0, 250, (rng.randint(4, 40),))
                   for _ in range(16)]
        outs = {}
        for mode in ("off", "auto"):
            srv = serving(tiny_engine, paged_kernel=mode, num_blocks=64,
                          max_seqs=8)
            handles = []
            for i, p in enumerate(prompts):
                handles.append(srv.submit(p, max_new_tokens=8))
                if i % 4 == 3:
                    srv.step()
            srv.run()
            outs[mode] = [h.result() for h in handles]
        for i, p in enumerate(prompts):
            want = np.asarray(tiny_engine.generate(p[None],
                                                   max_new_tokens=8))[0]
            np.testing.assert_array_equal(outs["off"][i], want,
                                          err_msg=f"gather {i} diverged")
            np.testing.assert_array_equal(outs["auto"][i], want,
                                          err_msg=f"paged {i} diverged")


# ---------------------------------------------------------------------------
# deadline enforcement at decode time (ISSUE-12 satellite): an expired
# request must stop consuming rows/blocks, finish as deadline_exceeded, and
# keep the request ledger balanced
# ---------------------------------------------------------------------------


class TestDeadlineEnforcement:
    def test_running_request_expires_and_frees_blocks(self, tiny_engine):
        from deepspeed_tpu.serving import DeadlineExceeded

        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk, prefix_cache=False)
        try:
            h = srv.submit(np.arange(1, 40, dtype=np.int32),
                           max_new_tokens=64, deadline_s=5.0)
            for _ in range(4):
                srv.step()
            assert len(h.tokens) > 0 and not h.done   # mid-stream
            assert srv.alloc.blocks_in_use > 0
            clk.advance(10.0)                         # past the deadline
            progress = srv.step()
            assert progress                            # expiry IS progress
            assert h.state == "deadline_exceeded" and h.done
            # the bugfix: rows and blocks free NOW, not at token budget
            assert srv.alloc.blocks_in_use == 0
            assert srv.sched.queue_depth() == 0
            assert len(srv.sched.running) == 0
            assert srv.sched.deadline_exceeded_count == 1
            with pytest.raises(DeadlineExceeded):
                h.result()
        finally:
            srv.close()

    def test_queued_request_expires_before_admission(self, tiny_engine):
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk, max_seqs=1,
                      prefix_cache=False)
        try:
            # one request holds the only row; the second queues (admit h0
            # FIRST — EDF would otherwise prefer the deadline-bearing h1)
            h0 = srv.submit(np.arange(1, 20, dtype=np.int32),
                            max_new_tokens=32)
            srv.step()
            h1 = srv.submit(np.arange(1, 20, dtype=np.int32),
                            max_new_tokens=4, deadline_s=2.0)
            srv.step()
            assert h1.state == "queued"
            clk.advance(5.0)
            srv.step()
            assert h1.state == "deadline_exceeded"
            assert len(h1.tokens) == 0      # never decoded a token
            h0.result()                     # the survivor is unaffected
        finally:
            srv.close()

    def test_pending_fork_siblings_expire_with_parent(self, tiny_engine):
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk, prefix_cache=False,
                      prefill_chunk=16)
        try:
            # long prompt: parent still prefilling when the deadline hits,
            # so the n=3 siblings are still waiting for their fork point
            hs = srv.submit(np.arange(1, 100, dtype=np.int32),
                            max_new_tokens=8, deadline_s=3.0, n=3)
            srv.step()
            assert srv._pending_fork_count() == 2
            clk.advance(5.0)
            srv.step()
            assert all(h.state == "deadline_exceeded" for h in hs)
            assert srv._pending_fork_count() == 0
            assert srv.sched.deadline_exceeded_count == 3
            assert srv.alloc.blocks_in_use == 0
        finally:
            srv.close()

    def test_ledger_balances_across_terminal_states(self, tiny_engine):
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk, prefix_cache=False)
        try:
            done = srv.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=2)
            gone = srv.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=8)
            late = srv.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=8, deadline_s=1.0)
            srv.step()
            gone.cancel()
            clk.advance(2.0)
            srv.run()
            done.result()
            s = srv.sched
            assert (s.finished_count, s.cancelled_count,
                    s.deadline_exceeded_count) == (1, 1, 1)
            # submitted == completed + cancelled + deadline_exceeded
            assert (s.finished_count + s.cancelled_count
                    + s.deadline_exceeded_count) == 3
            assert srv.in_flight() == 0
        finally:
            srv.close()

    def test_no_deadline_never_expires(self, tiny_engine):
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk, prefix_cache=False)
        try:
            h = srv.submit(np.arange(1, 20, dtype=np.int32),
                           max_new_tokens=4)
            clk.advance(1e6)
            out = h.result()
            assert out.size == 4 and h.state == "finished"
        finally:
            srv.close()
