"""Megatron checkpoint import (inference/megatron_import.py) — the
MegatronSDLoader analog (reference runtime/state_dict_factory.py:21).

A synthetic 2-way-TP Megatron checkpoint is built FROM our own tiny model
params (the inverse layout mapping lives in the test), saved with torch in
the mp_rank_XX layout, loaded back, and must reproduce the original tree
bit-exactly — for both query_key_value orderings the reference handles
(checkpoint_version 0 per-head interleave, >=2.0 per-partition blocks)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.megatron_import import (
    load_megatron_checkpoint, merge_megatron_shards)
from deepspeed_tpu.models.transformer import TransformerConfig, build_model


def _cfg():
    return TransformerConfig(vocab_size=96, hidden_size=32, num_layers=2,
                             num_heads=4, max_seq_len=64,
                             dtype=jnp.float32, tie_embeddings=True)


def _params(cfg):
    return jax.tree.map(lambda x: np.asarray(x, np.float32),
                        build_model(cfg).init(jax.random.PRNGKey(3)))


def _to_megatron_shards(params, cfg, tp, version, vocab_pad=8):
    """Inverse mapping: our tree → per-rank Megatron language_model dicts."""
    H, N, D, L = (cfg.hidden_size, cfg.num_heads, cfg.head_dim,
                  cfg.num_layers)
    npart = N // tp
    V = cfg.vocab_size
    tokens = np.concatenate(
        [params["embed"]["tokens"],
         np.zeros((vocab_pad, H), np.float32)], axis=0)  # Megatron pads vocab
    shards = []
    for r in range(tp):
        sd = {
            "embedding.word_embeddings.weight":
                np.array_split(tokens, tp, axis=0)[r],
            "embedding.position_embeddings.weight": params["pos"],
            "transformer.final_layernorm.weight":
                params["final_norm"]["scale"],
            "transformer.final_layernorm.bias":
                params["final_norm"]["bias"],
        }
        for i in range(L):
            lay = jax.tree.map(lambda x: x[i], params["layers"])
            p = f"transformer.layers.{i}."
            # ours (in, out) → Megatron (out, in); slice this rank's heads
            q = lay["attn"]["wq"].T.reshape(N, D, H)[r * npart:(r + 1) * npart]
            k = lay["attn"]["wk"].T.reshape(N, D, H)[r * npart:(r + 1) * npart]
            v = lay["attn"]["wv"].T.reshape(N, D, H)[r * npart:(r + 1) * npart]
            qb = lay["attn"]["bq"].reshape(N, D)[r * npart:(r + 1) * npart]
            kb = lay["attn"]["bk"].reshape(N, D)[r * npart:(r + 1) * npart]
            vb = lay["attn"]["bv"].reshape(N, D)[r * npart:(r + 1) * npart]
            if version >= 2.0:
                qkv_w = np.concatenate([q.reshape(-1, H), k.reshape(-1, H),
                                        v.reshape(-1, H)], axis=0)
                qkv_b = np.concatenate([qb.reshape(-1), kb.reshape(-1),
                                        vb.reshape(-1)], axis=0)
            else:   # per-head interleave: (np, 3, hn)
                qkv_w = np.stack([q, k, v], axis=1).reshape(-1, H)
                qkv_b = np.stack([qb, kb, vb], axis=1).reshape(-1)
            Fs = lay["mlp"]["w_up"].shape[1]
            sd.update({
                p + "input_layernorm.weight": lay["ln1"]["scale"],
                p + "input_layernorm.bias": lay["ln1"]["bias"],
                p + "post_attention_layernorm.weight": lay["ln2"]["scale"],
                p + "post_attention_layernorm.bias": lay["ln2"]["bias"],
                p + "attention.query_key_value.weight": qkv_w,
                p + "attention.query_key_value.bias": qkv_b,
                p + "attention.dense.weight":
                    np.array_split(lay["attn"]["wo"].T, tp, axis=1)[r],
                p + "attention.dense.bias": lay["attn"]["bo"],
                p + "mlp.dense_h_to_4h.weight":
                    np.array_split(lay["mlp"]["w_up"].T, tp, axis=0)[r],
                p + "mlp.dense_h_to_4h.bias":
                    np.array_split(lay["mlp"]["b_up"], tp, axis=0)[r],
                p + "mlp.dense_4h_to_h.weight":
                    np.array_split(lay["mlp"]["w_down"].T, tp, axis=1)[r],
                p + "mlp.dense_4h_to_h.bias": lay["mlp"]["b_down"],
            })
        shards.append(sd)
    return shards


def _assert_tree_equal(got, want):
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), got, want)


@pytest.mark.parametrize("version", [0.0, 2.0])
def test_merge_round_trip(version):
    cfg = _cfg()
    params = _params(cfg)
    shards = _to_megatron_shards(params, cfg, tp=2, version=version)
    merged = merge_megatron_shards(shards, cfg,
                                   checkpoint_version=version)
    _assert_tree_equal(merged, params)


def test_merge_tp4_and_logits():
    """4-way merge + the merged tree actually runs: logits equal the
    original params' logits."""
    cfg = _cfg()
    params = _params(cfg)
    shards = _to_megatron_shards(params, cfg, tp=4, version=2.0)
    merged = merge_megatron_shards(shards, cfg, checkpoint_version=2.0)
    _assert_tree_equal(merged, params)
    model = build_model(cfg)
    ids = np.random.RandomState(0).randint(0, 96, (2, 12))
    a, _ = model.apply(jax.tree.map(jnp.asarray, params),
                       {"input_ids": jnp.asarray(ids)})
    b, _ = model.apply(jax.tree.map(jnp.asarray, merged),
                       {"input_ids": jnp.asarray(ids)})
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_from_torch_dir(tmp_path):
    """The on-disk path: mp_rank_XX/model_optim_rng.pt torch files with the
    classic nested {'model': {'language_model': {...}}} structure +
    checkpoint_version metadata (reference get_checkpoint_files layout)."""
    torch = pytest.importorskip("torch")
    cfg = _cfg()
    params = _params(cfg)
    shards = _to_megatron_shards(params, cfg, tp=2, version=0.0)
    for r, sd in enumerate(shards):
        d = tmp_path / f"mp_rank_{r:02d}"
        d.mkdir()
        nested = {"embedding": {}, "transformer": {}}
        for k, v in sd.items():
            sec, rest = k.split(".", 1)
            nested[sec][f"{sec}.{rest}"] = torch.tensor(v)
        torch.save({"checkpoint_version": 0.0,
                    "model": {"language_model": nested}},
                   d / "model_optim_rng.pt")
    loaded = load_megatron_checkpoint(str(tmp_path), cfg)
    _assert_tree_equal(loaded, params)

    # end-to-end surface: init_inference(checkpoint='megatron:<dir>')
    from deepspeed_tpu import init_inference

    engine = init_inference(model=build_model(cfg), dtype=jnp.float32,
                            max_out_tokens=64,
                            checkpoint=f"megatron:{tmp_path}")
    ids = np.random.RandomState(1).randint(0, 96, (1, 8))
    model = build_model(cfg)
    want, _ = model.apply(jax.tree.map(jnp.asarray, params),
                          {"input_ids": jnp.asarray(ids)})
    np.testing.assert_allclose(np.asarray(engine.forward(ids)),
                               np.asarray(want), atol=1e-4, rtol=1e-4)
