"""Async I/O extension tests — mirrors reference tests/unit/ops/aio/
test_aio.py (single/parallel read+write round trips, wait semantics)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AIOHandle, aio_compatible

pytestmark = pytest.mark.skipif(not aio_compatible(),
                                reason="no g++ toolchain for the extension")


def test_sync_roundtrip(tmp_path):
    h = AIOHandle(queue_depth=4, num_threads=2)
    data = np.random.RandomState(0).bytes(1 << 16)
    arr = np.frombuffer(data, np.uint8).copy()
    path = str(tmp_path / "blob.bin")
    assert h.sync_pwrite(arr, path) == 1
    out = np.zeros_like(arr)
    assert h.sync_pread(out, path) == 2  # completed counter is cumulative
    np.testing.assert_array_equal(out, arr)
    h.close()


def test_parallel_writes_then_reads(tmp_path):
    h = AIOHandle(queue_depth=8, num_threads=4)
    n = 8
    arrays = [np.full((1 << 14,), i, np.uint8) for i in range(n)]
    for i, a in enumerate(arrays):
        h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
    h.wait()
    outs = [np.zeros_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"f{i}.bin"))
    h.wait()
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)
    h.close()


def test_offset_read(tmp_path):
    h = AIOHandle()
    arr = np.arange(4096, dtype=np.uint8) % 251
    path = str(tmp_path / "off.bin")
    h.sync_pwrite(arr, path)
    part = np.zeros(1024, np.uint8)
    h.sync_pread(part, path, offset=1024)
    np.testing.assert_array_equal(part, arr[1024:2048])
    h.close()


def test_read_error_surfaces(tmp_path):
    h = AIOHandle()
    buf = np.zeros(128, np.uint8)
    with pytest.raises(OSError):
        h.sync_pread(buf, str(tmp_path / "missing.bin"))
    h.close()


def test_config_knobs_kept():
    h = AIOHandle(block_size=1 << 19, queue_depth=16, single_submit=True,
                  overlap_events=False)
    assert h.block_size == 1 << 19 and h.queue_depth == 16
    assert h.single_submit and not h.overlap_events
    h.close()
