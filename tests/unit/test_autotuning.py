"""Autotuner tests — experiment generation without runs (reference
tests/unit/autotuning/test_autotuning.py pattern) + in-process scheduler."""

import json

import pytest

from deepspeed_tpu.autotuning import (Autotuner, generate_experiments,
                                      grid_space, random_space)

BASE = {"train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}


def test_grid_space_counts():
    space = {"a": [1, 2], "b.c": ["x", "y", "z"]}
    assert len(grid_space(space)) == 6


def test_random_space_subsample_deterministic():
    space = {"a": list(range(10)), "b": list(range(10))}
    s1 = random_space(space, 7, seed=3)
    s2 = random_space(space, 7, seed=3)
    assert s1 == s2 and len(s1) == 7
    assert random_space(space, 1000) == grid_space(space)


def test_generate_experiments_applies_nested_overrides():
    exps = generate_experiments(
        BASE, {"train_micro_batch_size_per_gpu": [2, 4],
               "zero_optimization.stage": [0, 3]})
    assert len(exps) == 4
    names = [n for n, _ in exps]
    assert len(set(names)) == 4
    for name, cfg in exps:
        assert cfg["zero_optimization"]["stage"] in (0, 3)
        assert cfg["train_micro_batch_size_per_gpu"] in (2, 4)
        # base not mutated
    assert "zero_optimization" not in BASE


def test_unknown_tuner_rejected():
    with pytest.raises(ValueError, match="tuner_type"):
        generate_experiments(BASE, {"a": [1]}, tuner_type="bayes")


def test_tune_picks_best_and_writes_summary(tmp_path):
    def fake_runner(name, cfg):
        mb = cfg["train_micro_batch_size_per_gpu"]
        if cfg["zero_optimization"]["stage"] == 3 and mb == 8:
            return None  # simulated OOM
        return mb * (1.0 + cfg["zero_optimization"]["stage"])

    tuner = Autotuner(BASE, results_dir=str(tmp_path), runner=fake_runner)
    best, val = tuner.tune(space={"train_micro_batch_size_per_gpu": [2, 8],
                                  "zero_optimization.stage": [0, 3]})
    assert val == 8.0  # mb8/stage0 wins since mb8/stage3 "OOMs"
    summary = json.loads((tmp_path / "summary.json").read_text())
    assert summary["best"] == best
    assert len(summary["results"]) == 4
