"""Autotuner tests — experiment generation without runs (reference
tests/unit/autotuning/test_autotuning.py pattern) + in-process scheduler."""

import json

import pytest

from deepspeed_tpu.autotuning import (Autotuner, generate_experiments,
                                      grid_space, random_space)

BASE = {"train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}


def test_grid_space_counts():
    space = {"a": [1, 2], "b.c": ["x", "y", "z"]}
    assert len(grid_space(space)) == 6


def test_random_space_subsample_deterministic():
    space = {"a": list(range(10)), "b": list(range(10))}
    s1 = random_space(space, 7, seed=3)
    s2 = random_space(space, 7, seed=3)
    assert s1 == s2 and len(s1) == 7
    assert random_space(space, 1000) == grid_space(space)


def test_generate_experiments_applies_nested_overrides():
    exps = generate_experiments(
        BASE, {"train_micro_batch_size_per_gpu": [2, 4],
               "zero_optimization.stage": [0, 3]})
    assert len(exps) == 4
    names = [n for n, _ in exps]
    assert len(set(names)) == 4
    for name, cfg in exps:
        assert cfg["zero_optimization"]["stage"] in (0, 3)
        assert cfg["train_micro_batch_size_per_gpu"] in (2, 4)
        # base not mutated
    assert "zero_optimization" not in BASE


def test_unknown_tuner_rejected():
    with pytest.raises(ValueError, match="tuner_type"):
        generate_experiments(BASE, {"a": [1]}, tuner_type="bayes")


def test_tune_picks_best_and_writes_summary(tmp_path):
    def fake_runner(name, cfg):
        mb = cfg["train_micro_batch_size_per_gpu"]
        if cfg["zero_optimization"]["stage"] == 3 and mb == 8:
            return None  # simulated OOM
        return mb * (1.0 + cfg["zero_optimization"]["stage"])

    tuner = Autotuner(BASE, results_dir=str(tmp_path), runner=fake_runner)
    best, val = tuner.tune(space={"train_micro_batch_size_per_gpu": [2, 8],
                                  "zero_optimization.stage": [0, 3]})
    assert val == 8.0  # mb8/stage0 wins since mb8/stage3 "OOMs"
    summary = json.loads((tmp_path / "summary.json").read_text())
    assert summary["best"] == best
    assert len(summary["results"]) == 4


class TestModelBasedTuner:
    """Reference tuner/model_based_tuner.py + cost_model.py, TPU-rendered:
    the analytic cost model prunes OOM configs and ranks the rest, so the
    tuner reaches the grid-best config in a fraction of the grid's trials."""

    MODEL_INFO = {"num_params": 124e6, "hidden_size": 768,
                  "num_layers": 12, "seq_length": 1024}

    def _oracle(self):
        """Recorded-sweep stand-in: measured tokens/s by (micro, stage) on
        the dev chip for gpt2-125m (bench.py family numbers); micro 64 OOMs."""
        sweep = {(8, 0): 84e3, (8, 1): 82e3, (8, 2): 80e3,
                 (16, 0): 105e3, (16, 1): 103e3, (16, 2): 100e3,
                 (32, 0): 117e3, (32, 1): 115e3, (32, 2): 112e3,
                 (128, 0): None, (128, 1): None, (128, 2): None}  # OOM

        calls = []

        def runner(name, cfg):
            key = (cfg["train_micro_batch_size_per_gpu"],
                   cfg.get("zero_optimization", {}).get("stage", 0))
            calls.append(key)
            return sweep[key]

        return runner, calls, sweep

    def test_cost_model_prunes_oom_and_ranks(self):
        from deepspeed_tpu.autotuning import TpuCostModel

        m = TpuCostModel(model_info=self.MODEL_INFO, hbm_bytes=16e9,
                         device_kind="TPU v5 lite")
        small = {"train_micro_batch_size_per_gpu": 8,
                 "zero_optimization": {"stage": 0}}
        big = {"train_micro_batch_size_per_gpu": 512,
               "zero_optimization": {"stage": 0}}
        assert m.predict_throughput(small) > 0
        assert m.predict_throughput(big) == 0.0        # activation OOM
        # larger micro batch amortises overhead: predicted faster
        mid = {"train_micro_batch_size_per_gpu": 32,
               "zero_optimization": {"stage": 0}}
        assert m.predict_throughput(mid) > m.predict_throughput(small)

    def test_reaches_best_in_half_the_trials(self, tmp_path):
        runner, calls, sweep = self._oracle()
        space = {"train_micro_batch_size_per_gpu": [8, 16, 32, 128],
                 "zero_optimization.stage": [0, 1, 2]}
        tuner = Autotuner({"train_batch_size": 32},
                          results_dir=str(tmp_path), runner=runner)
        best, val = tuner.tune(space=space, tuner_type="model_based",
                               num_trials=6, model_info=self.MODEL_INFO,
                               hbm_bytes=16e9, device_kind="TPU v5 lite")
        grid_size = 12
        assert len(calls) <= grid_size // 2            # <= half of grid
        # found the true best (micro 32, stage 0)
        assert val == 117e3
        assert (32, 0) in calls
        # OOM configs were never measured
        assert all(k[0] != 128 for k in calls)

    def test_model_based_requires_model_info(self, tmp_path):
        tuner = Autotuner({}, results_dir=str(tmp_path),
                          runner=lambda n, c: 1.0)
        with pytest.raises(ValueError, match="model_info"):
            tuner.tune(tuner_type="model_based")

    def test_resource_manager_parallel(self):
        import threading
        import time as _time

        from deepspeed_tpu.autotuning import ResourceManager

        seen = []
        lock = threading.Lock()

        def runner(name, cfg):
            with lock:
                seen.append(name)
            _time.sleep(0.2)
            return float(len(name))

        exps = [(f"e{i}", {}) for i in range(4)]
        t0 = _time.perf_counter()
        out = ResourceManager(runner, max_parallel=4).run(exps)
        dt = _time.perf_counter() - t0
        assert len(out) == 4 and all(v is not None for v in out.values())
        assert dt < 0.6        # ran concurrently, not 4 x 0.2s sequentially
