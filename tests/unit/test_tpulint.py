"""tpulint unit tests: per-rule positive/negative fixtures, suppressions,
baseline semantics, and the repo-wide gate (the linter run against
``deepspeed_tpu/`` with the committed baseline must be clean — this test is
what makes tier-1 enforce static analysis)."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.tpulint import analyze_source
from tools.tpulint import baseline as baseline_mod
from tools.tpulint.cli import main as tpulint_main
from tools.tpulint.core import RULES, Finding

REPO = Path(__file__).resolve().parents[2]


def rules_of(source, **kw):
    return sorted({f.rule for f in analyze_source(source, **kw)})


# ---------------------------------------------------------------------------
# rule fixtures


class TestHostSyncInJit:
    def test_positive_item_in_decorated_jit(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n")
        assert "host-sync-in-jit" in rules_of(src)

    def test_positive_np_asarray_reachable_through_helper(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def helper(x):\n"
            "    return np.asarray(x)\n"
            "def step(x):\n"
            "    return helper(x)\n"
            "fast = jax.jit(step)\n")
        assert "host-sync-in-jit" in rules_of(src)

    def test_positive_float_cast(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x)\n")
        assert "host-sync-in-jit" in rules_of(src)

    def test_negative_outside_jit(self):
        src = (
            "import numpy as np\n"
            "def log_metrics(x):\n"
            "    return float(np.asarray(x).mean()), x.item()\n")
        assert rules_of(src) == []

    def test_negative_jnp_inside_jit(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return jnp.asarray(x) + jnp.float32(1.0)\n")
        assert rules_of(src) == []


class TestImpureJit:
    def test_positive_print_time_random(self):
        src = (
            "import jax, time, random\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    print('hi')\n"
            "    t = time.time()\n"
            "    r = random.random()\n"
            "    return x\n")
        findings = [f for f in analyze_source(src) if f.rule == "impure-jit"]
        assert len(findings) == 3

    def test_positive_attribute_mutation(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(self, x):\n"
            "    self.cache = x\n"
            "    return x\n")
        assert "impure-jit" in rules_of(src)

    def test_positive_global(self):
        src = (
            "import jax\n"
            "N = 0\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    global N\n"
            "    N = 1\n"
            "    return x\n")
        assert "impure-jit" in rules_of(src)

    def test_negative_jax_debug_print(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    jax.debug.print('x={x}', x=x)\n"
            "    return x\n")
        assert rules_of(src) == []

    def test_negative_print_outside_jit(self):
        src = (
            "import time\n"
            "def report():\n"
            "    print(time.time())\n")
        assert rules_of(src) == []


class TestMissingDonation:
    def test_positive_decorator_form(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(params, batch):\n"
            "    return params\n")
        assert "missing-donation" in rules_of(src)

    def test_positive_call_wrapping_new_name(self):
        src = (
            "import jax\n"
            "def update(opt_state, grads):\n"
            "    new_opt_state = grads\n"
            "    return new_opt_state\n"
            "fast = jax.jit(update)\n")
        assert "missing-donation" in rules_of(src)

    def test_negative_with_donate_argnums(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def step(params, batch):\n"
            "    return params\n"
            "def update(opt_state, g):\n"
            "    return opt_state\n"
            "fast = jax.jit(update, donate_argnums=(0,))\n")
        assert rules_of(src) == []

    def test_negative_no_roundtrip(self):
        # takes params but returns a loss — nothing to donate
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def eval_step(params, batch):\n"
            "    return jnp.sum(batch)\n")
        assert rules_of(src) == []


class TestUnknownMeshAxis:
    DECL = 'MODEL_AXIS = "model"\nDATA_AXIS = "data"\n'

    def test_positive_typo_in_partition_spec(self):
        src = (self.DECL +
               "from jax.sharding import PartitionSpec as P\n"
               "spec = P('modle', None)\n")
        assert "unknown-mesh-axis" in rules_of(src)

    def test_positive_collective_axis_kwarg(self):
        src = (self.DECL +
               "import jax\n"
               "def f(x):\n"
               "    return jax.lax.psum(x, axis_name='dataa')\n")
        assert "unknown-mesh-axis" in rules_of(src)

    def test_negative_declared_axes(self):
        # (hardcoded-partition-spec still fires on the literal axes — this
        # fixture only cares that the axes are KNOWN)
        src = (self.DECL +
               "from jax.sharding import PartitionSpec as P\n"
               "spec = P(('data',), 'model')\n")
        assert "unknown-mesh-axis" not in rules_of(src)

    def test_negative_without_any_declaration(self):
        # no mesh in the analyzed set -> nothing to validate against
        src = ("from jax.sharding import PartitionSpec as P\n"
               "spec = P('anything')\n")
        assert "unknown-mesh-axis" not in rules_of(src)


class TestHardcodedPartitionSpec:
    SRC = ('MODEL_AXIS = "model"\n'
           "from jax.sharding import PartitionSpec as P\n"
           "spec = P('model', None)\n")

    def test_positive_literal_axis(self):
        assert "hardcoded-partition-spec" in rules_of(self.SRC)

    def test_positive_tuple_axes(self):
        src = ("from jax.sharding import PartitionSpec as P\n"
               "spec = P(('expert', 'data'))\n")
        assert "hardcoded-partition-spec" in rules_of(src)

    def test_negative_axis_constant(self):
        # placement through the named constants stays allowed — only the
        # string literals bypass the registry
        src = ('MODEL_AXIS = "model"\n'
               "from jax.sharding import PartitionSpec as P\n"
               "spec = P(MODEL_AXIS)\n")
        assert "hardcoded-partition-spec" not in rules_of(src)

    def test_negative_empty_spec(self):
        src = ("from jax.sharding import PartitionSpec as P\n"
               "spec = P()\n")
        assert "hardcoded-partition-spec" not in rules_of(src)

    def test_negative_in_rule_registry(self):
        assert "hardcoded-partition-spec" not in rules_of(
            self.SRC, path="deepspeed_tpu/parallel/rules.py")

    def test_negative_in_tests(self):
        assert "hardcoded-partition-spec" not in rules_of(
            self.SRC, path="tests/unit/test_something.py")

    def test_inline_suppression(self):
        src = ("from jax.sharding import PartitionSpec as P\n"
               "spec = P('model')  # tpulint: disable=hardcoded-partition-spec\n")
        assert "hardcoded-partition-spec" not in rules_of(src)


class TestDeprecatedJaxApi:
    def test_positive_tree_map(self):
        src = ("import jax\n"
               "out = jax.tree_map(lambda v: v, {})\n")
        assert "deprecated-jax-api" in rules_of(src)

    def test_positive_pjit_import(self):
        src = "from jax.experimental.pjit import pjit\n"
        assert "deprecated-jax-api" in rules_of(src)

    def test_positive_maps_import(self):
        src = "import jax.experimental.maps\n"
        assert "deprecated-jax-api" in rules_of(src)

    def test_negative_modern_apis(self):
        src = ("import jax\n"
               "out = jax.tree.map(lambda v: v, {})\n"
               "out2 = jax.tree_util.tree_map(lambda v: v, {})\n")
        assert rules_of(src) == []


class TestKeyReuse:
    def test_positive_reuse(self):
        src = (
            "import jax\n"
            "def f():\n"
            "    key = jax.random.PRNGKey(0)\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    b = jax.random.uniform(key, (2,))\n"
            "    return a + b\n")
        assert "key-reuse" in rules_of(src)

    def test_negative_split(self):
        src = (
            "import jax\n"
            "def f():\n"
            "    key = jax.random.PRNGKey(0)\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    a = jax.random.normal(k1, (2,))\n"
            "    b = jax.random.uniform(k2, (2,))\n"
            "    return a + b\n")
        assert rules_of(src) == []

    def test_negative_rebound_key(self):
        src = (
            "import jax\n"
            "def f():\n"
            "    key = jax.random.PRNGKey(0)\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    key = jax.random.PRNGKey(1)\n"
            "    b = jax.random.normal(key, (2,))\n"
            "    return a + b\n")
        assert rules_of(src) == []


class TestWallclockTimingWithoutSync:
    RULE = "wallclock-timing-without-sync"

    def test_positive_unfenced_delta(self):
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step, batch):\n"
            "    t0 = time.perf_counter()\n"
            "    for _ in range(10):\n"
            "        loss = step(batch)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return dt\n")
        assert self.RULE in rules_of(src)

    def test_positive_delta_nested_in_append(self):
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step, batch, out):\n"
            "    t0 = time.perf_counter()\n"
            "    step(batch)\n"
            "    out.append(time.perf_counter() - t0)\n")
        assert self.RULE in rules_of(src)

    def test_positive_work_after_last_fence(self):
        # one early fence does not bless work dispatched after it
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step1, step2, batch):\n"
            "    t0 = time.perf_counter()\n"
            "    a = step1(batch)\n"
            "    jax.block_until_ready(a)\n"
            "    b = step2(batch)\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE in rules_of(src)

    def test_negative_block_until_ready_fence(self):
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step, batch):\n"
            "    t0 = time.perf_counter()\n"
            "    loss = step(batch)\n"
            "    jax.block_until_ready(loss)\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE not in rules_of(src)

    def test_negative_float_materialisation_fence(self):
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step, batch):\n"
            "    t0 = time.perf_counter()\n"
            "    loss = step(batch)\n"
            "    float(loss)\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE not in rules_of(src)

    def test_negative_local_helper_that_fences(self):
        src = (
            "import time\n"
            "import jax\n"
            "def bench(step, batch):\n"
            "    def run():\n"
            "        jax.block_until_ready(step(batch))\n"
            "    run()\n"
            "    t0 = time.perf_counter()\n"
            "    run()\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE not in rules_of(src)

    def test_negative_module_without_jax(self):
        src = (
            "import time\n"
            "def bench(parse, data):\n"
            "    t0 = time.perf_counter()\n"
            "    out = parse(data)\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE not in rules_of(src)

    def test_negative_no_calls_between(self):
        src = (
            "import time\n"
            "import jax\n"
            "def f():\n"
            "    t0 = time.perf_counter()\n"
            "    x = 1 + 2\n"
            "    return time.perf_counter() - t0\n")
        assert self.RULE not in rules_of(src)


# ---------------------------------------------------------------------------
# suppressions + baseline


class TestSuppression:
    SRC = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item(){comment}\n")

    def test_same_line(self):
        src = self.SRC.format(
            comment="  # tpulint: disable=host-sync-in-jit")
        assert rules_of(src) == []

    def test_previous_comment_line(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    # intentional scalar readback. tpulint: disable=host-sync-in-jit\n"
            "    return x.item()\n")
        assert rules_of(src) == []

    def test_wrong_rule_does_not_mask(self):
        src = self.SRC.format(comment="  # tpulint: disable=impure-jit")
        assert rules_of(src) == ["host-sync-in-jit"]

    def test_disable_all(self):
        src = self.SRC.format(comment="  # tpulint: disable=all")
        assert rules_of(src) == []


class TestBaseline:
    def _findings(self, n, path="a.py", rule="host-sync-in-jit"):
        return [Finding(rule, path, i + 1, 0, "m") for i in range(n)]

    def test_baselined_findings_masked(self, tmp_path):
        bl = tmp_path / "bl.json"
        baseline_mod.write(str(bl), self._findings(2))
        known = baseline_mod.load(str(bl))
        assert baseline_mod.new_findings(self._findings(2), known) == []

    def test_over_budget_fails(self, tmp_path):
        bl = tmp_path / "bl.json"
        baseline_mod.write(str(bl), self._findings(1))
        known = baseline_mod.load(str(bl))
        assert len(baseline_mod.new_findings(self._findings(2), known)) == 1

    def test_fixes_only_lower_counts_pass(self, tmp_path):
        bl = tmp_path / "bl.json"
        baseline_mod.write(str(bl), self._findings(3))
        known = baseline_mod.load(str(bl))
        assert baseline_mod.new_findings(self._findings(1), known) == []

    def test_cli_roundtrip(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        bl = tmp_path / "bl.json"
        assert tpulint_main([str(bad), "--root", str(tmp_path)]) == 1
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl), "--write-baseline"]) == 0
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl)]) == 0
        data = json.loads(bl.read_text())
        assert data["counts"] == {"bad.py::host-sync-in-jit": 1}

    def test_stale_baseline_entry_fails_the_gate(self, tmp_path, capsys):
        """Fixing a finding without regenerating the baseline leaves a stale
        budget that would silently re-admit regressions — the gate errors."""
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        bl = tmp_path / "bl.json"
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl), "--write-baseline"]) == 0
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x\n")
        capsys.readouterr()
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl)]) == 1
        assert "stale baseline entry" in capsys.readouterr().out

    def test_prune_baseline_drops_stale_keys(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        bl = tmp_path / "bl.json"
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl), "--write-baseline"]) == 0
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x\n")
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl), "--prune-baseline"]) == 0
        assert json.loads(bl.read_text())["counts"] == {}
        assert tpulint_main([str(bad), "--root", str(tmp_path),
                             "--baseline", str(bl)]) == 0

    def test_deleted_file_under_analyzed_dir_is_stale(self, tmp_path, capsys):
        """Deleting a file is the most common source of baseline rot — its
        keys are in scope when the run covers the enclosing directory."""
        bad = tmp_path / "pkg" / "bad.py"
        bad.parent.mkdir()
        bad.write_text("import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        bl = tmp_path / "bl.json"
        assert tpulint_main([str(tmp_path / "pkg"), "--root", str(tmp_path),
                             "--baseline", str(bl), "--write-baseline"]) == 0
        bad.unlink()
        capsys.readouterr()
        assert tpulint_main([str(tmp_path / "pkg"), "--root", str(tmp_path),
                             "--baseline", str(bl)]) == 1
        assert "stale baseline entry" in capsys.readouterr().out
        assert tpulint_main([str(tmp_path / "pkg"), "--root", str(tmp_path),
                             "--baseline", str(bl), "--prune-baseline"]) == 0
        assert json.loads(bl.read_text())["counts"] == {}

    def test_prune_missing_baseline_is_an_error(self, tmp_path, capsys):
        a = tmp_path / "a.py"
        a.write_text("x = 1\n")
        assert tpulint_main([str(a), "--root", str(tmp_path),
                             "--baseline", str(tmp_path / "nope.json"),
                             "--prune-baseline"]) == 2

    def test_partial_run_does_not_condemn_out_of_scope_keys(self, tmp_path,
                                                            capsys):
        """Linting one file with a baseline that also budgets another file
        must not flag the other file's keys as stale."""
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        src = "import jax\n@jax.jit\ndef f(x):\n    return x.item()\n"
        a.write_text(src)
        b.write_text(src)
        bl = tmp_path / "bl.json"
        assert tpulint_main([str(a), str(b), "--root", str(tmp_path),
                             "--baseline", str(bl), "--write-baseline"]) == 0
        assert tpulint_main([str(a), "--root", str(tmp_path),
                             "--baseline", str(bl)]) == 0


# ---------------------------------------------------------------------------
# CLI surface


class TestCli:
    def test_json_format(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nout = jax.tree_map(lambda v: v, {})\n")
        rc = tpulint_main([str(bad), "--root", str(tmp_path),
                           "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["new_findings"] == 1
        assert out["findings"][0]["rule"] == "deprecated-jax-api"

    def test_select_unknown_rule_errors(self, capsys):
        assert tpulint_main(["--select", "not-a-rule"]) == 2

    def test_list_rules_names_all_seven(self, capsys):
        assert tpulint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in ("host-sync-in-jit", "impure-jit", "missing-donation",
                     "unknown-mesh-axis", "deprecated-jax-api", "key-reuse",
                     "wallclock-timing-without-sync"):
            assert name in out
        assert len(RULES) >= 7


# ---------------------------------------------------------------------------
# repo-wide gate


class TestRepoGate:
    def test_source_tree_clean_under_baseline(self):
        """Acceptance gate: the committed tree + committed baseline lint
        clean. A new host sync / impurity / donation miss in deepspeed_tpu/
        fails this test (and therefore tier-1)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "deepspeed_tpu/",
             "--baseline", ".tpulint-baseline.json"],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, \
            f"tpulint found new issues:\n{proc.stdout}\n{proc.stderr}"

    def test_lint_script_gate(self):
        """scripts/lint.sh (the CI entry point) must pass on the tree."""
        proc = subprocess.run(
            ["bash", "scripts/lint.sh"],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, \
            f"scripts/lint.sh failed:\n{proc.stdout}\n{proc.stderr}"

    def test_seeded_violation_detected(self, tmp_path):
        """A seeded .item() inside a jitted fn must be flagged as NEW even
        with the committed baseline in effect."""
        bad = tmp_path / "seeded.py"
        bad.write_text(
            "import jax\n"
            "@jax.jit\n"
            "def train_step(params, batch):\n"
            "    loss = batch.item()\n"
            "    return params\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", str(bad),
             "--baseline", ".tpulint-baseline.json", "--root", str(tmp_path)],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        assert "host-sync-in-jit" in proc.stdout
