"""Optimizer parity tests — analog of reference tests/unit/ops/adam/test_adamw.py
(compares DeepSpeed optimizers against torch.optim references on small shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from deepspeed_tpu.config import Config, OptimizerConfig, load_config
from deepspeed_tpu.runtime.optimizer import (MixedPrecisionOptimizer,
                                             build_optax_transform,
                                             build_optimizer,
                                             clip_by_global_norm)


def _run_ours(opt_type, params_np, grads_np, steps, lr=1e-2, wd=0.0, dtype=jnp.float32):
    cfg = OptimizerConfig(type=opt_type, params={"lr": lr, "weight_decay": wd})
    tx = build_optax_transform(cfg, lr)
    opt = MixedPrecisionOptimizer(tx, lr_schedule=lr)
    params = {k: jnp.asarray(v, dtype) for k, v in params_np.items()}
    state = opt.init(params)
    for _ in range(steps):
        grads = {k: jnp.asarray(v, dtype) for k, v in grads_np.items()}
        params, state, _ = opt.apply(params, grads, state)
    master = state.master if state.master is not None else params
    return {k: np.asarray(v, np.float32) for k, v in master.items()}


def _run_torch(torch_cls, params_np, grads_np, steps, **kw):
    tensors = {k: torch.tensor(v, dtype=torch.float32, requires_grad=True)
               for k, v in params_np.items()}
    opt = torch_cls(list(tensors.values()), **kw)
    for _ in range(steps):
        for k, t in tensors.items():
            t.grad = torch.tensor(grads_np[k], dtype=torch.float32)
        opt.step()
    return {k: t.detach().numpy() for k, t in tensors.items()}


@pytest.fixture
def problem():
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(4, 8).astype(np.float32), "b": rng.randn(8).astype(np.float32)}
    grads = {"w": rng.randn(4, 8).astype(np.float32), "b": rng.randn(8).astype(np.float32)}
    return params, grads


def test_adamw_matches_torch(problem):
    params, grads = problem
    ours = _run_ours("adamw", params, grads, steps=5, lr=1e-2, wd=0.01)
    ref = _run_torch(torch.optim.AdamW, params, grads, steps=5, lr=1e-2, weight_decay=0.01)
    for k in params:
        np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)


def test_adam_matches_torch(problem):
    params, grads = problem
    ours = _run_ours("adam", params, grads, steps=5, lr=1e-2)
    ref = _run_torch(torch.optim.Adam, params, grads, steps=5, lr=1e-2)
    for k in params:
        np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)


def test_adagrad_matches_torch(problem):
    params, grads = problem
    ours = _run_ours("adagrad", params, grads, steps=5, lr=1e-2)
    ref = _run_torch(torch.optim.Adagrad, params, grads, steps=5, lr=1e-2)
    for k in params:
        np.testing.assert_allclose(ours[k], ref[k], rtol=1e-4, atol=1e-5)


def test_sgd_matches_torch(problem):
    params, grads = problem
    ours = _run_ours("sgd", params, grads, steps=3, lr=1e-2)
    ref = _run_torch(torch.optim.SGD, params, grads, steps=3, lr=1e-2)
    for k in params:
        np.testing.assert_allclose(ours[k], ref[k], rtol=1e-6)


def test_lamb_runs(problem):
    params, grads = problem
    out = _run_ours("lamb", params, grads, steps=3, lr=1e-2)
    for k in params:
        assert np.isfinite(out[k]).all()
        assert not np.allclose(out[k], params[k])


def test_bf16_master_weights(problem):
    """bf16 params keep an fp32 master; repeated tiny updates must accumulate
    in the master even when each is below bf16 resolution."""
    params = {"w": np.ones((8, 8), np.float32)}
    grads = {"w": np.full((8, 8), 1e-4, np.float32)}
    cfg = OptimizerConfig(type="sgd", params={"lr": 1e-3})
    opt = MixedPrecisionOptimizer(build_optax_transform(cfg, 1e-3), lr_schedule=1e-3)
    p = {k: jnp.asarray(v, jnp.bfloat16) for k, v in params.items()}
    state = opt.init(p)
    assert state.master is not None
    for _ in range(100):
        g = {k: jnp.asarray(v, jnp.bfloat16) for k, v in grads.items()}
        p, state, _ = opt.apply(p, g, state)
    # master moved by ~100 * 1e-3 * 1e-4 = 1e-5; bf16-only accumulation would stall at 1.0
    master = np.asarray(state.master["w"], np.float32)
    assert (master < 1.0).all()
    np.testing.assert_allclose(master, 1.0 - 1e-5, rtol=0.05)


def test_skip_update(problem):
    params, grads = problem
    cfg = OptimizerConfig(type="adamw", params={"lr": 1e-2})
    opt = MixedPrecisionOptimizer(build_optax_transform(cfg, 1e-2), lr_schedule=1e-2)
    p = {k: jnp.asarray(v) for k, v in params.items()}
    state = opt.init(p)
    g = {k: jnp.asarray(v) for k, v in grads.items()}
    p2, state2, stats = opt.apply(p, g, state, skip_update=jnp.asarray(True))
    for k in p:
        np.testing.assert_array_equal(np.asarray(p2[k]), np.asarray(p[k]))
    assert bool(stats.skipped)
    # count still advances (attempt recorded)
    assert int(state2.count) == 1


def test_grad_clipping():
    grads = {"a": jnp.full((10,), 3.0)}
    clipped, norm = clip_by_global_norm(grads, max_norm=1.0)
    np.testing.assert_allclose(float(norm), np.sqrt(90.0), rtol=1e-6)
    new_norm = float(jnp.linalg.norm(clipped["a"]))
    assert new_norm == pytest.approx(1.0, rel=1e-4)


def test_build_from_config():
    cfg = load_config({"optimizer": {"type": "adamw", "params": {"lr": 3e-4}},
                       "gradient_clipping": 1.0})
    opt = build_optimizer(cfg)
    assert opt.grad_clip == 1.0
    p = {"w": jnp.ones((2, 2))}
    s = opt.init(p)
    p2, s2, stats = opt.apply(p, {"w": jnp.ones((2, 2))}, s)
    assert float(stats.lr) == pytest.approx(3e-4)


def test_jit_compatible(problem):
    params, grads = problem
    cfg = OptimizerConfig(type="adamw", params={"lr": 1e-2})
    opt = MixedPrecisionOptimizer(build_optax_transform(cfg, 1e-2), lr_schedule=1e-2)
    p = {k: jnp.asarray(v) for k, v in params.items()}
    state = opt.init(p)
    g = {k: jnp.asarray(v) for k, v in grads.items()}

    @jax.jit
    def step(p, g, s):
        return opt.apply(p, g, s)

    p2, s2, stats = step(p, g, state)
    assert np.isfinite(float(stats.grad_norm))
