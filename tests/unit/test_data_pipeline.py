"""Data-efficiency tests — curriculum scheduler math (reference
test_data_efficiency.py semantics), sampler eligibility/resume, random-LTD
subset mechanics."""

import jax
import os
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (CurriculumDataSampler,
                                                 CurriculumScheduler,
                                                 RandomLTDScheduler,
                                                 sample_token_subset)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (gather_tokens,
                                                            scatter_tokens)


class TestCurriculumScheduler:
    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "min_difficulty": 1, "max_difficulty": 3,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [1, 2, 3], "max_step": [5, 10]}})
        assert s.get_difficulty(1) == 1
        assert s.get_difficulty(5) == 1
        assert s.get_difficulty(6) == 2
        assert s.get_difficulty(10) == 2
        assert s.get_difficulty(11) == 3
        assert s.get_difficulty(10_000) == 3

    def test_fixed_linear_ramp(self):
        s = CurriculumScheduler({
            "min_difficulty": 8, "max_difficulty": 128,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(100) == 128
        mid = s.get_difficulty(50)
        assert 56 <= mid <= 72 and mid % 8 == 0
        # monotone
        vals = [s.get_difficulty(t) for t in range(0, 110, 10)]
        assert vals == sorted(vals)

    def test_fixed_root_slower_start(self):
        lin = CurriculumScheduler({
            "min_difficulty": 0, "max_difficulty": 100,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 1}})
        root = CurriculumScheduler({
            "min_difficulty": 0, "max_difficulty": 100,
            "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "root_degree": 2, "difficulty_step": 1}})
        # sqrt ramp rises faster early
        assert root.get_difficulty(25) > lin.get_difficulty(25)

    def test_validation(self):
        with pytest.raises(ValueError, match="min_difficulty"):
            CurriculumScheduler({"max_difficulty": 2,
                                 "schedule_type": "fixed_linear"})
        with pytest.raises(ValueError, match="schedule_type"):
            CurriculumScheduler({"min_difficulty": 1, "max_difficulty": 2,
                                 "schedule_type": "warp"})


class TestCurriculumSampler:
    def _sampler(self, bs=4):
        sched = CurriculumScheduler({
            "min_difficulty": 1, "max_difficulty": 10,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 1}})
        diffs = np.arange(100) % 10 + 1
        return CurriculumDataSampler(diffs, bs, sched, seed=1), diffs

    def test_respects_difficulty(self):
        sampler, diffs = self._sampler()
        batch = sampler.sample_batch(global_step=0)   # difficulty 1
        assert (diffs[batch] <= 1).all()
        batch = sampler.sample_batch(global_step=5)   # difficulty ~5
        assert (diffs[batch] <= sampler.scheduler.current_difficulty).all()

    def test_deterministic_and_resumable(self):
        s1, _ = self._sampler()
        s2, _ = self._sampler()
        b1 = [s1.sample_batch() for _ in range(5)]
        s2.load_state_dict({"global_step": 3,
                            "scheduler": {"current_difficulty": 1}})
        b2 = [s2.sample_batch() for _ in range(2)]
        np.testing.assert_array_equal(b1[3], b2[0])
        np.testing.assert_array_equal(b1[4], b2[1])


class TestRandomLTD:
    def test_schedule_ramp(self):
        s = RandomLTDScheduler({"min_value": 64, "max_value": 512,
                                "schedule_config": {
                                    "total_layer_token_step": 100,
                                    "difficulty_step": 8}})
        assert s.get_seq_len(0) == 64
        assert s.get_seq_len(100) == 512

    def test_forward_wiring(self):
        """LTD layers run on a token subset: forward stays shape-correct,
        differs from the full model, and reduces to it at ltd_keep == S."""
        from deepspeed_tpu.models import create_model

        full = create_model("tiny", num_layers=4)
        params = full.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 full.config.vocab_size)

        ltd = create_model("tiny", num_layers=4, ltd_enabled=True,
                           ltd_layers=(1, 2), ltd_keep=8)
        base, _ = full.apply(params, {"input_ids": ids})
        out, _ = ltd.apply(params, {"input_ids": ids})
        assert out.shape == base.shape
        assert np.isfinite(np.asarray(out)).all()
        assert not np.allclose(np.asarray(out), np.asarray(base))

        # keep == S => no drop anywhere, bit-identical to the plain model
        noop = create_model("tiny", num_layers=4, ltd_enabled=True,
                            ltd_keep=16)
        noop_out, _ = noop.apply(params, {"input_ids": ids})
        np.testing.assert_array_equal(np.asarray(noop_out), np.asarray(base))

    @pytest.mark.slow
    def test_engine_schedule_drives_keep(self):
        import deepspeed_tpu
        from deepspeed_tpu.models import create_model

        model = create_model("tiny")
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 1,
               "steps_per_print": 1000,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
               "data_efficiency": {
                   "enabled": True,
                   "data_routing": {"random_ltd": {
                       "enabled": True,
                       "random_ltd_schedule": {
                           "min_value": 8, "max_value": 32,
                           "schedule_config": {"total_layer_token_step": 4,
                                               "difficulty_step": 8}}}}}}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        assert engine.model.config.ltd_enabled
        assert engine.model.config.ltd_layers == (1,)  # tiny: 2 layers
        ids = jax.random.randint(jax.random.PRNGKey(0),
                                 (1, engine.train_batch_size(), 32), 0,
                                 model.config.vocab_size)
        keeps = []
        for _ in range(6):
            loss = engine.train_batch(batch={"input_ids": ids})
            assert np.isfinite(float(loss))
            keeps.append(engine.model.config.ltd_keep)
        assert keeps[0] == 8               # ramp start
        assert keeps[-1] == 32             # ramp done: full sequence
        assert keeps == sorted(keeps)

    def test_subset_gather_scatter_roundtrip(self):
        rng = jax.random.PRNGKey(0)
        kept, mask = sample_token_subset(rng, 16, 6)
        assert kept.shape == (6,) and int(mask.sum()) == 6
        assert (np.diff(np.asarray(kept)) > 0).all()  # sorted
        x = jnp.arange(2 * 16 * 4, dtype=jnp.float32).reshape(2, 16, 4)
        part = gather_tokens(x, kept)
        assert part.shape == (2, 6, 4)
        back = scatter_tokens(x, part * 2, kept)
        np.testing.assert_allclose(np.asarray(back[:, kept]),
                                   np.asarray(part) * 2)
        inv = ~np.asarray(mask)
        np.testing.assert_allclose(np.asarray(back[:, inv]),
                                   np.asarray(x[:, inv]))


class TestDataAnalyzer:
    """Reference data_sampling/data_analyzer.py map/reduce protocol: workers
    index their shard offline, reduce merges into the difficulty index the
    curriculum sampler consumes."""

    def _dataset(self, n=20):
        rng = np.random.RandomState(0)
        return [{"input_ids": np.zeros(int(l), np.int32)}
                for l in rng.randint(4, 64, n)]

    def test_map_reduce_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, load_difficulties, token_count_metric)

        ds = self._dataset()
        for w in range(3):
            DataAnalyzer(ds, {"seqlen": token_count_metric}, str(tmp_path),
                         num_workers=3, worker_id=w).run_map()
        DataAnalyzer.run_reduce(str(tmp_path), "seqlen", num_workers=3)
        diff = load_difficulties(str(tmp_path), "seqlen")
        want = [len(s["input_ids"]) for s in ds]
        np.testing.assert_array_equal(np.asarray(diff), want)
        # metric_to_sample buckets are consistent
        import json as _json
        import os as _os
        with open(_os.path.join(str(tmp_path), "seqlen", "index.json")) as f:
            idx = _json.load(f)
        assert idx["num_samples"] == len(ds)
        buckets = np.load(_os.path.join(str(tmp_path), "seqlen",
                                        "metric_to_sample.npz"))
        for val, ids in buckets.items():
            assert all(want[i] == float(val) for i in ids)

    def test_feeds_curriculum_sampler(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                         CurriculumDataSampler)
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, load_difficulties, token_count_metric)

        ds = self._dataset(32)
        DataAnalyzer(ds, {"seqlen": token_count_metric},
                     str(tmp_path)).run_map()
        DataAnalyzer.run_reduce(str(tmp_path), "seqlen", num_workers=1)
        diff = load_difficulties(str(tmp_path), "seqlen")
        sched = CurriculumScheduler({
            "min_difficulty": 16, "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 8}})
        sampler = CurriculumDataSampler(diff, batch_size=4, scheduler=sched)
        batch = sampler.sample_batch(global_step=0)
        assert all(diff[i] <= 16 for i in batch)

    def test_missing_shard_fails_loudly(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, token_count_metric)

        ds = self._dataset()
        DataAnalyzer(ds, {"seqlen": token_count_metric}, str(tmp_path),
                     num_workers=2, worker_id=0).run_map()
        import pytest as _pytest
        with _pytest.raises(FileNotFoundError):
            DataAnalyzer.run_reduce(str(tmp_path), "seqlen", num_workers=2)


class TestMMapIndexedDataset:
    """Megatron .bin/.idx mmap format (reference
    data_sampling/indexed_dataset.py:369): byte-level layout oracle,
    round-trip, sub-range reads, and the analyzer->sampler workflow over a
    production-format corpus."""

    def _build(self, prefix, dtype=np.int32):
        from deepspeed_tpu.runtime.data_pipeline import (
            MMapIndexedDatasetBuilder)

        rng = np.random.RandomState(0)
        seqs = [rng.randint(0, 50000, size=n).astype(dtype)
                for n in (5, 17, 3, 64, 1, 30)]
        b = MMapIndexedDatasetBuilder(prefix, dtype=dtype)
        for i, s in enumerate(seqs):
            b.add_item(s)
            if i in (1, 4):          # documents: [0,1], [2,3,4], [5]
                b.end_document()
        b.end_document()
        b.finalize()
        return seqs

    def test_roundtrip_and_layout_oracle(self, tmp_path):
        import struct

        from deepspeed_tpu.runtime.data_pipeline import MMapIndexedDataset

        prefix = str(tmp_path / "corpus")
        seqs = self._build(prefix)
        ds = MMapIndexedDataset(prefix)
        assert len(ds) == len(seqs)
        for i, s in enumerate(seqs):
            np.testing.assert_array_equal(ds[i], s)
        np.testing.assert_array_equal(ds.sizes,
                                      [len(s) for s in seqs])
        np.testing.assert_array_equal(ds.doc_idx, [0, 2, 5, 6])
        # byte-level oracle: independent struct parse of the header
        raw = open(prefix + ".idx", "rb").read()
        assert raw[:9] == b"MMIDIDX\x00\x00"
        version, = struct.unpack("<Q", raw[9:17])
        code = raw[17]
        count, doc_count = struct.unpack("<QQ", raw[18:34])
        assert (version, code, count, doc_count) == (1, 4, 6, 4)
        sizes = np.frombuffer(raw, np.int32, count, offset=34)
        pointers = np.frombuffer(raw, np.int64, count,
                                 offset=34 + sizes.nbytes)
        assert pointers[0] == 0
        np.testing.assert_array_equal(
            np.diff(pointers), (sizes[:-1] * 4).astype(np.int64))
        # .bin holds exactly the tokens, back to back
        assert (os.path.getsize(prefix + ".bin")
                == sum(len(s) for s in seqs) * 4)

    def test_subrange_get_and_slice(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import MMapIndexedDataset

        prefix = str(tmp_path / "corpus")
        seqs = self._build(prefix)
        ds = MMapIndexedDataset(prefix)
        np.testing.assert_array_equal(ds.get(3, offset=10, length=20),
                                      seqs[3][10:30])
        got = ds[1:3]
        assert len(got) == 2
        np.testing.assert_array_equal(got[0], seqs[1])
        with pytest.raises(IndexError):
            ds.get(0, offset=2, length=10)   # past the end of seq 0 (len 5)
        assert MMapIndexedDataset.exists(prefix)
        assert not MMapIndexedDataset.exists(prefix + "-nope")

    def test_uint16_dtype(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import (
            MMapIndexedDataset, MMapIndexedDatasetBuilder)

        prefix = str(tmp_path / "c16")
        b = MMapIndexedDatasetBuilder(prefix, dtype=np.uint16)
        b.add_item(np.asarray([1, 2, 65000], np.uint16))
        b.finalize()
        ds = MMapIndexedDataset(prefix)
        assert ds.dtype == np.uint16
        np.testing.assert_array_equal(ds[0], [1, 2, 65000])

    def test_bad_magic_rejected(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import MMapIndexedDataset

        prefix = str(tmp_path / "bad")
        open(prefix + ".idx", "wb").write(b"TNTIDX\x00\x00X" + b"\x00" * 32)
        open(prefix + ".bin", "wb").write(b"")
        with pytest.raises(ValueError, match="magic"):
            MMapIndexedDataset(prefix)

    def test_analyzer_curriculum_over_mmap_corpus(self, tmp_path):
        """The production workflow (VERDICT r4 #9): mmap corpus -> 2-worker
        map/reduce difficulty index -> curriculum sampler batches easy
        samples first."""
        from deepspeed_tpu.runtime.data_pipeline import (
            CurriculumDataSampler, CurriculumScheduler, DataAnalyzer,
            MMapIndexedDataset, MMapIndexedDatasetBuilder,
            load_difficulties, token_count_metric)

        prefix = str(tmp_path / "corpus")
        rng = np.random.RandomState(1)
        lens = rng.randint(4, 100, size=32)
        b = MMapIndexedDatasetBuilder(prefix, dtype=np.uint16)
        for n in lens:
            b.add_item(rng.randint(0, 1000, size=n).astype(np.uint16))
        b.finalize()

        ds = MMapIndexedDataset(prefix)
        save = str(tmp_path / "index")
        for w in range(2):
            DataAnalyzer(ds, {"seqlen": token_count_metric}, save,
                         num_workers=2, worker_id=w).run_map()
        DataAnalyzer.run_reduce(save, "seqlen", num_workers=2)
        diff = load_difficulties(save, "seqlen")
        np.testing.assert_array_equal(np.asarray(diff, np.int64), lens)

        sched = CurriculumScheduler({
            "min_difficulty": 20, "max_difficulty": 100,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 8}})
        sampler = CurriculumDataSampler(diff, batch_size=4, scheduler=sched)
        batch = sampler.sample_batch(global_step=0)
        assert all(lens[i] <= 20 for i in batch)
        # the sampled ids read straight back out of the mmap corpus
        assert all(len(ds[int(i)]) == lens[i] for i in batch)
