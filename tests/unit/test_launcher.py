"""Launcher tests — mirror of reference tests/unit/launcher/
(test_ds_arguments.py, test_multinode_runner.py: generated-command
assertions, no cluster needed) plus a real 2-process local smoke test
(the DistributedExec pattern driven through the actual CLI)."""

import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher.launch import (build_rank_env, decode_world_info,
                                           encode_world_info)
from deepspeed_tpu.launcher.multinode import PDSHRunner, SSHRunner
from deepspeed_tpu.launcher.runner import (build_node_cmd, fetch_hostfile,
                                           filter_hosts, parse_args)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TestHostfile:
    def test_parse(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("worker-0 slots=4\nworker-1 slots=4\n# comment\n\n")
        assert fetch_hostfile(str(hf)) == {"worker-0": 4, "worker-1": 4}

    def test_duplicate_host_rejected(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("w0 slots=2\nw0 slots=2\n")
        with pytest.raises(ValueError, match="duplicate"):
            fetch_hostfile(str(hf))

    def test_localhost_fallback(self):
        env = os.environ.pop("TPU_WORKER_HOSTNAMES", None)
        try:
            assert fetch_hostfile(None) == {"localhost": 1}
        finally:
            if env is not None:
                os.environ["TPU_WORKER_HOSTNAMES"] = env

    def test_tpu_pod_env(self):
        os.environ["TPU_WORKER_HOSTNAMES"] = "t0,t1,t2,t3"
        try:
            assert fetch_hostfile(None) == {"t0": 1, "t1": 1, "t2": 1, "t3": 1}
        finally:
            del os.environ["TPU_WORKER_HOSTNAMES"]

    def test_filters(self):
        hosts = {"a": 1, "b": 1, "c": 1}
        assert filter_hosts(hosts, "a,b", None, -1) == {"a": 1, "b": 1}
        assert filter_hosts(hosts, None, "b", -1) == {"a": 1, "c": 1}
        assert filter_hosts(hosts, None, None, 2) == {"a": 1, "b": 1}
        with pytest.raises(ValueError):
            filter_hosts(hosts, "zzz", None, -1)


class TestWorldInfo:
    def test_roundtrip(self):
        wi = {"worker-0": 2, "worker-1": 2}
        assert decode_world_info(encode_world_info(wi)) == wi

    def test_rank_assignment(self):
        wi = {"w0": 2, "w1": 3}
        envs = build_rank_env(wi, "w1", "10.0.0.1", 29500)
        assert [e["RANK"] for e in envs] == ["2", "3", "4"]
        assert all(e["WORLD_SIZE"] == "5" for e in envs)
        assert all(e["MASTER_ADDR"] == "10.0.0.1" for e in envs)
        assert [e["LOCAL_RANK"] for e in envs] == ["0", "1", "2"]

    def test_unknown_node_rejected(self):
        with pytest.raises(ValueError):
            build_rank_env({"w0": 1}, "nope", "addr", 1)


class TestMultinodeCommands:
    def _args(self):
        return parse_args(["--master_port", "29501", "train.py", "--flag"])

    def test_node_cmd(self):
        args = self._args()
        cmd = build_node_cmd(args, {"h0": 1, "h1": 1}, "h0")
        assert cmd[1:3] == ["-m", "deepspeed_tpu.launcher.launch"]
        assert "--world_info" in cmd
        i = cmd.index("--world_info")
        assert decode_world_info(cmd[i + 1]) == {"h0": 1, "h1": 1}
        assert cmd[-2:] == ["train.py", "--flag"]

    def test_pdsh_cmd(self):
        runner = PDSHRunner(exports={"PYTHONPATH": "/x"})
        cmds = runner.get_cmd(["h0", "h1"],
                              {h: ["python", "-m", "mod"] for h in ["h0", "h1"]})
        assert len(cmds) == 1
        cmd = cmds[0]
        assert cmd[0] == "pdsh"
        assert cmd[cmd.index("-w") + 1] == "h0,h1"
        assert "export PYTHONPATH=/x;" in cmd[-1]
        assert "export DSTPU_NODE_NAME=%h;" in cmd[-1]

    def test_ssh_cmd(self):
        runner = SSHRunner()
        cmds = runner.get_cmd(["h0", "h1"],
                              {h: ["python", "-m", "mod"] for h in ["h0", "h1"]})
        assert len(cmds) == 2
        assert cmds[0][0] == "ssh" and cmds[0][-2] == "h0"
        assert "export DSTPU_NODE_NAME=h0;" in cmds[0][-1]


@pytest.mark.slow
def test_local_two_process_smoke(tmp_path):
    """End-to-end: the CLI spawns 2 local processes x 4 virtual CPU devices
    that rendezvous via jax.distributed and psum across the 8-device global
    mesh (reference DistributedExec, driven through the real launcher)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from deepspeed_tpu import comm
        comm.init_distributed()
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 8, len(jax.devices())
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        ones = jax.jit(
            lambda: jax.lax.with_sharding_constraint(
                jnp.ones((8,)), NamedSharding(mesh, P("data"))).sum())()
        assert float(ones) == 8.0
        print(f"SMOKE-OK rank={jax.process_index()}", flush=True)
    """ % REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "deepspeed-tpu"),
         "--num_procs", "2", "--cpu_devices_per_proc", "4",
         "--master_port", "29517", str(script)],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.count("SMOKE-OK") == 2, out.stdout + out.stderr


def test_ds_report_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds-tpu-report")],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
    assert out.returncode == 0, out.stderr
    assert "flash_attention" in out.stdout
    assert "jax version" in out.stdout
