"""Launcher tests — mirror of reference tests/unit/launcher/
(test_ds_arguments.py, test_multinode_runner.py: generated-command
assertions, no cluster needed) plus a real 2-process local smoke test
(the DistributedExec pattern driven through the actual CLI)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher.launch import (build_rank_env, decode_world_info,
                                           encode_world_info)
from deepspeed_tpu.launcher.multinode import PDSHRunner, SSHRunner
from deepspeed_tpu.launcher.runner import (build_node_cmd, fetch_hostfile,
                                           filter_hosts, parse_args)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TestHostfile:
    def test_parse(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("worker-0 slots=4\nworker-1 slots=4\n# comment\n\n")
        assert fetch_hostfile(str(hf)) == {"worker-0": 4, "worker-1": 4}

    def test_duplicate_host_rejected(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("w0 slots=2\nw0 slots=2\n")
        with pytest.raises(ValueError, match="duplicate"):
            fetch_hostfile(str(hf))

    def test_localhost_fallback(self):
        env = os.environ.pop("TPU_WORKER_HOSTNAMES", None)
        try:
            assert fetch_hostfile(None) == {"localhost": 1}
        finally:
            if env is not None:
                os.environ["TPU_WORKER_HOSTNAMES"] = env

    def test_tpu_pod_env(self):
        os.environ["TPU_WORKER_HOSTNAMES"] = "t0,t1,t2,t3"
        try:
            assert fetch_hostfile(None) == {"t0": 1, "t1": 1, "t2": 1, "t3": 1}
        finally:
            del os.environ["TPU_WORKER_HOSTNAMES"]

    def test_filters(self):
        hosts = {"a": 1, "b": 1, "c": 1}
        assert filter_hosts(hosts, "a,b", None, -1) == {"a": 1, "b": 1}
        assert filter_hosts(hosts, None, "b", -1) == {"a": 1, "c": 1}
        assert filter_hosts(hosts, None, None, 2) == {"a": 1, "b": 1}
        with pytest.raises(ValueError):
            filter_hosts(hosts, "zzz", None, -1)


class TestWorldInfo:
    def test_roundtrip(self):
        wi = {"worker-0": 2, "worker-1": 2}
        assert decode_world_info(encode_world_info(wi)) == wi

    def test_rank_assignment(self):
        wi = {"w0": 2, "w1": 3}
        envs = build_rank_env(wi, "w1", "10.0.0.1", 29500)
        assert [e["RANK"] for e in envs] == ["2", "3", "4"]
        assert all(e["WORLD_SIZE"] == "5" for e in envs)
        assert all(e["MASTER_ADDR"] == "10.0.0.1" for e in envs)
        assert [e["LOCAL_RANK"] for e in envs] == ["0", "1", "2"]

    def test_unknown_node_rejected(self):
        with pytest.raises(ValueError):
            build_rank_env({"w0": 1}, "nope", "addr", 1)


class TestMultinodeCommands:
    def _args(self):
        return parse_args(["--master_port", "29501", "train.py", "--flag"])

    def test_node_cmd(self):
        args = self._args()
        cmd = build_node_cmd(args, {"h0": 1, "h1": 1}, "h0")
        assert cmd[1:3] == ["-m", "deepspeed_tpu.launcher.launch"]
        assert "--world_info" in cmd
        i = cmd.index("--world_info")
        assert decode_world_info(cmd[i + 1]) == {"h0": 1, "h1": 1}
        assert cmd[-2:] == ["train.py", "--flag"]

    def test_pdsh_cmd(self):
        runner = PDSHRunner(exports={"PYTHONPATH": "/x"})
        cmds = runner.get_cmd(["h0", "h1"],
                              {h: ["python", "-m", "mod"] for h in ["h0", "h1"]})
        assert len(cmds) == 1
        cmd = cmds[0]
        assert cmd[0] == "pdsh"
        assert cmd[cmd.index("-w") + 1] == "h0,h1"
        assert "export PYTHONPATH=/x;" in cmd[-1]
        assert "export DSTPU_NODE_NAME=%h;" in cmd[-1]

    def test_openmpi_cmd(self):
        from deepspeed_tpu.launcher.multinode import OpenMPIRunner

        runner = OpenMPIRunner(exports={"PYTHONPATH": "/x"})
        cmds = runner.get_cmd(["h0", "h1"],
                              {h: ["python", "-m", "mod"] for h in ["h0", "h1"]})
        assert len(cmds) == 1
        cmd = cmds[0]
        assert cmd[:5] == ["mpirun", "-n", "2", "-npernode", "1"]
        assert cmd[cmd.index("-host") + 1] == "h0,h1"
        assert "PYTHONPATH=/x" in cmd[cmd.index("-x") + 1:]
        assert cmd[-3:-1] == ["bash", "-c"]
        assert "DSTPU_NODE_NAME=$(hostname)" in cmd[-1]

    def test_mpich_cmd(self):
        from deepspeed_tpu.launcher.multinode import MPICHRunner

        cmds = MPICHRunner(exports={"A": "1"}).get_cmd(
            ["h0"], {"h0": ["python", "x.py"]})
        cmd = cmds[0]
        assert cmd[:5] == ["mpirun", "-n", "1", "-ppn", "1"]
        i = cmd.index("-genv")
        assert cmd[i + 1:i + 3] == ["A", "1"]

    def test_slurm_cmd(self):
        from deepspeed_tpu.launcher.multinode import SlurmRunner

        cmds = SlurmRunner(exports={"A": "1"}).get_cmd(
            ["h0", "h1"], {h: ["python", "x.py"] for h in ["h0", "h1"]})
        cmd = cmds[0]
        assert cmd[:3] == ["srun", "-n", "2"]
        assert cmd[cmd.index("--nodelist") + 1] == "h0,h1"
        assert any(a.startswith("--export=ALL,") and "A=1" in a for a in cmd)

    def test_get_runner_names(self):
        from deepspeed_tpu.launcher.multinode import get_runner

        for name in ("pdsh", "ssh", "openmpi", "mpich", "slurm"):
            assert get_runner(name).name == name
        import pytest as _pytest
        with _pytest.raises(ValueError, match="unknown launcher"):
            get_runner("mvapich2")

    def test_ssh_cmd(self):
        runner = SSHRunner()
        cmds = runner.get_cmd(["h0", "h1"],
                              {h: ["python", "-m", "mod"] for h in ["h0", "h1"]})
        assert len(cmds) == 2
        assert cmds[0][0] == "ssh" and cmds[0][-2] == "h0"
        assert "export DSTPU_NODE_NAME=h0;" in cmds[0][-1]


@pytest.mark.slow
def test_local_two_process_smoke(tmp_path):
    """End-to-end: the CLI spawns 2 local processes x 4 virtual CPU devices
    that rendezvous via jax.distributed and psum across the 8-device global
    mesh (reference DistributedExec, driven through the real launcher)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from deepspeed_tpu import comm
        comm.init_distributed()
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 8, len(jax.devices())
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        ones = jax.jit(
            lambda: jax.lax.with_sharding_constraint(
                jnp.ones((8,)), NamedSharding(mesh, P("data"))).sum())()
        assert float(ones) == 8.0
        print(f"SMOKE-OK rank={jax.process_index()}", flush=True)
    """ % REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "deepspeed-tpu"),
         "--num_procs", "2", "--cpu_devices_per_proc", "4",
         "--master_port", "29517", str(script)],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.count("SMOKE-OK") == 2, out.stdout + out.stderr


def test_ds_report_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds-tpu-report")],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
    assert out.returncode == 0, out.stderr
    assert "flash_attention" in out.stdout
    assert "jax version" in out.stdout


class TestElasticAgent:
    """Reference elasticity/elastic_agent.py:28 semantics: worker failure →
    group restart with re-rendezvous, up to max_restarts; resume from the
    latest checkpoint; membership shrink recomputes the elastic micro
    batch."""

    @pytest.mark.slow
    def test_kill_worker_restarts_and_resumes(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig)

        log = tmp_path / "steps.jsonl"
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import json, os, sys
            sys.path.insert(0, %r)
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import jax.numpy as jnp
            import deepspeed_tpu as ds
            from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                          build_model)

            ckpt_root, log_path, total = sys.argv[1], sys.argv[2], int(sys.argv[3])
            rank = os.environ["RANK"]
            restart = int(os.environ["DSTPU_RESTART_COUNT"])
            ckpt = os.path.join(ckpt_root, f"rank{rank}")
            model = build_model(TransformerConfig(
                vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
                max_seq_len=16))
            engine, *_ = ds.initialize(model=model, config={
                "train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
            engine.load_checkpoint(ckpt)          # no-op on first run
            start = engine.global_steps
            rng = np.random.default_rng(0)
            for step in range(start, total):
                loss = float(engine.train_batch(
                    batch={"input_ids": rng.integers(0, 64, (1, 2, 16))}))
                engine.save_checkpoint(ckpt)
                with open(log_path, "a") as f:
                    f.write(json.dumps({"rank": rank, "restart": restart,
                                        "step": step}) + chr(10))
                if step == 2 and restart == 0 and rank == "0":
                    os._exit(17)                  # simulated worker death
            print("WORKER-DONE", rank, flush=True)
        """ % REPO))
        agent = ElasticAgent(
            [sys.executable, str(script), str(tmp_path / "ck"), str(log),
             "5"],
            nprocs=2,
            config=ElasticAgentConfig(max_restarts=2, master_port=29530),
            env_base={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                      "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
        rc = agent.run()
        assert rc == 0
        assert agent.restart_count == 1
        lines = [json.loads(l)
                 for l in log.read_text().splitlines()]
        r0 = [l for l in lines if l["rank"] == "0"]
        # incarnation 0 died after step 2; incarnation 1 RESUMED at step 3
        # (checkpoint restore), not step 0
        steps_by_restart = {}
        for l in r0:
            steps_by_restart.setdefault(l["restart"], []).append(l["step"])
        assert steps_by_restart[0] == [0, 1, 2]
        assert steps_by_restart[1][0] == 3, steps_by_restart
        assert steps_by_restart[1][-1] == 4

    @__import__('pytest').mark.slow
    def test_membership_shrink_recomputes_micro(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig)

        probe = tmp_path / "probe.py"
        # workers only survive at world size <= 2 — the agent must shrink
        # membership to the next VALID elastic world size and re-spawn with
        # the recomputed micro batch in the env
        probe.write_text(textwrap.dedent("""
            import json, os, sys
            with open(sys.argv[1], "a") as f:
                f.write(json.dumps({
                    "world": os.environ["WORLD_SIZE"],
                    "micro": os.environ.get("DSTPU_ELASTIC_MICRO"),
                    "port": os.environ["MASTER_PORT"]}) + chr(10))
            sys.exit(0 if int(os.environ["WORLD_SIZE"]) <= 2 else 1)
        """))
        log = tmp_path / "probe.jsonl"
        elastic = {"elasticity": {
            "enabled": True, "max_train_batch_size": 16,
            "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8,
            "version": 0.1}}
        agent = ElasticAgent(
            [sys.executable, str(probe), str(log)], nprocs=4,
            config=ElasticAgentConfig(max_restarts=3, min_workers=1,
                                      master_port=29540,
                                      elastic_config=elastic))
        rc = agent.run()
        assert rc == 0
        assert agent._world == 2 and agent.restart_count == 1
        lines = [json.loads(l)
                 for l in log.read_text().splitlines()]
        # re-rendezvous: the port moved between incarnations
        assert lines[0]["port"] != lines[-1]["port"]
        assert lines[-1]["world"] == "2"
        assert lines[-1]["micro"] is not None

    def test_max_restarts_exhausted(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig,
                                                          WorkerGroupFailure)

        agent = ElasticAgent(
            [sys.executable, "-c", "import sys; sys.exit(3)"], nprocs=1,
            config=ElasticAgentConfig(max_restarts=1, master_port=29550,
                                      backoff_base_s=0.01))
        with pytest.raises(WorkerGroupFailure, match="max_restarts"):
            agent.run()
        assert agent.restart_count == 1


class TestAgentRestartHardening:
    """PR-9 satellite: exponential backoff with jitter between respawns and
    the max-restarts-per-window circuit breaker (with a flight-recorder
    bundle naming the last failure on trip), plus the eviction-request
    control channel the fleet-health straggler policy drives."""

    def _agent(self, tmp_path, cmd=None, nprocs=1, clock=None, **cfg):
        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig)
        import random

        cfg.setdefault("master_port", 29555)
        cfg.setdefault("agent_dir", str(tmp_path / "agent"))
        sleeps = []
        agent = ElasticAgent(
            cmd or [sys.executable, "-c", "import sys; sys.exit(3)"],
            nprocs=nprocs, config=ElasticAgentConfig(**cfg),
            clock=clock or (lambda: 0.0),
            sleep_fn=sleeps.append, rng=random.Random(0))
        agent._test_sleeps = sleeps
        return agent

    def test_backoff_ladder_with_jitter(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import WorkerGroupFailure

        agent = self._agent(tmp_path, max_restarts=4, backoff_base_s=1.0,
                            backoff_max_s=3.0, backoff_jitter=0.25)
        with pytest.raises(WorkerGroupFailure, match="max_restarts"):
            agent.run()
        sleeps = agent._test_sleeps
        assert len(sleeps) == 4
        # exponential ladder 1, 2, 3(cap), 3(cap) — each with up to +25%
        for got, base in zip(sleeps, (1.0, 2.0, 3.0, 3.0)):
            assert base <= got <= base * 1.25, (sleeps)
        # jitter actually applied (not all exactly at base)
        assert any(got > base for got, base in zip(sleeps,
                                                   (1.0, 2.0, 3.0, 3.0)))

    def test_circuit_breaker_trips_with_bundle(self, tmp_path):
        from deepspeed_tpu.launcher.elastic_agent import WorkerGroupFailure

        agent = self._agent(tmp_path, max_restarts=10, backoff_base_s=0.0,
                            restart_window_s=60.0,
                            max_restarts_per_window=3)
        with pytest.raises(WorkerGroupFailure, match="circuit breaker"):
            agent.run()
        # 3 respawns inside the window are ALLOWED; the 4th attempt trips
        assert agent.restart_count == 3
        # the bundle names the last failure
        crash_dir = tmp_path / "agent" / "crash"
        bundles = list(crash_dir.glob("crash-*restart-breaker*"))
        assert bundles, list(crash_dir.iterdir())
        manifest = json.loads((bundles[0] / "MANIFEST.json").read_text())
        assert manifest["reason"] == "restart-breaker"
        extra = manifest["extra"]
        assert extra["last_failure"]["rc"] == 3
        assert extra["restarts_in_window"] == 4

    def test_breaker_window_slides(self, tmp_path):
        """Restarts spread WIDER than the window never trip the breaker."""
        t = [0.0]

        def clock():
            t[0] += 100.0   # each poll/restart 100s apart > 60s window
            return t[0]

        agent = self._agent(tmp_path, max_restarts=4, backoff_base_s=0.0,
                            restart_window_s=60.0,
                            max_restarts_per_window=2, clock=clock)
        from deepspeed_tpu.launcher.elastic_agent import WorkerGroupFailure

        # exhausts max_restarts (the total budget) WITHOUT a breaker trip
        with pytest.raises(WorkerGroupFailure, match="max_restarts"):
            agent.run()

    @pytest.mark.parametrize("max_restarts", [2, 0])
    def test_eviction_request_restarts_with_shrink(self, tmp_path,
                                                   max_restarts):
        """An evict.json dropped into the agent dir (what
        session.TrainingSession's straggler policy writes via
        request_eviction) kills + re-rendezvouses at a smaller
        membership. max_restarts=0: a DELIBERATE eviction does not consume
        the crash budget — remediation must work even with no crash
        restarts left."""
        import json as _json
        import threading
        import time as _time

        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig,
                                                          request_eviction)

        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        log = tmp_path / "probe.jsonl"
        # workers: finish instantly at world <= 2, otherwise linger
        probe = tmp_path / "probe.py"
        probe.write_text(
            "import json, os, sys, time\n"
            "with open(sys.argv[1], 'a') as fh:\n"
            "    fh.write(json.dumps({'world': os.environ['WORLD_SIZE'],\n"
            "        'agent_dir': os.environ.get('DSTPU_AGENT_DIR')})\n"
            "        + chr(10))\n"
            "if int(os.environ['WORLD_SIZE']) <= 2:\n"
            "    sys.exit(0)\n"
            "time.sleep(30)\n")
        agent = ElasticAgent(
            [sys.executable, str(probe), str(log)], nprocs=3,
            config=ElasticAgentConfig(
                max_restarts=max_restarts, min_workers=1, master_port=29556,
                monitor_interval=0.05, backoff_base_s=0.01,
                agent_dir=str(agent_dir)))

        def drop_request():
            # DEFLAKED (was: a fixed 0.7s sleep): on a loaded box spawning
            # 3 interpreters can take longer than any fixed sleep, and a
            # request dropped before every worker has written its probe
            # line makes the `lines[0]["world"] == "3"` assertion race the
            # restart. Wait for the OBSERVABLE condition instead — all 3
            # incarnation-0 workers logged — before requesting eviction.
            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline:
                try:
                    if len(log.read_text().splitlines()) >= 3:
                        break
                except OSError:
                    pass
                _time.sleep(0.05)
            request_eviction(1, reason="test straggler", step=7,
                             agent_dir=str(agent_dir))

        t = threading.Thread(target=drop_request)
        t.start()
        rc = agent.run()
        t.join()
        assert rc == 0
        assert agent.evictions == 1 and agent.restart_count == 1
        assert agent._world == 2
        assert agent.last_failure["kind"] == "eviction"
        assert agent.last_failure["rank"] == 1
        lines = [_json.loads(l) for l in log.read_text().splitlines()]
        assert lines[0]["world"] == "3" and lines[-1]["world"] == "2"
        # workers saw the control-channel contract
        assert lines[0]["agent_dir"] == str(agent_dir)

    def test_eviction_ignored_when_membership_cannot_shrink(self, tmp_path):
        """min_workers unset (the default): honouring an eviction would
        respawn the same membership — straggler included — and churn
        forever; the agent must drop the request instead."""
        import threading
        import time as _time

        from deepspeed_tpu.launcher.elastic_agent import (ElasticAgent,
                                                          ElasticAgentConfig,
                                                          request_eviction)

        agent_dir = tmp_path / "agent"
        agent_dir.mkdir()
        agent = ElasticAgent(
            [sys.executable, "-c", "import time; time.sleep(2)"], nprocs=2,
            config=ElasticAgentConfig(
                max_restarts=2, master_port=29558, monitor_interval=0.05,
                agent_dir=str(agent_dir)))

        def drop():
            _time.sleep(0.4)
            request_eviction(1, reason="slow", agent_dir=str(agent_dir))

        t = threading.Thread(target=drop)
        t.start()
        rc = agent.run()
        t.join()
        assert rc == 0
        assert agent.evictions == 0 and agent.restart_count == 0
        assert agent._world == 2

    def test_request_eviction_without_agent_is_dropped(self, monkeypatch):
        from deepspeed_tpu.launcher.elastic_agent import request_eviction

        monkeypatch.delenv("DSTPU_AGENT_DIR", raising=False)
        assert request_eviction(3, reason="no agent") is None

    def test_stale_eviction_request_cleared_on_failure_restart(self,
                                                               tmp_path):
        """An evict.json racing a worker crash must not survive the crash
        restart — left behind it would trigger a second, spurious shrink
        on the next healthy poll."""
        from deepspeed_tpu.launcher.elastic_agent import request_eviction

        agent = self._agent(
            tmp_path, cmd=[sys.executable, "-c", "import sys; sys.exit(0)"],
            max_restarts=3, backoff_base_s=0.0)
        request_eviction(1, reason="raced by a crash",
                         agent_dir=agent.agent_dir)
        req = os.path.join(agent.agent_dir, "evict.json")
        assert os.path.exists(req)
        agent._restart("worker exit rc=7", shrink=True)   # the CRASH path
        agent._terminate_all()
        assert not os.path.exists(req)
        assert agent.evictions == 0   # the stale request was never honoured
