"""Fleet health + numerics sentinel tests.

Multi-rank behavior is tested single-process: the gather is injectable
(``gather_fn``), so a fake fleet table stands in for N processes, and
in-process data-parallel replicas over the 8 virtual CPU devices exercise
the replica-checksum divergence path with a genuinely corrupted replica
buffer (the SDC the sentinel exists for).
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import simple_model
from deepspeed_tpu.observability import (FleetHealthMonitor, NumericsTrip,
                                         get_session, reset_session)
from deepspeed_tpu.observability.flightrecorder import FlightRecorder
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.observability import numerics as numerics_mod


@pytest.fixture(autouse=True)
def _fresh_session():
    yield
    reset_session()


def _obs_cfg(tmp_path, **over):
    cfg = {"enabled": True, "output_dir": str(tmp_path / "obs"),
           "flight_dump_dir": str(tmp_path / "crash")}
    cfg.update(over)
    return cfg


def _engine(tmp_path, obs=None, hidden=10, micro=4, zero=0):
    model = simple_model(hidden_dim=hidden)
    cfg = {"train_micro_batch_size_per_gpu": micro,
           "steps_per_print": 10 ** 9,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": zero}}
    if obs is not None:
        cfg["observability"] = obs
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def _batch(engine, hidden=10, nan=False, seed=0):
    gb = engine.train_batch_size()
    rng = np.random.RandomState(seed)
    x = rng.randn(1, gb, hidden).astype(np.float32)
    y = rng.randn(1, gb, 1).astype(np.float32)
    if nan:
        x[0, 0, 0] = np.nan
    return {"x": x, "y": y}


# ---------------------------------------------------------------------------
# numerics: device-half unit behavior
# ---------------------------------------------------------------------------


class TestNumericsObserve:
    def test_clean_step_no_flags(self):
        st = numerics_mod.init_state()
        st, tripped = numerics_mod.observe(st, jnp.float32(1.0),
                                           {"g": jnp.ones((3,))})
        assert not bool(tripped)
        assert int(st.flags) == 0 and int(st.trip_step) == -1
        assert float(st.ema_loss) == pytest.approx(1.0)

    def test_nonfinite_loss_and_grads_flagged(self):
        st = numerics_mod.init_state()
        st, tripped = numerics_mod.observe(
            st, jnp.float32("nan"), {"g": jnp.array([1.0, jnp.inf])})
        assert bool(tripped)
        flags = int(st.flags)
        assert flags & numerics_mod.NONFINITE_LOSS
        assert flags & numerics_mod.NONFINITE_GRADS
        assert int(st.trip_step) == 0
        assert numerics_mod.describe_flags(flags) == \
            "nonfinite-loss+nonfinite-grads"

    def test_nan_does_not_poison_ema(self):
        st = numerics_mod.init_state()
        st, _ = numerics_mod.observe(st, jnp.float32(2.0), {})
        st, _ = numerics_mod.observe(st, jnp.float32("nan"), {})
        assert float(st.ema_loss) == pytest.approx(2.0)

    def test_loss_spike_after_warmup(self):
        st = numerics_mod.init_state()
        for _ in range(3):
            st, tripped = numerics_mod.observe(st, jnp.float32(1.0), {},
                                               spike_factor=3.0,
                                               spike_warmup=2)
            assert not bool(tripped)
        st, tripped = numerics_mod.observe(st, jnp.float32(100.0), {},
                                           spike_factor=3.0, spike_warmup=2)
        assert bool(tripped)
        assert int(st.flags) & numerics_mod.LOSS_SPIKE

    def test_spike_disarmed_during_warmup(self):
        st = numerics_mod.init_state()
        st, tripped = numerics_mod.observe(st, jnp.float32(100.0), {},
                                           spike_factor=3.0, spike_warmup=5)
        assert not bool(tripped)

    def test_warmup_zero_first_step_not_a_spike(self):
        # spike arming requires a SEEDED ema: with warmup=0 the first
        # positive loss must not trip against the unseeded 0.0 reference
        st = numerics_mod.init_state()
        st, tripped = numerics_mod.observe(st, jnp.float32(5.0), {},
                                           spike_factor=2.0, spike_warmup=0)
        assert not bool(tripped)
        st, tripped = numerics_mod.observe(st, jnp.float32(50.0), {},
                                           spike_factor=2.0, spike_warmup=0)
        assert bool(tripped)
        assert int(st.flags) & numerics_mod.LOSS_SPIKE

    def test_nonfinite_first_loss_does_not_seed_ema(self):
        st = numerics_mod.init_state()
        st, _ = numerics_mod.observe(st, jnp.float32("nan"), {})
        assert int(st.steps) == 0           # finite-loss counter
        st, _ = numerics_mod.observe(st, jnp.float32(3.0), {})
        assert float(st.ema_loss) == pytest.approx(3.0)  # seeded directly

    def test_fp16_overflow_suppresses_grads_bit(self):
        # the DynamicLossScaler's periodic inf grads are its own backoff
        # signal, not a numerics fault — the engine passes overflow as
        # suppress_grads
        st = numerics_mod.init_state()
        st, tripped = numerics_mod.observe(
            st, jnp.float32(1.0), {"g": jnp.array([1.0, jnp.inf])},
            suppress_grads=jnp.bool_(True))
        assert not bool(tripped) and int(st.flags) == 0
        # a nonfinite LOSS still trips even under suppression
        st, tripped = numerics_mod.observe(
            st, jnp.float32("nan"), {"g": jnp.array([jnp.inf])},
            suppress_grads=jnp.bool_(True))
        assert bool(tripped)
        assert int(st.flags) == numerics_mod.NONFINITE_LOSS


# ---------------------------------------------------------------------------
# numerics: engine integration, three actions
# ---------------------------------------------------------------------------


class TestNumericsEngine:
    def test_warn_action_trips_and_dumps_bundle(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="warn",
            numerics_check_steps=1))
        engine.train_batch(batch=_batch(engine))
        obs = get_session()
        assert obs.numerics.trips == 0
        engine.train_batch(batch=_batch(engine, nan=True))
        assert obs.numerics.trips == 1
        trip = obs.numerics.last_trip
        assert "nonfinite" in trip["trip_kind"]
        bundles = glob.glob(str(tmp_path / "crash" / "*numerics*"))
        assert bundles, "numerics trip must dump a flight-record bundle"
        man = json.load(open(os.path.join(bundles[0], "MANIFEST.json")))
        assert man["reason"] == "numerics"
        assert man["extra"]["culprit_rank"] == 0
        assert man["extra"]["step"] == 2
        # warn does NOT protect the params: the NaN update landed, so the
        # next (clean-data) step is genuinely non-finite and re-trips
        engine.train_batch(batch=_batch(engine))
        assert obs.numerics.trips == 2

    def test_skip_step_action_preserves_params(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="skip_step",
            numerics_check_steps=1))
        engine.train_batch(batch=_batch(engine))
        before = jax.device_get(engine.params)
        engine.train_batch(batch=_batch(engine, nan=True))
        after = jax.device_get(engine.params)
        jax.tree.map(np.testing.assert_array_equal, before, after)
        assert get_session().numerics.trips == 1
        # flags cleared after handling: the skipped update kept params
        # finite, so a clean step does not re-trip — and updates params
        engine.train_batch(batch=_batch(engine))
        assert get_session().numerics.trips == 1
        after2 = jax.device_get(engine.params)
        w2 = np.asarray(after2["head"]["w"])
        assert np.isfinite(w2).all()
        assert not np.allclose(w2, np.asarray(after["head"]["w"]))

    def test_warn_action_does_not_skip(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="warn",
            numerics_check_steps=1))
        engine.train_batch(batch=_batch(engine))
        engine.train_batch(batch=_batch(engine, nan=True))
        after = jax.device_get(engine.params)
        assert not np.isfinite(np.asarray(after["head"]["w"])).all()

    def test_abort_action_raises(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="abort",
            numerics_check_steps=1))
        engine.train_batch(batch=_batch(engine))
        with pytest.raises(NumericsTrip) as exc:
            engine.train_batch(batch=_batch(engine, nan=True))
        assert "nonfinite" in str(exc.value)
        assert exc.value.bundle and os.path.isdir(exc.value.bundle)
        # the handled flags were cleared on the raise path: session close
        # must NOT re-report the same trip with a duplicate bundle
        obs = get_session()
        assert int(engine._numerics_state.flags) == 0
        trips_before = obs.numerics.trips
        bundles_before = len(glob.glob(str(tmp_path / "crash" / "*")))
        reset_session()
        assert obs.numerics.trips == trips_before
        assert len(glob.glob(str(tmp_path / "crash" / "*"))) == \
            bundles_before

    def test_happy_path_no_sync_no_extra_dispatch(self, tmp_path):
        """The sentinel must be FUSED: one executable dispatch per step, no
        recompile after warmup, and zero host materialisations between
        cadence checks."""
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="warn",
            numerics_check_steps=100))
        batch = _batch(engine)
        # two warmup steps: the first compiles the step, the second the tiny
        # skipped-counter accumulation op (pre-existing, sentinel-unrelated)
        engine.train_batch(batch=batch)
        engine.train_batch(batch=batch)
        obs = get_session()
        compiled = engine._compiled_step
        calls = []

        def counting_step(*args):
            calls.append(1)
            return compiled(*args)

        engine._compiled_step = counting_step
        compiles_before = sum(
            obs.registry.counter("xla/compiles").series().values())
        for _ in range(3):
            engine.train_batch(batch=batch)
        compiles_after = sum(
            obs.registry.counter("xla/compiles").series().values())
        assert len(calls) == 3          # exactly ONE dispatch per step
        assert compiles_after == compiles_before   # no re-specialisation
        assert obs.numerics.checks == 0  # no host sync before the cadence
        # the pending flag stays a lazy device value on the happy path
        assert isinstance(engine._numerics_state.flags, jax.Array)

    def test_final_window_trip_flushed_on_close(self, tmp_path):
        """A trip AFTER the last cadence check must still be reported when
        the session closes — the silent-NaN-exit the sentinel exists for."""
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="warn",
            numerics_check_steps=100))
        engine.train_batch(batch=_batch(engine, nan=True))
        obs = get_session()
        assert obs.numerics.trips == 0     # cadence (step 100) never hit
        reset_session()                    # closes the session -> flush
        assert obs.numerics.trips == 1
        assert glob.glob(str(tmp_path / "crash" / "*numerics*"))
        del engine

    def test_check_runs_at_cadence(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, numerics_sentinel=True, numerics_action="warn",
            numerics_check_steps=3))
        batch = _batch(engine)
        for _ in range(6):
            engine.train_batch(batch=batch)
        assert get_session().numerics.checks == 2   # steps 3 and 6


# ---------------------------------------------------------------------------
# fleet: straggler + divergence on injected gathers (fake fleet)
# ---------------------------------------------------------------------------


def _fake_table(world=4, step_time=0.1, overrides=None):
    from deepspeed_tpu.observability.fleethealth import HEALTH_STATS

    table = np.zeros((world, len(HEALTH_STATS)))
    table[:, 0] = step_time         # rolling median
    table[:, 1] = step_time         # last
    table[:, 2] = 1.5               # loss
    table[:, 3] = 0.7               # grad_norm
    for (stat, rank), value in (overrides or {}).items():
        table[rank, HEALTH_STATS.index(stat)] = value
    return table


class TestStragglerDetection:
    def _monitor(self, tmp_path, table, **kw):
        reg = MetricsRegistry()
        rec = FlightRecorder(dump_dir=str(tmp_path / "crash"))
        mon = FleetHealthMonitor(
            registry=reg, recorder=rec, cadence_steps=10,
            straggler_factor=2.0, gather_fn=lambda vec: table,
            rank=0, world=table.shape[0], **kw)
        return mon, reg, rec

    def test_fake_clock_straggler_flagged(self, tmp_path):
        # rank 2's injected delay: 10x the fleet median step time
        table = _fake_table(world=4, overrides={("step_time_median_s", 2): 1.0,
                                       ("step_time_last_s", 2): 1.0})
        mon, reg, rec = self._monitor(tmp_path, table)
        mon.note_step_time(0.1)
        summary = mon.aggregate(10)
        assert summary["straggler_rank"] == 2
        assert reg.gauge("fleet/straggler_rank").value() == 2
        assert reg.counter("fleet/straggler_events").value(rank=2) == 1
        kinds = [e["kind"] for e in rec.snapshot()]
        assert "straggler" in kinds
        assert mon.last_straggler_rank == 2

    def test_no_straggler_publishes_minus_one(self, tmp_path):
        mon, reg, _ = self._monitor(tmp_path, _fake_table(world=4))
        mon.aggregate(10)
        assert reg.gauge("fleet/straggler_rank").value() == -1
        assert mon.straggler_events == 0

    def test_fleet_aggregates_published(self, tmp_path):
        table = _fake_table(world=4, overrides={("step_time_median_s", 3): 0.2})
        mon, reg, _ = self._monitor(tmp_path, table)
        mon.aggregate(10)
        g = reg.gauge("fleet/step_time_median_s")
        assert g.value(agg="min") == pytest.approx(0.1)
        assert g.value(agg="max") == pytest.approx(0.2)
        assert g.value(agg="skew") == pytest.approx(1.0)  # (0.2-0.1)/0.1
        for r in range(4):
            assert reg.gauge("fleet/rank_step_time_s").value(rank=r) \
                is not None
        assert reg.gauge("fleet/world").value() == 4

    def test_cadence_gating(self, tmp_path):
        mon, _, _ = self._monitor(tmp_path, _fake_table())
        assert not mon.note_step(7)
        assert mon.aggregations == 0
        assert mon.note_step(20)
        assert mon.aggregations == 1

    def test_divergent_loss_dumps_bundle_naming_rank(self, tmp_path):
        table = _fake_table(world=4, overrides={("loss", 1): 9.0})
        mon, reg, rec = self._monitor(tmp_path, table)
        summary = mon.aggregate(30)
        assert summary["divergence"][0]["culprit_rank"] == 1
        assert reg.counter("fleet/divergence_events").value(stat="loss") == 1
        assert rec.dumps, "divergence must dump a bundle"
        man = json.load(open(os.path.join(rec.dumps[0], "MANIFEST.json")))
        assert man["reason"] == "divergence"
        assert man["extra"]["culprit_rank"] == 1
        assert man["extra"]["step"] == 30
        assert man["extra"]["stat"] == "loss"

    def test_agreeing_fleet_no_divergence(self, tmp_path):
        mon, _, rec = self._monitor(tmp_path, _fake_table(world=4))
        mon.aggregate(10)
        assert mon.divergence_events == 0 and not rec.dumps

    def test_nonzero_rank_counts_but_does_not_dump(self, tmp_path):
        """Every rank sees the same gathered table; only rank 0 dumps and
        logs — N identical bundles per incident would not scale."""
        table = _fake_table(world=4, overrides={("loss", 1): 9.0})
        reg = MetricsRegistry()
        rec = FlightRecorder(dump_dir=str(tmp_path / "crash"))
        mon = FleetHealthMonitor(registry=reg, recorder=rec,
                                 gather_fn=lambda v: table, rank=3, world=4)
        mon.aggregate(10)
        assert mon.divergence_events == 1
        assert reg.counter("fleet/divergence_events").value(stat="loss") == 1
        assert not rec.dumps                      # rank 3 stays quiet
        assert any(e["kind"] == "divergence" for e in rec.snapshot())

    def test_persistent_divergence_dumps_one_bundle(self, tmp_path):
        """Counters keep counting every cadence, but a persistent (same
        stat, same culprit) divergence writes only the FIRST bundle."""
        table = _fake_table(world=4, overrides={("loss", 1): 9.0})
        mon, reg, rec = self._monitor(tmp_path, table)
        mon.aggregate(10)
        mon.aggregate(20)
        mon.aggregate(30)
        assert mon.divergence_events == 3
        assert reg.counter("fleet/divergence_events").value(stat="loss") == 3
        assert len(rec.dumps) == 1
        # a DIFFERENT culprit still gets its own bundle
        table2 = _fake_table(world=4, overrides={("loss", 2): 9.0})
        mon.gather_fn = lambda vec: table2
        mon.aggregate(40)
        assert len(rec.dumps) == 2

    def test_hang_context_names_missing_rank(self, tmp_path):
        seen = {}

        def gather(vec):
            seen.update(mon.hang_context())
            return _fake_table(world=2)

        reg = MetricsRegistry()
        mon = FleetHealthMonitor(registry=reg, gather_fn=gather,
                                 rank=0, world=2)
        mon.last_straggler_rank = 1
        mon.aggregate(40)
        assert seen["in_fleet_gather"] is True
        assert seen["fleet_gather_step"] == 40
        assert "rank 1 never arrived" in seen["note"]
        assert mon.hang_context()["in_fleet_gather"] is False

    def test_gather_failure_never_raises(self, tmp_path):
        def broken(vec):
            raise RuntimeError("gather transport down")

        mon = FleetHealthMonitor(registry=MetricsRegistry(),
                                 gather_fn=broken, rank=0, world=2)
        assert mon.note_step(10) is False   # swallowed, logged


# ---------------------------------------------------------------------------
# fleet: real replica divergence on the CPU mesh (corrupted replica buffer)
# ---------------------------------------------------------------------------


class TestReplicaChecksumDivergence:
    def test_corrupted_replica_named(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, fleet_health=True, fleet_cadence_steps=2,
            fleet_param_checksum=True), zero=0)
        obs = get_session()
        assert obs.fleet is not None and obs.fleet._checksum_fn is not None
        batch = _batch(engine)
        engine.train_batch(batch=batch)
        engine.train_batch(batch=batch)     # cadence step: clean fleet
        assert obs.fleet.aggregations == 1
        assert obs.fleet.divergence_events == 0

        # simulate SDC: corrupt ONE data-parallel replica's copy of a
        # replicated param (per-device buffers of a replicated jax.Array)
        leaf = engine.params["linear_0"]["w"]
        culprit_dev = engine.mesh.devices[0, 0, 3, 0, 0]   # data index 3
        shards = []
        for shard in leaf.addressable_shards:
            buf = np.array(shard.data)
            if shard.device == culprit_dev:
                buf[0, 0] += 100.0
            shards.append(jax.device_put(buf, shard.device))
        engine.params["linear_0"]["w"] = \
            jax.make_array_from_single_device_arrays(
                leaf.shape, leaf.sharding, shards)

        summary = obs.fleet.aggregate(4)
        div = summary["divergence"]
        assert div and div[0]["stat"] == "param_checksum"
        # a data-axis REPLICA index, deliberately not labeled a rank
        assert div[0]["culprit_replica"] == 3
        bundles = glob.glob(str(tmp_path / "crash" / "*divergence*"))
        assert bundles
        man = json.load(open(os.path.join(bundles[0], "MANIFEST.json")))
        assert man["extra"]["culprit_replica"] == 3
        assert man["extra"]["step"] == 4

    def test_checksum_probe_refused_for_zero3(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, fleet_health=True, fleet_param_checksum=True), zero=3)
        assert get_session().fleet._checksum_fn is None
        del engine


# ---------------------------------------------------------------------------
# disabled-path wiring + report CLI
# ---------------------------------------------------------------------------


class TestWiring:
    def test_disabled_gates_wire_nothing(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(tmp_path))
        obs = get_session()
        assert obs.fleet is None and obs.numerics is None
        assert engine._numerics is None and engine._numerics_state is None
        if obs.hang is not None:
            assert obs.hang.context_fn is None
        # the step runs with an empty numerics slot
        engine.train_batch(batch=_batch(engine))
        assert engine._numerics_state is None

    def test_fully_disabled_session(self, tmp_path):
        engine = _engine(tmp_path, obs=None)
        obs = engine._obs
        assert not obs.enabled
        assert obs.fleet is None and obs.numerics is None
        engine.train_batch(batch=_batch(engine))

    def test_engine_fleet_note_step_cadence(self, tmp_path):
        engine = _engine(tmp_path, _obs_cfg(
            tmp_path, fleet_health=True, fleet_cadence_steps=2))
        obs = get_session()
        batch = _batch(engine)
        for _ in range(4):
            engine.train_batch(batch=batch)
        assert obs.fleet.aggregations == 2
        # the engine's loss/grad-norm made it into the fleet table
        assert obs.registry.gauge("fleet/loss").value(agg="median") \
            is not None
        assert obs.registry.gauge("fleet/grad_norm").value(agg="median") \
            is not None


class TestReportCLI:
    def _dump(self, tmp_path, reg):
        path = str(tmp_path / "metrics.jsonl")
        reg.dump_jsonl(path)
        return path

    def test_fleet_section(self, tmp_path, capsys):
        from deepspeed_tpu.observability.report import main

        table = _fake_table(world=4, overrides={("step_time_median_s", 2): 1.0})
        reg = MetricsRegistry()
        mon = FleetHealthMonitor(registry=reg, gather_fn=lambda v: table,
                                 rank=0, world=4)
        mon.aggregate(10)
        rc = main([self._dump(tmp_path, reg)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== fleet ==" in out and "ranks=4" in out
        assert "step_time skew" in out
        assert "!! straggler: rank 2" in out
        assert "straggler incidents [rank 2]: 1" in out
        # four per-rank rows
        for r in range(4):
            assert f"\n{r} " in out or out.count(f"{r}  ") >= 1

    def test_no_fleet_records_no_section(self, tmp_path, capsys):
        from deepspeed_tpu.observability.report import main

        reg = MetricsRegistry()
        reg.gauge("Train/Samples/train_loss").set(1.0)
        main([self._dump(tmp_path, reg)])
        assert "== fleet ==" not in capsys.readouterr().out

    def test_crash_dump_surfaces_culprit_rank(self, tmp_path, capsys):
        from deepspeed_tpu.observability.report import main

        rec = FlightRecorder(dump_dir=str(tmp_path / "crash"))
        bundle = rec.dump(reason="divergence",
                          extra={"culprit_rank": 5, "step": 12,
                                 "stat": "grad_norm"})
        rc = main(["--crash-dump", bundle])
        out = capsys.readouterr().out
        assert rc == 0
        assert "culprit: rank 5 (grad_norm, step 12)" in out

    def test_crash_dump_fleet_gather_note(self, tmp_path, capsys):
        from deepspeed_tpu.observability.report import main

        rec = FlightRecorder(dump_dir=str(tmp_path / "crash"))
        bundle = rec.dump(reason="hang", extra={
            "in_fleet_gather": True, "fleet_gather_step": 30,
            "note": "blocked in the step-30 fleet gather — rank 2 never "
                    "arrived"})
        main(["--crash-dump", bundle])
        out = capsys.readouterr().out
        assert "rank 2 never arrived" in out
