"""Hybrid engine tests — reference tests/unit/hybrid_engine concerns: one
weight set serves both train_batch and generate, generation reflects
training updates, ZeRO-3/pipelined layouts flip correctly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import create_model
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
from deepspeed_tpu.config.config import load_config

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests



def _hybrid(zero=0, pp=1, **cfg_extra):
    model = create_model("tiny", dtype=jnp.float32, max_seq_len=128)
    cfg = load_config({
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-2}},
        "zero_optimization": {"stage": zero},
        "parallel": {"pipeline_parallel_size": pp},
        **cfg_extra,
    })
    return HybridEngine(model=model, config=cfg, max_out_tokens=128)


def _batch(engine, seed=0):
    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    ids = jax.random.randint(jax.random.PRNGKey(seed), (gas, gb, 32), 0, 250)
    return {"input_ids": ids}


def test_generate_uses_current_weights():
    engine = _hybrid()
    prompt = np.arange(10)[None]
    before = np.asarray(engine.generate(prompt, max_new_tokens=6))
    # generation matches a plain forward greedy loop on the SAME weights
    ids = jnp.asarray(prompt, jnp.int32)
    for i in range(3):
        logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1)
        assert int(nxt[0]) == before[0, i]
        ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)], 1)

    # big-LR training must change the generation (weights really flip)
    for _ in range(20):
        engine.train_batch(batch=_batch(engine))
    after = np.asarray(engine.generate(prompt, max_new_tokens=6))
    assert not np.array_equal(before, after)


def test_moe_policy_generate_over_expert_parallel():
    """RLHF over an MoE actor: train under ep=2, generate through the MoE
    inference side (which inherits the training expert degree), and verify
    training really changes generation."""
    model = create_model("moe-tiny", dtype=jnp.float32, max_seq_len=128,
                         moe_drop_tokens=False)
    cfg = load_config({
        "train_micro_batch_size_per_gpu": 4,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-2}},
        "zero_optimization": {"stage": 2},
        "parallel": {"expert_parallel_size": 2, "data_parallel_size": 8},
    })
    engine = HybridEngine(model=model, config=cfg, max_out_tokens=128)
    prompt = np.arange(10)[None]
    before = np.asarray(engine.generate(prompt, max_new_tokens=5))
    # generation side runs expert-parallel
    assert int(engine._infer.mesh.shape.get("expert", 1)) == 2
    # greedy parity with a plain forward loop on the same weights
    ids = jnp.asarray(prompt, jnp.int32)
    for i in range(3):
        logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1)
        assert int(nxt[0]) == before[0, i]
        ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)], 1)
    for _ in range(15):
        engine.train_batch(batch=_batch(engine))
    after = np.asarray(engine.generate(prompt, max_new_tokens=5))
    assert not np.array_equal(before, after)


def test_zero3_flip():
    engine = _hybrid(zero=3, parallel={"data_parallel_size": 8})
    engine.train_batch(batch=_batch(engine))
    out = engine.generate(np.arange(8)[None], max_new_tokens=4)
    assert np.asarray(out).shape == (1, 4)
    # inference params are the merged/replicated view of the fsdp weights
    wq_train = engine.params["layers"]["attn"]["wq"]
    wq_infer = engine._infer.params["layers"]["attn"]["wq"]
    np.testing.assert_allclose(np.asarray(wq_infer), np.asarray(wq_train),
                               atol=1e-6)


def test_pipelined_flip():
    engine = _hybrid(pp=2, gradient_accumulation_steps=2)
    engine.train_batch(batch=_batch(engine))
    out = engine.generate(np.arange(8)[None], max_new_tokens=4)
    assert np.asarray(out).shape == (1, 4)
    # stage-stacked layers were merged back to (L, ...) for inference
    assert engine._infer.params["layers"]["attn"]["wq"].ndim == 3


def test_lora_fuse_unfuse_roundtrip():
    """Reference hybrid_engine.py:121-154: W +-= scaling * right@left; fuse
    then unfuse restores the originals, and generate() serves the ADAPTED
    weights without touching the training tree."""
    engine = _hybrid()

    L, H = 2, 64
    r = 4
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    right = jax.random.normal(k1, (L, H, r), jnp.float32) * 0.1
    left = jax.random.normal(k2, (L, r, H), jnp.float32) * 0.1
    engine.set_lora({"attn/wq": (right, left)}, scaling=0.5)

    w0 = np.asarray(engine.params["layers"]["attn"]["wq"])
    # generate serves fused weights; training tree untouched
    exported = engine._export_params()
    want = w0 + 0.5 * np.einsum("lir,lro->lio", np.asarray(right),
                                np.asarray(left))
    np.testing.assert_allclose(np.asarray(exported["layers"]["attn"]["wq"]),
                               want, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(engine.params["layers"]["attn"]["wq"]), w0)

    prompt = np.arange(8)[None]
    base_out = np.asarray(engine.generate(prompt, max_new_tokens=4))
    engine.set_lora({"attn/wq": (right * 0, left * 0)}, scaling=0.5)
    zero_out = np.asarray(engine.generate(prompt, max_new_tokens=4))
    # zero adapters == no adapters; nonzero adapters changed generation
    engine._lora = None
    engine._infer_params_step = -1
    none_out = np.asarray(engine.generate(prompt, max_new_tokens=4))
    np.testing.assert_array_equal(zero_out, none_out)

    # in-place fuse/unfuse roundtrip
    engine.set_lora({"attn/wq": (right, left)}, scaling=0.5)
    engine.fuse_lora_weight()
    np.testing.assert_allclose(
        np.asarray(engine.params["layers"]["attn"]["wq"]), want,
        rtol=1e-5, atol=1e-6)
    engine.unfuse_lora_weight()
    np.testing.assert_allclose(
        np.asarray(engine.params["layers"]["attn"]["wq"]), w0,
        rtol=1e-5, atol=1e-6)


def test_fused_save_guard(tmp_path):
    """ADVICE r3: saving while LoRA is fused would persist fused bf16
    params alongside the UNFUSED fp32 master — an internally inconsistent
    checkpoint. Both save paths must refuse, mirroring train_batch."""
    import pytest

    engine = _hybrid()
    L, H, r = 2, 64, 4
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    engine.set_lora({"attn/wq": (
        jax.random.normal(k1, (L, H, r), jnp.float32) * 0.1,
        jax.random.normal(k2, (L, r, H), jnp.float32) * 0.1)})
    engine.fuse_lora_weight()
    with pytest.raises(RuntimeError, match="unfuse"):
        engine.save_checkpoint(str(tmp_path))
    with pytest.raises(RuntimeError, match="unfuse"):
        engine.save_16bit_model(str(tmp_path))
    engine.unfuse_lora_weight()
    engine.save_checkpoint(str(tmp_path))   # unfused saves fine
