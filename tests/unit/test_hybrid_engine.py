"""Hybrid engine tests — reference tests/unit/hybrid_engine concerns: one
weight set serves both train_batch and generate, generation reflects
training updates, ZeRO-3/pipelined layouts flip correctly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import create_model
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
from deepspeed_tpu.config.config import load_config

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests



def _hybrid(zero=0, pp=1, **cfg_extra):
    model = create_model("tiny", dtype=jnp.float32, max_seq_len=128)
    cfg = load_config({
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-2}},
        "zero_optimization": {"stage": zero},
        "parallel": {"pipeline_parallel_size": pp},
        **cfg_extra,
    })
    return HybridEngine(model=model, config=cfg, max_out_tokens=128)


def _batch(engine, seed=0):
    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    ids = jax.random.randint(jax.random.PRNGKey(seed), (gas, gb, 32), 0, 250)
    return {"input_ids": ids}


def test_generate_uses_current_weights():
    engine = _hybrid()
    prompt = np.arange(10)[None]
    before = np.asarray(engine.generate(prompt, max_new_tokens=6))
    # generation matches a plain forward greedy loop on the SAME weights
    ids = jnp.asarray(prompt, jnp.int32)
    for i in range(3):
        logits, _ = engine.model.apply(engine.params, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1)
        assert int(nxt[0]) == before[0, i]
        ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)], 1)

    # big-LR training must change the generation (weights really flip)
    for _ in range(20):
        engine.train_batch(batch=_batch(engine))
    after = np.asarray(engine.generate(prompt, max_new_tokens=6))
    assert not np.array_equal(before, after)


def test_zero3_flip():
    engine = _hybrid(zero=3, parallel={"data_parallel_size": 8})
    engine.train_batch(batch=_batch(engine))
    out = engine.generate(np.arange(8)[None], max_new_tokens=4)
    assert np.asarray(out).shape == (1, 4)
    # inference params are the merged/replicated view of the fsdp weights
    wq_train = engine.params["layers"]["attn"]["wq"]
    wq_infer = engine._infer.params["layers"]["attn"]["wq"]
    np.testing.assert_allclose(np.asarray(wq_infer), np.asarray(wq_train),
                               atol=1e-6)


def test_pipelined_flip():
    engine = _hybrid(pp=2, gradient_accumulation_steps=2)
    engine.train_batch(batch=_batch(engine))
    out = engine.generate(np.arange(8)[None], max_new_tokens=4)
    assert np.asarray(out).shape == (1, 4)
    # stage-stacked layers were merged back to (L, ...) for inference
    assert engine._infer.params["layers"]["attn"]["wq"].ndim == 3
