"""Transformer model tests: shapes, causality, KV-cache consistency, loss
masking, and logical-axis spec resolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import (create_model, cross_entropy_loss,
                                  resolve_param_specs, param_count)
from deepspeed_tpu.models.transformer import (TransformerConfig, build_model,
                                              forward, init_params)


@pytest.fixture(scope="module", params=["tiny", "tiny-llama"])
def model(request):
    return create_model(request.param)


def _batch(cfg, b=2, s=16, seed=0):
    rng = jax.random.PRNGKey(seed)
    return {"input_ids": jax.random.randint(rng, (b, s), 0, cfg.vocab_size)}


def test_forward_shapes(model):
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0))
    logits, cache = model.apply(params, _batch(cfg))
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert cache is None
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_causality(model):
    """Changing a future token must not change past logits."""
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(cfg)
    logits1, _ = model.apply(params, batch)
    ids2 = batch["input_ids"].at[:, -1].set((batch["input_ids"][:, -1] + 1) % cfg.vocab_size)
    logits2, _ = model.apply(params, {"input_ids": ids2})
    np.testing.assert_allclose(np.asarray(logits1[:, :-1], np.float32),
                               np.asarray(logits2[:, :-1], np.float32), atol=1e-5)
    assert not np.allclose(np.asarray(logits1[:, -1], np.float32),
                           np.asarray(logits2[:, -1], np.float32))


@pytest.mark.slow
def test_kv_cache_matches_full_forward(model):
    """Prefill + token-by-token decode must reproduce the full forward — the
    correctness contract of the reference's KV-cache kernels
    (csrc/transformer/inference transform.cu KV append)."""
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(cfg, b=2, s=12)
    full_logits, _ = model.apply(params, batch)

    T_max = 16
    L, B = cfg.num_layers, 2
    cache = {
        "k": jnp.zeros((L, B, T_max, cfg.num_kv_heads, cfg.head_dim), cfg.dtype),
        "v": jnp.zeros((L, B, T_max, cfg.num_kv_heads, cfg.head_dim), cfg.dtype),
        "index": jnp.zeros((L,), jnp.int32),
    }
    # prefill on first 8 tokens
    prefill_logits, cache = model.apply(
        params, {"input_ids": batch["input_ids"][:, :8]}, cache=cache, start_pos=0)
    np.testing.assert_allclose(np.asarray(prefill_logits, np.float32),
                               np.asarray(full_logits[:, :8], np.float32),
                               atol=2e-4, rtol=1e-3)
    # decode tokens 8..11 one at a time
    for t in range(8, 12):
        step_logits, cache = model.apply(
            params, {"input_ids": batch["input_ids"][:, t:t + 1]}, cache=cache,
            start_pos=t)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0], np.float32),
                                   np.asarray(full_logits[:, t], np.float32),
                                   atol=2e-4, rtol=1e-3)


def test_padding_mask(model):
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(cfg, b=1, s=8)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]])
    logits_masked, _ = model.apply(params, {**batch, "attention_mask": mask})
    # perturb a masked-out position; unmasked logits must not move
    ids2 = batch["input_ids"].at[:, 5].set((batch["input_ids"][:, 5] + 7) % cfg.vocab_size)
    logits2, _ = model.apply(params, {"input_ids": ids2, "attention_mask": mask})
    np.testing.assert_allclose(np.asarray(logits_masked[:, :4], np.float32),
                               np.asarray(logits2[:, :4], np.float32), atol=1e-5)


@pytest.mark.slow
def test_loss_decreases_with_training():
    model = create_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(model.config, b=4, s=32)

    loss_g = jax.jit(jax.value_and_grad(model.loss_fn))
    loss0, grads = loss_g(params, batch)
    # plain SGD steps on the same batch must reduce loss
    for _ in range(10):
        loss, grads = loss_g(params, batch)
        params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
    loss1, _ = loss_g(params, batch)
    assert float(loss1) < float(loss0)


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((1, 4, 10))
    labels = jnp.array([[1, 2, -100, -100]])
    loss = cross_entropy_loss(logits, labels)
    # uniform logits -> log(10) per counted token
    assert float(loss) == pytest.approx(np.log(10), rel=1e-5)


@pytest.mark.slow
def test_remat_matches(model):
    cfg_remat = TransformerConfig(**{**model.config.__dict__, "remat": True})
    m2 = build_model(cfg_remat)
    params = model.init(jax.random.PRNGKey(0))
    batch = _batch(model.config)
    l1 = jax.jit(model.loss_fn)(params, batch)
    l2 = jax.jit(m2.loss_fn)(params, batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.jit(jax.grad(model.loss_fn))(params, batch)
    g2 = jax.jit(jax.grad(m2.loss_fn))(params, batch)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), g1, g2)


def test_param_specs_tp_and_fsdp(model):
    params = model.init(jax.random.PRNGKey(0))
    specs = resolve_param_specs(params, model.axes, fsdp_axis="data", fsdp_min_size=1)
    flat = jax.tree_util.tree_leaves_with_path(specs)
    # attention qkv sharded over model axis on the heads dim
    d = dict((jax.tree_util.keystr(k), v) for k, v in flat)
    wq_key = [k for k in d if "wq" in k][0]
    assert d[wq_key] == P(None, "data", "model")
    tok_key = [k for k in d if "tokens" in k][0]
    assert d[tok_key] == P("model", "data")


@pytest.mark.slow
def test_param_count_presets():
    m = create_model("gpt2-125m")
    params = m.init(jax.random.PRNGKey(0))
    n = param_count(params)
    assert 115e6 < n < 135e6  # ~124M


@pytest.mark.slow
class TestDropout:
    """cfg.dropout applies at embed/attn-out/mlp-out when the train engine
    enables it; eval and decode stay deterministic (reference transformer
    kernel dropout semantics minus in-kernel attention-prob dropout — see
    TransformerConfig.dropout)."""

    def test_changes_training_forward_only_when_enabled(self):
        from deepspeed_tpu.models import create_model

        base = create_model("tiny")
        params = base.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 250)
        out0, _ = base.apply(params, {"input_ids": ids})

        off = create_model("tiny", dropout=0.5)           # rate set, not enabled
        out_off, _ = off.apply(params, {"input_ids": ids})
        np.testing.assert_array_equal(np.asarray(out_off), np.asarray(out0))

        on = create_model("tiny", dropout=0.5, dropout_enabled=True)
        out_on, _ = on.apply(params, {"input_ids": ids})
        assert not np.allclose(np.asarray(out_on), np.asarray(out0))
        assert np.isfinite(np.asarray(out_on)).all()

    def test_engine_enables_eval_disables(self):
        import deepspeed_tpu
        from deepspeed_tpu.models import create_model

        model = create_model("tiny", dropout=0.3)
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "steps_per_print": 1000,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
        assert engine.model.config.dropout_enabled
        ids = jax.random.randint(jax.random.PRNGKey(0),
                                 (1, engine.train_batch_size(), 16), 0, 250)
        l1 = float(engine.train_batch(batch={"input_ids": ids}))
        assert np.isfinite(l1)
        # eval is deterministic and dropout-free: matches a dropout-0 model
        ev_batch = jax.tree.map(lambda x: x[0], {"input_ids": ids})
        ev = float(engine.eval_loss(ev_batch))
        ref = create_model("tiny")
        ref_loss = float(jax.jit(ref.loss_fn)(engine.params, ev_batch))
        np.testing.assert_allclose(ev, ref_loss, rtol=1e-6)
        assert engine.model.config.dropout_enabled  # restored after eval


@pytest.mark.parametrize("kw", [
    dict(),                                             # gelu + layernorm
    dict(activation="swiglu", norm="rmsnorm", position="rope",
         tie_embeddings=False),
    dict(moe_num_experts=4, moe_use_residual=True),
])
def test_init_layer_block_matches_init_slice(kw):
    """Load-bearing contract for ZeRO-3 param offload: Model.init_layer_block
    (rng, lo, blen) must be BIT-IDENTICAL to the corresponding slice of
    init(rng)["layers"] — pinned-host runs init one block at a time and must
    train from exactly the weights the resident engine would."""
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  build_model)

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, num_layers=5,
                            num_heads=2, max_seq_len=16, **kw)
    model = build_model(cfg)
    rng = jax.random.PRNGKey(42)
    full = model.init(rng)["layers"]
    for lo, blen in ((0, 2), (2, 2), (4, 1), (0, 5)):
        # reuse is the contract under test: block init must be bit-identical
        # to full init under the SAME key. tpulint: disable=key-reuse
        blk = model.init_layer_block(rng, lo, blen)
        want = jax.tree.map(lambda l: l[lo:lo + blen], full)
        for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_flatten_with_path(blk)[0],
                jax.tree_util.tree_flatten_with_path(want)[0]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{jax.tree_util.keystr(pa)} [{lo}:{lo + blen}]")


def test_remat_policy_knobs():
    """remat_policy surface incl. the cpu_checkpointing analog
    ('offload-dots' — saved dots live in pinned host memory; functional
    equivalence validated on real TPU, docs/offload_design.md)."""
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  resolve_remat_policy)

    assert resolve_remat_policy(TransformerConfig(remat_policy="full")) is None
    assert resolve_remat_policy(
        TransformerConfig(remat_policy="dots")) is not None
    assert resolve_remat_policy(
        TransformerConfig(remat_policy="offload-dots")) is not None
