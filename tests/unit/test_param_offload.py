"""ZeRO-3 parameter offload tests (runtime/param_offload.py).

The bar (VERDICT r2 #1): a model whose params live off-device runs
train_batch with trajectory equivalence against the resident engine, the
NVMe tier streams through aio files, and checkpoints round-trip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer import TransformerConfig, build_model
from deepspeed_tpu.parallel import mesh as mesh_mod

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests



def _model():
    return build_model(TransformerConfig(
        vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, tie_embeddings=True))


def _cfg(extra_zero=None, **kw):
    zero = {"stage": 3}
    zero.update(extra_zero or {})
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "steps_per_print": 1000,
           "optimizer": {"type": "adamw",
                         "params": {"lr": 5e-3, "weight_decay": 0.01}},
           "zero_optimization": zero}
    cfg.update(kw)
    return cfg


def _batch(gas=1, mb=8, S=32, seed=0):
    # mb is the GLOBAL micro batch: micro_batch_per_gpu (1) x dp world (8)
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 128, (gas, mb, S))}


def _run(config, steps=4, gas=1, seed=0):
    mesh_mod.reset_mesh()
    engine, *_ = ds.initialize(model=_model(), config=config,
                               rng=jax.random.PRNGKey(7))
    losses = [float(engine.train_batch(batch=_batch(gas=gas, seed=seed + i)))
              for i in range(steps)]
    return engine, losses


class TestParamOffloadCPU:
    def test_trajectory_matches_resident_engine(self):
        _, base = _run(_cfg(), steps=4)
        eng, off = _run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}), steps=4)
        # buffer_size=1 byte => 1 layer per block => 4 blocks
        assert eng._param_offload.num_blocks == 4
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        # fused path must report a real grad norm, not 0
        with eng.mesh:
            batch = eng._globalize_batch(_batch(seed=99), leading_gas=True)
            _, gn, _ = eng._param_offload.train_step(batch)
        assert gn > 0.0

    def test_zero_to_fp32_consolidation_uses_offload_masters(self):
        """ds-tpu-zero-to-fp32 over an OFFLOAD checkpoint: the offline
        consolidator must pick the fp32 masters from the layer_master/
        res_master layout, not fall back to bf16-rounded params."""
        import tempfile

        from deepspeed_tpu.runtime.checkpoint import (consolidate_checkpoint,
                                                      load_flat_weights)

        cfg = _cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}})
        cfg["bf16"] = {"enabled": True}
        mesh_mod.reset_mesh()
        model = build_model(TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=32, dtype=jnp.bfloat16, tie_embeddings=True))
        engine, *_ = ds.initialize(model=model, config=cfg,
                                   rng=jax.random.PRNGKey(7))
        engine.train_batch(batch=_batch())
        d = tempfile.mkdtemp()
        engine.save_checkpoint(d, tag="t1")
        out = consolidate_checkpoint(d, f"{d}/fp32")   # no .npz on purpose
        assert out.endswith(".npz")
        flat = load_flat_weights(out)
        ex = engine._param_offload
        # resident master exact
        np.testing.assert_array_equal(
            flat["embed##tokens"],
            np.asarray(jax.device_get(ex._res_master["embed"]["tokens"]),
                       np.float32))
        # a layer master exact (flatten-order list layout)
        masters = ex._opt_leaves_np("master")
        lkeys = [k for k in flat if k.startswith("layers##")]
        got = flat[lkeys[0]]
        np.testing.assert_array_equal(got, np.asarray(masters[0], np.float32))
        # masters differ from the bf16-rounded params (non-vacuous)
        p = np.asarray(ex._block_host_leaves(0)[0], np.float32)
        assert np.abs(np.asarray(masters[0][:1], np.float32) - p[:1]).max() > 0

    def test_stream_stats_and_overlap_report(self):
        """VERDICT r4 #5 instrumentation: every step records streamed bytes
        + achieved bandwidth, and overlap_report produces the fetch/compute/
        step decomposition with sane bounds."""
        eng, _ = _run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}), steps=2)
        ex = eng._param_offload
        stats = ex.last_step_stats
        assert stats is not None and stats["wall_s"] > 0
        # fused path: fwd fetches all blocks, bwd all but the last
        P = sum(ex._block_bytes)
        elems = sum(ex._block_elems)
        assert stats["h2d_bytes"] == 2 * P - ex._block_bytes[-1] + 12 * elems
        assert stats["d2h_bytes"] == P + 12 * elems
        assert stats["achieved_h2d_gbps"] > 0
        with eng.mesh:
            peak = ex.measure_stream_peak(sweeps=1)
            assert peak > 0
            batch = eng._globalize_batch(_batch(seed=3), leading_gas=True)
            rep = ex.overlap_report(batch)
        assert 0.0 <= rep["overlap_efficiency"] <= 1.0
        assert rep["t_fetch_s"] > 0 and rep["t_compute_s"] > 0
        assert rep["h2d_utilization"] > 0
        assert rep["t_step_s"] >= 0

    def test_multi_layer_blocks_and_remainder(self):
        eng, off = _run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 10**9}}),
            steps=3)
        assert eng._param_offload.num_blocks == 1
        _, base = _run(_cfg(), steps=3)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        # remainder block: 4 layers in blocks of 3 -> (3, 1)
        mesh_mod.reset_mesh()
        m = _model()
        eng3, *_ = ds.initialize(model=m, config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 3 * 9000}}),
            rng=jax.random.PRNGKey(7))
        po = eng3._param_offload
        if po.num_blocks > 1:          # depends on per-layer bytes
            assert po._bounds[-1][1] == 4
        l0 = float(eng3.train_batch(batch=_batch()))
        assert np.isfinite(l0)

    def test_gas_accumulation_path(self):
        cfg = _cfg(gradient_accumulation_steps=2)
        _, base = _run(cfg, steps=3, gas=2)
        cfg_off = _cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}},
            gradient_accumulation_steps=2)
        _, off = _run(cfg_off, steps=3, gas=2)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)

    def test_grad_clip_path(self):
        cfg = _cfg(gradient_clipping=0.01)
        _, base = _run(cfg, steps=3)
        cfg_off = _cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}},
            gradient_clipping=0.01)
        eng, off = _run(cfg_off, steps=3)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)

    def test_type_embed_trajectory_and_grads(self):
        """ADVICE r3 (medium): segment embeddings (type_vocab_size>0) must
        flow through the offload executor's embed segment — same trajectory
        as the resident engine, and type_embed row 0 actually updates."""
        def m():
            return build_model(TransformerConfig(
                vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=32, dtype=jnp.float32, type_vocab_size=2))

        def run(config, steps=3):
            mesh_mod.reset_mesh()
            eng, *_ = ds.initialize(model=m(), config=config,
                                    rng=jax.random.PRNGKey(7))
            ls = [float(eng.train_batch(batch=_batch(seed=i)))
                  for i in range(steps)]
            return eng, ls

        eng_base, base = run(_cfg())
        eng, off = run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}))
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        te_base = np.asarray(eng_base.params["type_embed"], np.float32)
        te_off = np.asarray(eng._param_offload.resident["type_embed"],
                            np.float32)
        np.testing.assert_allclose(te_off, te_base, rtol=1e-4, atol=1e-5)
        init_te = np.asarray(m().init(jax.random.PRNGKey(7))["type_embed"])
        assert np.abs(te_off[0] - init_te[0]).max() > 1e-5  # row 0 trained

    def test_fp16_trajectory_and_overflow_skip(self):
        """VERDICT r3 #4: offload_param x fp16 dynamic loss scaling. The
        scaled seed flows through every block vjp; an overflow step skips
        BEFORE any streamed update commits and halves the scale — same
        trajectory (losses, scale, skip pattern) as the resident fp16
        engine."""
        def run(offload):
            mesh_mod.reset_mesh()
            zero = {"stage": 3}
            if offload:
                zero["offload_param"] = {"device": "cpu", "buffer_size": 1}
            cfg = {"train_micro_batch_size_per_gpu": 1,
                   "gradient_accumulation_steps": 1, "steps_per_print": 1000,
                   "optimizer": {"type": "adamw",
                                 "params": {"lr": 5e-3}},
                   # huge initial scale => guaranteed fp16 overflow on step
                   # 1, then recovery: exercises the skip path end-to-end
                   "fp16": {"enabled": True, "initial_scale_power": 36,
                            "hysteresis": 1},
                   "zero_optimization": zero}
            eng, *_ = ds.initialize(model=_model(), config=cfg,
                                    rng=jax.random.PRNGKey(7))
            out = []
            for i in range(4):
                loss = float(eng.train_batch(batch=_batch(seed=i)))
                out.append((loss, float(eng.scaler_state.scale),
                            int(eng.skipped_steps)))
            return out

        res = run(offload=False)
        off = run(offload=True)
        assert res[0][2] >= 1, f"overflow never triggered: {res}"
        for (lr_, sr, kr), (lo_, so, ko) in zip(res, off):
            assert sr == so, (res, off)        # identical scale schedule
            assert kr == ko, (res, off)        # identical skip pattern
            np.testing.assert_allclose(lo_, lr_, rtol=2e-3, atol=2e-3)

    def test_moe_trajectory_matches_resident(self):
        """VERDICT r3 #4: offload_param x MoE — expert leaves stream
        through the block executor and the aux loss (with its router
        gradient) survives the segmented step."""
        def moe_model():
            return build_model(TransformerConfig(
                vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=32, dtype=jnp.float32, moe_num_experts=4,
                moe_top_k=2, moe_aux_loss_coef=0.01))

        def run(offload, steps=3):
            mesh_mod.reset_mesh()
            zero = {"stage": 3}
            if offload:
                zero["offload_param"] = {"device": "cpu", "buffer_size": 1}
            eng, *_ = ds.initialize(
                model=moe_model(),
                config=_cfg(extra_zero=zero.get("offload_param") and {
                    "offload_param": zero["offload_param"]} or {}),
                rng=jax.random.PRNGKey(7))
            return [float(eng.train_batch(batch=_batch(seed=i)))
                    for i in range(steps)]

        base = run(offload=False)
        off = run(offload=True)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        # the aux loss is actually present (a zero-aux bug would also match
        # a broken resident, so pin it against a no-aux config)
        mesh_mod.reset_mesh()
        no_aux = build_model(TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=32, dtype=jnp.float32, moe_num_experts=4,
            moe_top_k=2, moe_aux_loss_coef=0.0))
        eng, *_ = ds.initialize(model=no_aux, config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(7))
        l0 = float(eng.train_batch(batch=_batch(seed=0)))
        assert abs(l0 - off[0]) > 1e-6   # coef=0.01 shifts the loss

    def test_eval_matches_resident(self):
        mesh_mod.reset_mesh()
        e1, _ = _run(_cfg(), steps=1)
        ev1 = float(e1.eval_loss(jax.tree.map(lambda x: x[0], _batch(seed=9))))
        e2, _ = _run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}), steps=1)
        ev2 = float(e2.eval_loss(jax.tree.map(lambda x: x[0], _batch(seed=9))))
        np.testing.assert_allclose(ev2, ev1, rtol=2e-4)

    def test_checkpoint_roundtrip(self, tmp_path):
        eng, losses = _run(_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}), steps=2)
        eng.save_checkpoint(str(tmp_path / "ck"))
        cont = [float(eng.train_batch(batch=_batch(seed=2 + i)))
                for i in range(2)]

        mesh_mod.reset_mesh()
        eng2, *_ = ds.initialize(
            model=_model(),
            config=_cfg(extra_zero={
                "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(0))    # different init — load overwrites
        eng2.load_checkpoint(str(tmp_path / "ck"))
        assert eng2.global_steps == 2
        resumed = [float(eng2.train_batch(batch=_batch(seed=2 + i)))
                   for i in range(2)]
        np.testing.assert_allclose(resumed, cont, rtol=2e-4, atol=2e-5)

    def test_gates(self):
        mesh_mod.reset_mesh()
        with pytest.raises(ValueError, match="stage 3"):
            ds.initialize(model=_model(), config=_cfg(
                extra_zero={"stage": 1,
                            "offload_param": {"device": "cpu"}}))
        mesh_mod.reset_mesh()
        with pytest.raises(ValueError, match="Adam family"):
            ds.initialize(model=_model(), config={
                **_cfg(extra_zero={"offload_param": {"device": "cpu"}}),
                "optimizer": {"type": "sgd", "params": {"lr": 1e-3}}})
        mesh_mod.reset_mesh()
        with pytest.raises(ValueError, match="subsumes"):
            ds.initialize(model=_model(), config=_cfg(extra_zero={
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"}}))
    def test_compression_qat_trajectory_matches_resident(self):
        """offload_param x compression (weight + activation QAT): the block
        programs apply the SAME per-layer-scale transform and rebuild at
        schedule boundaries — trajectory matches the resident engine
        across a boundary crossing."""
        comp = {"compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2},
                "different_groups": {
                    "g0": {"params": {"start_bits": 6, "target_bits": 6},
                           "modules": ["layers"]}}},
            "activation_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 3},
                "different_groups": {
                    "g0": {"params": {"bits": 8}, "modules": ["*"]}}}}}

        def run(offload, steps=5):
            mesh_mod.reset_mesh()
            cfg = {**_cfg(extra_zero=(
                {"offload_param": {"device": "cpu", "buffer_size": 1}}
                if offload else {})), **comp}
            eng, *_ = ds.initialize(model=_model(), config=cfg,
                                    rng=jax.random.PRNGKey(7))
            return [float(eng.train_batch(batch=_batch(seed=i)))
                    for i in range(steps)]

        base = run(offload=False)
        off = run(offload=True)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        # the boundary actually bit: a no-compression run diverges by step 5
        mesh_mod.reset_mesh()
        eng, *_ = ds.initialize(model=_model(), config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(7))
        plain = [float(eng.train_batch(batch=_batch(seed=i)))
                 for i in range(5)]
        assert abs(plain[-1] - off[-1]) > 1e-6

    def test_pld_trajectory_matches_resident(self):
        """offload_param x progressive_layer_drop: the block programs apply
        the SAME activation-derived stochastic-depth gate at the global
        layer index, so the trajectory matches the resident engine."""
        def run(offload, steps=3):
            mesh_mod.reset_mesh()
            cfg = {**_cfg(extra_zero=(
                {"offload_param": {"device": "cpu", "buffer_size": 1}}
                if offload else {})),
                "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                           "gamma": 0.01}}
            eng, *_ = ds.initialize(model=_model(), config=cfg,
                                    rng=jax.random.PRNGKey(7))
            return [float(eng.train_batch(batch=_batch(seed=i)))
                    for i in range(steps)]

        base = run(offload=False)
        off = run(offload=True)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)

    def test_gptneo_window_trajectory_matches_resident(self):
        """offload_param x attention_layers (GPT-Neo sliding windows): the
        traced global layer base keeps local layers LOCAL inside the
        shared block program."""
        def m():
            return build_model(TransformerConfig(
                vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
                max_seq_len=32, dtype=jnp.float32,
                attention_layers=("global", "local"), attention_window=8,
                attention_scale=1.0))

        def run(offload, steps=3):
            mesh_mod.reset_mesh()
            eng, *_ = ds.initialize(
                model=m(), config=_cfg(extra_zero=(
                    {"offload_param": {"device": "cpu", "buffer_size": 1}}
                    if offload else {})), rng=jax.random.PRNGKey(7))
            return [float(eng.train_batch(batch=_batch(seed=i)))
                    for i in range(steps)]

        base = run(offload=False)
        off = run(offload=True)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        # windows actually bind: an all-global config diverges
        mesh_mod.reset_mesh()
        allg = build_model(TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=32, dtype=jnp.float32, attention_scale=1.0))
        eng, *_ = ds.initialize(model=allg, config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(7))
        g0 = float(eng.train_batch(batch=_batch(seed=0)))
        assert abs(g0 - off[0]) > 1e-6


class TestMultiProcessOffload:
    """VERDICT r3 #2: offload over addressable shards with process_count>=2.
    Two jax.distributed CPU processes (4 virtual devices each) train the
    same model/config as a single-process 8-device run; every process
    streams only its own shards (_put_leaves/_writeback_shards) and the
    loss trajectories must agree with the single-process oracle."""

    WORKER = """
import sys
idx = int(sys.argv[1])
import jax
jax.distributed.initialize("localhost:12987", num_processes=2,
                           process_id=idx)
import numpy as np
import jax.numpy as jnp
import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer import TransformerConfig, build_model

assert jax.process_count() == 2
model = build_model(TransformerConfig(
    vocab_size=128, hidden_size=32, num_layers=4, num_heads=2,
    max_seq_len=32, dtype=jnp.float32, tie_embeddings=True))
cfg = {"train_micro_batch_size_per_gpu": 1,
       "gradient_accumulation_steps": 1, "steps_per_print": 1000,
       "optimizer": {"type": "adamw",
                     "params": {"lr": 5e-3, "weight_decay": 0.01}},
       "zero_optimization": {"stage": 3, "offload_param": {
           "device": "cpu", "buffer_size": 1}}}
engine, *_ = ds.initialize(model=model, config=cfg,
                           rng=jax.random.PRNGKey(7))
import sys as _s
mode = _s.argv[3] if len(_s.argv) > 3 else "train"
if mode == "resume":
    tag, _cs = engine.load_checkpoint(_s.argv[2])
    assert tag is not None
    losses = []
    for i in range(3, 5):
        ids = np.random.default_rng(i).integers(0, 128, (1, 8, 32))
        local = ids[:, 4 * idx:4 * idx + 4]
        losses.append(float(engine.train_batch(batch={"input_ids": local})))
    print("MP-RESUME-LOSSES", losses, flush=True)
else:
    losses = []
    for i in range(3):
        rng = np.random.default_rng(i)
        ids = rng.integers(0, 128, (1, 8, 32))      # GLOBAL batch
        local = ids[:, 4 * idx:4 * idx + 4]         # this process's share
        losses.append(float(engine.train_batch(batch={"input_ids": local})))
    if len(_s.argv) > 2:
        engine.save_checkpoint(_s.argv[2])          # per-region shard files
    print("MP-OFFLOAD-LOSSES", losses, flush=True)
"""

    def test_two_process_matches_single(self, tmp_path):
        import os
        import re
        import subprocess
        import sys

        script = tmp_path / "mp_offload_worker.py"
        script.write_text(self.WORKER)
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    "PALLAS_AXON_POOL_IPS": "",
                    "PYTHONPATH": os.getcwd()})
        ckpt = str(tmp_path / "mp_ckpt")
        procs = [subprocess.Popen([sys.executable, str(script), str(i),
                                   ckpt],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
                 for i in range(2)]
        outs = [p.communicate(timeout=600)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs[0] + outs[1]
        mp_losses = []
        for out in outs:
            m = re.search(r"MP-OFFLOAD-LOSSES \[([^\]]*)\]", out)
            assert m, out
            mp_losses.append([float(x) for x in m.group(1).split(",")])
        # both processes see the same (replicated) loss
        np.testing.assert_allclose(mp_losses[0], mp_losses[1], rtol=1e-6)

        # single-process oracle on the 8-device mesh, same global batches
        mesh_mod.reset_mesh()
        engine, *_ = ds.initialize(model=_model(), config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(7))
        oracle = []
        for i in range(5):
            ids = np.random.default_rng(i).integers(0, 128, (1, 8, 32))
            oracle.append(float(engine.train_batch(batch={"input_ids": ids})))
        np.testing.assert_allclose(mp_losses[0], oracle[:3], rtol=2e-4,
                                   atol=2e-5)

        # SAME-topology resume: a second 2-process wave loads the region
        # checkpoint and continues — trajectory matches the oracle
        procs = [subprocess.Popen([sys.executable, str(script), str(i),
                                   ckpt, "resume"],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
                 for i in range(2)]
        outs = [p.communicate(timeout=600)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs[0] + outs[1]
        m = re.search(r"MP-RESUME-LOSSES \[([^\]]*)\]", outs[0])
        assert m, outs[0]
        mp_resumed = [float(x) for x in m.group(1).split(",")]
        np.testing.assert_allclose(mp_resumed, oracle[3:], rtol=2e-4,
                                   atol=2e-5)

        # cross-topology resume: the 2-process checkpoint (per-region
        # shard files) loads into THIS single-process engine and the
        # continued trajectory matches the uninterrupted oracle
        mesh_mod.reset_mesh()
        eng2, *_ = ds.initialize(model=_model(), config=_cfg(extra_zero={
            "offload_param": {"device": "cpu", "buffer_size": 1}}),
            rng=jax.random.PRNGKey(11))   # different init — load overwrites
        tag, _ = eng2.load_checkpoint(ckpt)
        assert tag is not None
        resumed = []
        for i in range(3, 5):
            ids = np.random.default_rng(i).integers(0, 128, (1, 8, 32))
            resumed.append(float(eng2.train_batch(batch={"input_ids": ids})))
        np.testing.assert_allclose(resumed, oracle[3:], rtol=2e-4,
                                   atol=2e-5)


class TestParamOffloadNVMe:
    def test_nvme_tier_trajectory_and_files(self, tmp_path):
        _, base = _run(_cfg(), steps=3)
        cfg = _cfg(extra_zero={"offload_param": {
            "device": "nvme", "nvme_path": str(tmp_path),
            "buffer_size": 1}})
        eng, off = _run(cfg, steps=3)
        np.testing.assert_allclose(off, base, rtol=2e-4, atol=2e-5)
        import os
        swap = [f for r, _, fs in os.walk(tmp_path) for f in fs
                if f.startswith("params.block")]
        assert len(swap) == eng._param_offload.num_blocks
        # checkpoint materialises from files
        p = eng._param_offload.params_for_checkpoint()
        assert p["layers"]["attn"]["wq"].shape[0] == 4
        eng._param_offload.close()
