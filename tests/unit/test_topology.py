"""ProcessTopology rank-math tests — analog of reference
tests/unit/runtime/pipe/test_topology.py (pure math, no devices)."""

import pytest

from deepspeed_tpu.parallel.topology import (PipeDataParallelTopology,
                                             PipelineParallelGrid,
                                             PipeModelDataParallelTopology,
                                             ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.world_size() == 4


def test_topology_coord_roundtrip():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    for rank in range(topo.world_size()):
        coord = topo.get_coord(rank)
        assert topo.get_rank(pipe=coord.pipe, data=coord.data, model=coord.model) == rank


def test_axis_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert len(pipe_lists) == 4
    for lst in pipe_lists:
        assert len(lst) == 2
    data_lists = topo.get_axis_comm_lists("data")
    assert len(data_lists) == 2
    assert data_lists[0] == [0, 1, 2, 3]


def test_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    ranks = topo.filter_match(pipe=0)
    assert ranks == [0, 1, 2, 3]
    assert topo.filter_match(pipe=1, model=1) == [5, 7]


def test_get_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.get_rank_repr(0) == "pipe_00-model_00"


def test_bad_coords():
    topo = ProcessTopology(axes=["a"], dims=[2])
    with pytest.raises(ValueError):
        topo.get_rank(a=5)
    with pytest.raises(ValueError):
        topo.get_rank()  # missing axis


def test_grid():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=3)
    assert grid.pipe_parallel_size == 4
    assert grid.data_parallel_size == 2
    assert grid.get_stage_id() == 1
    assert grid.get_data_parallel_id() == 1
    assert not grid.is_first_stage() and not grid.is_last_stage()
    assert grid.stage_to_global(2) == 5


def test_grid_p2p_pairs():
    topo = PipeDataParallelTopology(num_pp=3, num_dp=1)
    grid = PipelineParallelGrid(topo, global_rank=0)
    assert grid.p2p_pairs() == [(0, 1), (1, 2)]


def test_mesh_shape_bridge():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.to_mesh_shape() == {"pipe": 2, "data": 2, "model": 2}
