"""Pipeline tests — analog of reference tests/unit/runtime/pipe/
(test_pipe_schedule.py pure-python schedule checks, test_pipe.py convergence
vs non-pipeline baseline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.parallel.pipeline import (partition_balanced,
                                             partition_layers,
                                             partition_uniform,
                                             pipelinize_model)
from deepspeed_tpu.parallel.schedule import (BackwardPass, ForwardPass,
                                             InferenceSchedule, LoadMicroBatch,
                                             OptimizerStep, TrainSchedule)

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests


class TestSchedules:
    def test_train_schedule_length(self):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
        assert len(sched) == 2 * (4 + 2 - 1)

    @pytest.mark.parametrize("stages,mb", [(2, 4), (4, 8), (3, 3)])
    def test_every_microbatch_forward_and_backward_once(self, stages, mb):
        for stage in range(stages):
            sched = TrainSchedule(micro_batches=mb, stages=stages, stage_id=stage)
            fwd, bwd = [], []
            for cmds in sched:
                for c in cmds:
                    if isinstance(c, ForwardPass):
                        fwd.append(c.kwargs["buffer_id"])
                    if isinstance(c, BackwardPass):
                        bwd.append(c.kwargs["buffer_id"])
            assert len(fwd) == mb, f"stage {stage}: {len(fwd)} forwards"
            assert len(bwd) == mb, f"stage {stage}: {len(bwd)} backwards"

    def test_backward_follows_forward(self):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
        seen_fwd = set()
        for cmds in sched:
            for c in cmds:
                if isinstance(c, ForwardPass):
                    seen_fwd.add(c.kwargs["buffer_id"])
                if isinstance(c, BackwardPass):
                    assert c.kwargs["buffer_id"] in seen_fwd

    def test_optimizer_step_last(self):
        sched = TrainSchedule(micro_batches=2, stages=2, stage_id=0)
        steps = list(sched)
        assert any(isinstance(c, OptimizerStep) for c in steps[-1])
        for cmds in steps[:-1]:
            assert not any(isinstance(c, OptimizerStep) for c in cmds)

    def test_first_stage_loads_microbatch(self):
        sched = TrainSchedule(micro_batches=2, stages=2, stage_id=0)
        loads = [c for cmds in sched for c in cmds if isinstance(c, LoadMicroBatch)]
        assert len(loads) == 2

    def test_inference_schedule(self):
        sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
        fwd = [c for cmds in sched for c in cmds if isinstance(c, ForwardPass)]
        assert len(fwd) == 4

    def test_num_pipe_buffers_1f1b_bound(self):
        # earlier stages hold more in-flight buffers
        s0 = TrainSchedule(micro_batches=8, stages=4, stage_id=0)
        s3 = TrainSchedule(micro_batches=8, stages=4, stage_id=3)
        assert s0.num_pipe_buffers() == 4
        assert s3.num_pipe_buffers() == 2


class TestPartitioning:
    def test_uniform(self):
        assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
        parts = partition_uniform(10, 4)
        assert parts[0] == 0 and parts[-1] == 10
        sizes = [parts[i + 1] - parts[i] for i in range(4)]
        assert max(sizes) - min(sizes) <= 1

    def test_balanced(self):
        parts = partition_balanced([1, 1, 1, 10, 1, 1], 2)
        assert parts[0] == 0 and parts[-1] == 6
        # the heavy item must sit alone-ish: first part carries items 0..3
        w = [1, 1, 1, 10, 1, 1]
        loads = [sum(w[parts[i]:parts[i + 1]]) for i in range(2)]
        assert max(loads) <= 13

    def test_partition_layers_type_regex(self):
        class TransformerLayer:
            pass

        class Embedding:
            pass

        layers = [Embedding()] + [TransformerLayer() for _ in range(4)] + [Embedding()]
        parts = partition_layers(layers, 2, method="type:transformerlayer")
        # each stage gets 2 transformer layers
        counts = []
        for i in range(2):
            counts.append(sum(1 for l in layers[parts[i]:parts[i + 1]]
                              if isinstance(l, TransformerLayer)))
        assert counts == [2, 2]


class TestPipelinedTraining:
    def _engine(self, pp, gas=4, zero=0, preset="tiny", **model_kw):
        model = create_model(preset, **model_kw)
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": gas,
               "steps_per_print": 1000,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": zero},
               "parallel": {"pipeline_parallel_size": pp}}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        return engine

    def _batch(self, engine, seed=0):
        gas = engine.gradient_accumulation_steps()
        gb = engine.train_batch_size() // gas
        ids = jax.random.randint(jax.random.PRNGKey(seed), (gas, gb, 16), 0, 256)
        return {"input_ids": ids}

    def test_pp_loss_matches_non_pp(self):
        """The pipelined program must compute the same loss and the same
        updated params as the plain engine (same data, same init)."""
        e1 = self._engine(pp=1, gas=4)
        e2 = self._engine(pp=2, gas=4)
        batch = self._batch(e1)
        l1 = float(e1.train_batch(batch=batch))
        l2 = float(e2.train_batch(batch=batch))
        assert l1 == pytest.approx(l2, rel=2e-3)

        # merge pp params back and compare trajectories
        from deepspeed_tpu.parallel.pipeline import _merge_stages

        p2 = dict(jax.device_get(e2.params))
        p2["layers"] = _merge_stages(p2["layers"])
        p1 = jax.device_get(e1.params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=5e-3),
            p1, p2)

    def test_pp_with_zero1(self):
        e = self._engine(pp=2, gas=2, zero=1)
        batch = self._batch(e)
        losses = [float(e.train_batch(batch=batch)) for _ in range(4)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_pp4(self):
        e = self._engine(pp=4, gas=4, num_layers=4)
        batch = self._batch(e)
        losses = [float(e.train_batch(batch=batch)) for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_pp_honors_labels_and_mask(self):
        """Custom labels (-100 masking, SFT-style) and attention_mask must give
        the same loss as the non-PP path."""
        e1 = self._engine(pp=1, gas=2)
        e2 = self._engine(pp=2, gas=2)
        gas, gb = 2, e1.train_batch_size() // 2
        rng = jax.random.PRNGKey(7)
        ids = jax.random.randint(rng, (gas, gb, 16), 0, 256)
        labels = ids.at[:, :, :8].set(-100)  # mask the "prompt" half
        mask = jnp.ones((gas, gb, 16), jnp.int32).at[:, :, 12:].set(0)
        batch = {"input_ids": ids, "labels": labels, "attention_mask": mask}
        l1 = float(e1.train_batch(batch=batch))
        l2 = float(e2.train_batch(batch=batch))
        assert l1 == pytest.approx(l2, rel=2e-3)

    def test_pp_forward_api_rejected(self):
        e = self._engine(pp=2, gas=2)
        with pytest.raises(RuntimeError, match="train_batch"):
            e.forward({"input_ids": jnp.zeros((2, 16), jnp.int32)})

    def test_pp_eval_loss(self):
        e = self._engine(pp=2, gas=2)
        gb = e.train_batch_size() // 2
        ids = jax.random.randint(jax.random.PRNGKey(0), (gb, 16), 0, 256)
        loss = float(e.eval_loss({"input_ids": ids}))
        assert np.isfinite(loss)

    def test_pp_rejects_indivisible_layers(self):
        model = create_model("tiny")  # 2 layers, pp=4 -> 2 % 4 != 0
        with pytest.raises(AssertionError):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                        "parallel": {"pipeline_parallel_size": 4}})


class TestPipelineMemory:
    def test_activation_residency_is_o_p_not_o_m(self):
        """1F1B contract (reference schedule.py:212 num_pipe_buffers): live
        activation storage is bounded by the stage depth P, not the
        microbatch count M. Compiled temp memory for the grad step must grow
        sub-linearly when M quadruples at fixed P (the round-1 fill-drain
        executor stacked every tick: O(M) growth)."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.models import create_model
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.pipeline import (pipelined_grad_fn,
                                                     pipelinize_model)
        from deepspeed_tpu.config.config import ParallelConfig

        mesh = mesh_mod.build_mesh(ParallelConfig(pipeline_parallel_size=4,
                                                  data_parallel_size=2))
        mesh_mod.set_mesh(mesh)
        model = create_model("tiny", dtype=jnp.float32, num_layers=4,
                             max_seq_len=64)
        pmodel = pipelinize_model(model, 4)
        params = pmodel.init(jax.random.PRNGKey(0))

        from deepspeed_tpu.utils.compat import pipeline_partitioner

        def temp_bytes(M):
            ids = jnp.zeros((M, 4, 64), jnp.int32)
            with mesh, pipeline_partitioner():
                lowered = jax.jit(pmodel.grad_fn).lower(
                    params, {"input_ids": ids}, jnp.float32(1.0))
                return lowered.compile().memory_analysis().temp_size_in_bytes

        with mesh:
            t2, t8 = temp_bytes(2), temp_bytes(8)
        # M x4 => temps must grow far less than proportionally
        assert t8 < t2 * 2.5, (
            f"temp memory grew {t8 / t2:.2f}x for 4x microbatches "
            f"({t2} -> {t8} bytes) — activation residency is not O(P)")


class TestPipelineMoE:
    def test_grad_fn_loss_matches_eval_loss_with_aux(self):
        """1F1B reported train loss and the eval loss_fn must agree for MoE
        models — both include CE + router aux (regression: the executor
        reported CE only while its grads included the aux term)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deepspeed_tpu.models import create_model
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.pipeline import pipelinize_model
        from deepspeed_tpu.config.config import ParallelConfig

        mesh = mesh_mod.build_mesh(ParallelConfig(pipeline_parallel_size=2,
                                                  data_parallel_size=4))
        mesh_mod.set_mesh(mesh)
        model = create_model("moe-tiny", dtype=jnp.float32, max_seq_len=64)
        pmodel = pipelinize_model(model, 2)
        params = pmodel.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 32), 0, 250)
        batch = {"input_ids": ids}
        from deepspeed_tpu.utils.compat import pipeline_partitioner

        with mesh, pipeline_partitioner():
            train_loss, grads = jax.jit(pmodel.grad_fn)(
                params, batch, jnp.float32(1.0))
            eval_loss = jax.jit(pmodel.loss_fn)(params, batch)
        np.testing.assert_allclose(float(train_loss), float(eval_loss),
                                   rtol=1e-5)
        # and aux really is in there: loss > plain-CE-only would require
        # recomputing without aux; instead check the router grads are nonzero
        g_router = np.abs(np.asarray(grads["layers"]["router"])).max()
        assert g_router > 0.0
