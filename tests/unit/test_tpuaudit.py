"""tpuaudit unit tests: per-check positive/negative program fixtures,
registry + baseline semantics (incl. stale-entry rot), engine entry-point
registration across the three layers, and the repo-wide gate (the selftest
engines audited against the committed baseline — what makes tier-1 enforce
program-level analysis)."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.tpuaudit import (clear_registry, get_entry_points,
                            register_entry_point, run_audit)
from tools.tpuaudit import baseline as baseline_mod
from tools.tpuaudit.checks import CHECKS
from tools.tpuaudit.cli import main as tpuaudit_main
from tools.tpuaudit.core import Finding, build_program, collect_collectives

REPO = Path(__file__).resolve().parents[2]


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_registry()
    yield
    clear_registry()


def sds(shape, dtype=jnp.float32, sharding=None):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def audit_one(name="fixture", options=None, **kw):
    ep = register_entry_point(name, **kw)
    return run_audit([ep], options=options, publish_metrics=False)


def checks_of(findings):
    return sorted({f.check for f in findings})


def mesh2x4():
    devs = np.array(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("data", "model"))


# ---------------------------------------------------------------------------
# check fixtures — a program that must trigger, and a clean twin


class TestUnexpectedCollective:
    def _reshard_fixture(self, expected):
        mesh = mesh2x4()

        def f(w, x):
            y = x @ w
            y = jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh, P(None, "model")))
            return y.sum()

        return audit_one(
            fn=jax.jit(f),
            args=(sds((256, 256), sharding=NamedSharding(mesh, P("model", None))),
                  sds((64, 256), sharding=NamedSharding(mesh, P("data", None)))),
            expected_collectives=expected)

    def test_positive_gspmd_inserted_all_gather(self):
        findings = self._reshard_fixture(frozenset())
        assert "unexpected-collective" in checks_of(findings)
        assert any("all-gather" in f.message for f in findings)

    def test_negative_declared_collectives(self):
        findings = self._reshard_fixture(
            frozenset({"all-gather", "all-reduce", "all-to-all",
                       "collective-permute"}))
        assert findings == []

    def test_explicit_shard_map_collective_without_compile(self):
        """shard_map collectives appear in the lowered StableHLO, so the
        census works even with compile=False."""
        from deepspeed_tpu.utils.compat import shard_map

        mesh = mesh2x4()
        body = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                         in_specs=P("data"), out_specs=P(),
                         check_vma=False, axis_names={"data"})
        findings = audit_one(fn=jax.jit(body), args=(sds((8,)),),
                             expected_collectives=frozenset(), compile=False)
        assert checks_of(findings) == ["unexpected-collective"]
        assert "all-reduce" in findings[0].message

    def test_none_disables_the_check(self):
        findings = self._reshard_fixture(None)
        assert findings == []


class TestDonation:
    def _state_fn(self, donate):
        def step(state, batch):
            return jax.tree.map(lambda a: a + 1.0, state), batch.sum()

        return dict(fn=jax.jit(step, donate_argnums=donate),
                    args=({"w": sds((600, 600))}, sds((4,))),
                    donate_argnums=donate, expected_collectives=frozenset())

    def test_positive_missed_donation(self):
        findings = audit_one(**self._state_fn(()))
        assert checks_of(findings) == ["missed-donation"]

    def test_negative_donated_state(self):
        assert audit_one(**self._state_fn((0,))) == []

    def test_threshold_hides_small_misses(self):
        def f(s, b):
            return s + 1.0, b.sum()

        findings = audit_one(fn=jax.jit(f), args=(sds((4,)), sds((4,))),
                             expected_collectives=frozenset())
        assert findings == []          # 16 bytes, far under the MiB default

    def test_positive_dead_donation(self):
        def f(x, dead):
            return x + 1.0

        findings = audit_one(
            fn=jax.jit(f, donate_argnums=(1,)),
            args=(sds((4,)), sds((600, 600), jnp.int32)),
            donate_argnums=(1,), expected_collectives=frozenset())
        assert checks_of(findings) == ["dead-donation"]
        assert "argument 1" in findings[0].message

    def test_negative_partial_alias_is_live(self):
        def f(state):
            return {"a": state["a"] * 2.0}

        findings = audit_one(
            fn=jax.jit(f, donate_argnums=(0,)),
            args=({"a": sds((8,)), "b": sds((3,), jnp.int32)},),
            donate_argnums=(0,), expected_collectives=frozenset())
        assert "dead-donation" not in checks_of(findings)

    def test_suppression_at_registration(self):
        spec = self._state_fn(())
        spec["suppress"] = frozenset({"missed-donation"})
        assert audit_one(**spec) == []


class TestHostCallback:
    def test_positive_debug_print(self):
        def f(x):
            jax.debug.print("x={x}", x=x)
            return x * 2

        findings = audit_one(fn=jax.jit(f), args=(sds((4,)),),
                             expected_collectives=frozenset())
        assert checks_of(findings) == ["host-callback-in-program"]
        assert "debug_callback" in findings[0].message

    def test_positive_pure_callback_in_scan(self):
        def f(x):
            def body(c, _):
                y = jax.pure_callback(
                    lambda a: a, jax.ShapeDtypeStruct((4,), jnp.float32), c)
                return y, None

            out, _ = jax.lax.scan(body, x, None, length=3)
            return out

        findings = audit_one(fn=jax.jit(f), args=(sds((4,)),),
                             expected_collectives=frozenset())
        assert "pure_callback" in " ".join(f.message for f in findings)

    def test_negative_pure_program(self):
        findings = audit_one(fn=jax.jit(lambda x: jnp.sin(x).sum()),
                             args=(sds((4,)),),
                             expected_collectives=frozenset())
        assert findings == []


class TestWeakTypeCapture:
    def test_positive_python_float_arg(self):
        findings = audit_one(fn=jax.jit(lambda x, s: x * s),
                             args=(sds((4,)), 0.1),
                             expected_collectives=frozenset())
        assert checks_of(findings) == ["weak-type-capture"]
        assert "arg1" in findings[0].message

    def test_negative_array_scalar(self):
        findings = audit_one(fn=jax.jit(lambda x, s: x * s),
                             args=(sds((4,)), sds((), jnp.float32)),
                             expected_collectives=frozenset())
        assert findings == []


class TestImplicitPromotion:
    def test_positive_f64_program(self):
        from jax.experimental import enable_x64

        def build():
            return jax.jit(lambda x: x * 2.0), (sds((4,), jnp.float64),), {}

        ep = register_entry_point("fix/x64", build=build,
                                  expected_collectives=frozenset())
        with enable_x64():
            findings = run_audit([ep], publish_metrics=False)
        assert "implicit-promotion" in checks_of(findings)

    def test_negative_f32_program(self):
        findings = audit_one(fn=jax.jit(lambda x: x * 2.0),
                             args=(sds((4,)),),
                             expected_collectives=frozenset())
        assert findings == []


class TestBakedConstant:
    def test_positive_closure_capture(self):
        big = np.ones((600, 600), np.float32)     # 1.4 MiB

        def f(x):
            return x + jnp.asarray(big).sum()

        findings = audit_one(fn=jax.jit(f), args=(sds((4,)),),
                             expected_collectives=frozenset())
        assert checks_of(findings) == ["baked-constant"]

    def test_negative_passed_as_argument(self):
        findings = audit_one(fn=jax.jit(lambda x, t: x + t.sum()),
                             args=(sds((4,)), sds((600, 600))),
                             expected_collectives=frozenset())
        assert findings == []

    def test_threshold_option(self):
        small = np.ones((64,), np.float32)

        def f(x):
            return x + jnp.asarray(small).sum()

        findings = audit_one(fn=jax.jit(f), args=(sds((4,)),),
                             expected_collectives=frozenset(),
                             options={"max_const_bytes": 16})
        assert checks_of(findings) == ["baked-constant"]


class TestCollectiveCensus:
    def test_explicit_collective_not_double_counted(self):
        """An explicit shard_map collective appears in BOTH the lowered and
        the compiled text; the census must report it once, not twice."""
        from deepspeed_tpu.utils.compat import shard_map

        mesh = mesh2x4()
        body = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                         in_specs=P("data"), out_specs=P(),
                         check_vma=False, axis_names={"data"})
        ep = register_entry_point("fix/census", fn=jax.jit(body),
                                  args=(sds((8,)),),
                                  expected_collectives=frozenset())
        program = build_program(ep)
        found = collect_collectives(program.stablehlo, program.compiled_hlo)
        assert found.get("all-reduce") == 1


class TestStaleEngine:
    def test_dead_engine_entry_is_skipped(self):
        """Registration holds only a weakref; once the engine is collected
        the entry audits to nothing instead of erroring or pinning it."""
        import gc

        import deepspeed_tpu
        from deepspeed_tpu.models import simple_model

        cfg = {"train_micro_batch_size_per_gpu": 2,
               "steps_per_print": 10 ** 9,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
        engine, *_ = deepspeed_tpu.initialize(model=simple_model(hidden_dim=10),
                                              config=cfg)
        gb = engine.train_batch_size() // engine.gradient_accumulation_steps()
        engine.register_audit_entries({"x": np.zeros((gb, 10), np.float32),
                                       "y": np.zeros((gb, 1), np.float32)})
        del engine
        gc.collect()
        findings = run_audit(get_entry_points(["train/step", "train/eval"]),
                             publish_metrics=False)
        assert findings == []


class TestTraceError:
    def test_broken_entry_reports_not_raises(self):
        def build():
            raise RuntimeError("boom")

        ep = register_entry_point("fix/broken", build=build)
        findings = run_audit([ep], publish_metrics=False)
        assert checks_of(findings) == ["trace-error"]
        assert "boom" in findings[0].message


# ---------------------------------------------------------------------------
# registry + baseline


class TestRegistry:
    def test_replace_by_name_latest_wins(self):
        register_entry_point("a", fn=jax.jit(lambda x: x), args=(sds((2,)),))
        register_entry_point("a", fn=jax.jit(lambda x: x * 2),
                             args=(sds((3,)),))
        eps = get_entry_points(["a"])
        assert len(eps) == 1 and eps[0].build()[1][0].shape == (3,)

    def test_unknown_entry_raises(self):
        with pytest.raises(KeyError):
            get_entry_points(["nope"])

    def test_unknown_collective_kind_rejected(self):
        with pytest.raises(ValueError):
            register_entry_point("a", fn=jax.jit(lambda x: x),
                                 args=(sds((2,)),),
                                 expected_collectives=frozenset({"all-hands"}))


class TestBaseline:
    def _findings(self, n, entry="train/step", check="missed-donation"):
        return [Finding(check, entry, f"m{i}") for i in range(n)]

    def test_roundtrip_masks_budgeted(self, tmp_path):
        bl = tmp_path / "bl.json"
        baseline_mod.write(str(bl), self._findings(2))
        known = baseline_mod.load(str(bl))
        assert baseline_mod.new_findings(self._findings(2), known) == []
        assert len(baseline_mod.new_findings(self._findings(3), known)) == 1

    def test_stale_keys_detected(self, tmp_path):
        known = {"train/step::missed-donation": 2}
        assert baseline_mod.stale_keys([], known) == \
            ["train/step::missed-donation"]
        assert baseline_mod.stale_keys(self._findings(1), known) == []

    def test_stale_scoping(self):
        known = {"other/entry::missed-donation": 1}
        in_scope = lambda k: k.startswith("train/")
        assert baseline_mod.stale_keys([], known, in_scope=in_scope) == []

    def test_pruned_drops_and_clamps(self):
        known = {"a::c": 5, "b::c": 2}
        out = baseline_mod.pruned(self._findings(1, entry="a", check="c"),
                                  known)
        assert out == {"a::c": 1}


# ---------------------------------------------------------------------------
# engine entry points on the CPU mesh


class TestTrainEngineEntries:
    def _engine(self, extra=None):
        import deepspeed_tpu
        from deepspeed_tpu.models import simple_model

        cfg = {"train_micro_batch_size_per_gpu": 2,
               "steps_per_print": 10 ** 9,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
        cfg.update(extra or {})
        engine, *_ = deepspeed_tpu.initialize(model=simple_model(hidden_dim=10),
                                              config=cfg)
        return engine

    def _micro(self, engine):
        gb = engine.train_batch_size() // engine.gradient_accumulation_steps()
        return {"x": np.zeros((gb, 10), np.float32),
                "y": np.zeros((gb, 1), np.float32)}

    def test_register_and_audit_clean(self):
        engine = self._engine({"zero_optimization": {"stage": 3}})
        names = engine.register_audit_entries(self._micro(engine))
        assert names == ["train/step", "train/eval"]
        assert run_audit(get_entry_points(names),
                         publish_metrics=False) == []

    def test_zero3_step_declares_its_collectives(self):
        engine = self._engine({"zero_optimization": {"stage": 3}})
        engine.register_audit_entries(self._micro(engine))
        ep = get_entry_points(["train/step"])[0]
        program = build_program(ep)
        found = collect_collectives(program.stablehlo, program.compiled_hlo)
        assert set(found) <= set(ep.expected_collectives)
        if engine.mesh.size > 1:      # 8 virtual devices in this suite
            assert found, "expected SPMD collectives on a multi-device mesh"

    def test_train_batch_autoregisters(self):
        engine = self._engine()
        micro = self._micro(engine)
        batch = {k: jnp.asarray(v)[None] for k, v in micro.items()}
        engine.train_batch(batch=batch)
        assert "train/step" in {e.name for e in get_entry_points()}

    def test_step_entry_donates_train_state(self):
        engine = self._engine()
        engine.register_audit_entries(self._micro(engine))
        ep = get_entry_points(["train/step"])[0]
        assert ep.donate_argnums == (0, 1)

    def test_onebit_step_declares_compressed_exchange(self):
        engine = self._engine({"optimizer": {
            "type": "onebitadam", "params": {"lr": 1e-3, "freeze_step": 2}}})
        names = engine.register_audit_entries(self._micro(engine))
        ep = get_entry_points(["train/step"])[0]
        assert {"all-to-all", "all-gather"} <= set(ep.expected_collectives)
        assert run_audit(get_entry_points(names),
                         publish_metrics=False) == []


class TestPipelineEntries:
    @pytest.fixture()
    def engine(self, devices8):
        import deepspeed_tpu
        from deepspeed_tpu.models import create_model

        cfg = {"train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 2,
               "steps_per_print": 10 ** 9,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
               "parallel": {"pipeline_parallel_size": 2}}
        engine, *_ = deepspeed_tpu.initialize(
            model=create_model("tiny", dtype=jnp.float32, max_seq_len=32),
            config=cfg)
        return engine

    def test_pipelinize_registers_stage_fns(self, engine):
        names = {e.name for e in get_entry_points()}
        assert {"pipeline/loss_fn", "pipeline/grad_fn"} <= names

    def test_stage_fns_audit_clean(self, engine):
        eps = get_entry_points(["pipeline/loss_fn", "pipeline/grad_fn"])
        assert run_audit(eps, publish_metrics=False) == []

    def test_stage_program_contains_the_ring_permute(self, engine):
        ep = get_entry_points(["pipeline/grad_fn"])[0]
        program = build_program(ep)
        found = collect_collectives(program.stablehlo, program.compiled_hlo)
        assert "collective-permute" in found

    def test_undeclared_permute_fails(self, engine):
        ep = get_entry_points(["pipeline/loss_fn"])[0]
        ep.expected_collectives = frozenset({"all-reduce", "all-gather"})
        findings = run_audit([ep], publish_metrics=False)
        assert checks_of(findings) == ["unexpected-collective"]
        assert "collective-permute" in findings[0].message


class TestInferenceEntries:
    def test_register_and_audit_clean(self):
        from deepspeed_tpu.inference import init_inference

        engine = init_inference(model="tiny", max_out_tokens=128)
        names = engine.register_audit_entries(batch_size=1, prompt_len=16,
                                              max_new_tokens=4)
        assert names == ["inference/prefill", "inference/decode"]
        assert run_audit(get_entry_points(names),
                         publish_metrics=False) == []

    def test_prefill_donates_the_kv_arena(self):
        from deepspeed_tpu.inference import init_inference

        engine = init_inference(model="tiny", max_out_tokens=128)
        engine.register_audit_entries(batch_size=1, prompt_len=16)
        ep = get_entry_points(["inference/prefill"])[0]
        assert ep.donate_argnums == (3,)
        program = build_program(ep)
        assert any(program.donated), "cache leaves should be donated"


class TestMetricsPublication:
    def test_findings_land_in_registry(self):
        from deepspeed_tpu.observability import get_registry

        def f(x):
            jax.debug.print("{x}", x=x)
            return x

        ep = register_entry_point("pub/test", fn=jax.jit(f), args=(sds((2,)),),
                                  expected_collectives=frozenset())
        before = get_registry().counter("tpuaudit/findings").value(
            entry="pub/test", check="host-callback-in-program")
        run_audit([ep])
        after = get_registry().counter("tpuaudit/findings").value(
            entry="pub/test", check="host-callback-in-program")
        assert after == before + 1


# ---------------------------------------------------------------------------
# CLI surface + repo-wide gate


class TestCli:
    def _register_bad_entry(self):
        mesh = mesh2x4()

        def f(w, x):
            return jax.lax.with_sharding_constraint(
                x @ w, NamedSharding(mesh, P(None, "model"))).sum()

        register_entry_point(
            "fix/reshard", fn=jax.jit(f),
            args=(sds((256, 256), sharding=NamedSharding(mesh, P("model", None))),
                  sds((64, 256), sharding=NamedSharding(mesh, P("data", None)))),
            expected_collectives=frozenset())

    def test_undeclared_all_gather_exits_nonzero(self, capsys):
        """Acceptance fixture: an entry whose program contains an undeclared
        all-gather must fail the gate."""
        self._register_bad_entry()
        rc = tpuaudit_main(["--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any("all-gather" in f["message"] for f in out["findings"])

    def test_baselined_fixture_passes_then_goes_stale(self, tmp_path, capsys):
        self._register_bad_entry()
        bl = tmp_path / "bl.json"
        assert tpuaudit_main(["--baseline", str(bl),
                              "--write-baseline"]) == 0
        assert tpuaudit_main(["--baseline", str(bl)]) == 0
        capsys.readouterr()
        # "fix" the entry: re-register with the collectives declared
        clear_registry()
        self._register_bad_entry()
        get_entry_points(["fix/reshard"])[0].expected_collectives = frozenset(
            {"all-gather", "all-reduce", "all-to-all", "collective-permute"})
        rc = tpuaudit_main(["--baseline", str(bl)])
        assert rc == 1
        assert "stale baseline entry" in capsys.readouterr().out
        assert tpuaudit_main(["--baseline", str(bl),
                              "--prune-baseline"]) == 0
        assert tpuaudit_main(["--baseline", str(bl)]) == 0
        assert json.loads(bl.read_text())["counts"] == {}

    def test_list_checks_names_all(self, capsys):
        assert tpuaudit_main(["--list-checks"]) == 0
        out = capsys.readouterr().out
        for name in ("unexpected-collective", "missed-donation",
                     "dead-donation", "host-callback-in-program",
                     "weak-type-capture", "implicit-promotion",
                     "baked-constant"):
            assert name in out
        assert len(CHECKS) >= 7

    def test_select_unknown_check_errors(self):
        assert tpuaudit_main(["--select", "not-a-check"]) == 2

    def test_no_entries_errors(self):
        assert tpuaudit_main([]) == 2


class TestRepoGate:
    def test_selftest_engines_clean_under_baseline(self):
        """Acceptance gate: the selftest config builds train (ZeRO-3, 8
        virtual devices), pipeline-parallel and inference engines; their
        registered entry points must audit clean against the committed
        baseline. An undeclared collective / donation miss / host callback
        introduced in any engine layer fails this test (and tier-1)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpuaudit",
             "--config", "tools/tpuaudit/selftest_config.json",
             "--baseline", ".tpuaudit-baseline.json", "--devices", "8"],
            cwd=REPO, capture_output=True, text=True, timeout=540,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
        assert proc.returncode == 0, \
            f"tpuaudit found new issues:\n{proc.stdout}\n{proc.stderr}"
