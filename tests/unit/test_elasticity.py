"""Elasticity math tests — mirrors reference tests/unit/elasticity/
test_elastic.py including its exact numeric oracles (batch 9792 / 23 valid
world sizes for the canonical 10k config; micro batch 17 at world 64)."""

import pytest

from deepspeed_tpu.elasticity import (ElasticityConfigError, ElasticityError,
                                      compute_elastic_config,
                                      elasticity_enabled)


@pytest.fixture
def config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_chips": 32,
            "max_chips": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k(config):
    batch, valid = compute_elastic_config(config)
    for w in valid:
        assert batch % w == 0
        per = batch // w
        assert any(per % mb == 0
                   for mb in config["elasticity"]["micro_batch_sizes"])
    assert batch == 9792
    assert len(valid) == 23


def test_disabled(config):
    config["elasticity"]["enabled"] = False
    assert not elasticity_enabled(config)
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(config)


def test_valid_world_size_picks_micro(config):
    batch, valid, micro = compute_elastic_config(config, world_size=64,
                                                 return_microbatch=True)
    assert micro == 17


def test_invalid_world_size(config):
    with pytest.raises(ElasticityError):
        compute_elastic_config(config, world_size=128)


def test_future_version(config):
    config["elasticity"]["version"] = 0.3
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(config)


def test_missing_fields(config):
    del config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(config)
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": True,
                                               "micro_batch_sizes": [2]}})


def test_invalid_micro_batches(config):
    config["elasticity"]["micro_batch_sizes"] = [2, 0, -1]
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(config)


def test_model_parallel_needs_v02(config):
    config["elasticity"]["model_parallel_size"] = 2
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(config)


def test_v02_node_granularity(config):
    config["elasticity"].update({
        "version": 0.2,
        "model_parallel_size": 2,
        "num_chips_per_node": 4,
    })
    batch, valid, micro = compute_elastic_config(config, world_size=64,
                                                 return_microbatch=True)
    # dp worlds move in whole nodes: every entry divisible by dp_per_node=2
    assert all(v % 2 == 0 for v in valid)
    assert batch % (64 // 2) == 0  # gas integral at current dp world
    assert micro in config["elasticity"]["micro_batch_sizes"]


def test_v02_incompatible_world_falls_back(config):
    config["elasticity"].update({
        "version": 0.2,
        "model_parallel_size": 1,
        "num_chips_per_node": 7,
    })
    # 3 nodes (21 chips) is below min_chips=32 -> off the elastic list ->
    # v0.2 falls back to the largest batch reachable at the current dp world
    batch, valid, micro = compute_elastic_config(config, world_size=21,
                                                 return_microbatch=True)
    assert valid == [21]
    assert batch % 21 == 0
    assert micro is not None and (batch // 21) % micro == 0
