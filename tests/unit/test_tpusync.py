"""tpusync unit tests: per-rule positive/negative/suppression fixtures,
thread-root graph + lock-order-cycle synthesis on miniature modules, and
the repo-wide gate (the analyzer run over the host-orchestration scope
with the committed zero-debt baseline must be clean — this test is what
makes tier-1 enforce concurrency analysis)."""

import subprocess
import sys
from pathlib import Path

from tools.tpusync import analyze_source, build_program
from tools.tpusync.core import DEFAULT_SCOPE, RULES, SyncModule
from tools.tpusync.threadgraph import LockId

REPO = Path(__file__).resolve().parents[2]


def rules_of(source, **kw):
    return sorted({f.rule for f in analyze_source(source, **kw)})


def findings_of(source, rule, **kw):
    return [f for f in analyze_source(source, **kw) if f.rule == rule]


# ---------------------------------------------------------------------------
# rule fixtures


SHARED_WRITE = (
    "import threading\n"
    "class Worker:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.count = 0\n"
    "    def run_loop(self):\n"
    "        self.count += 1\n"
    "    def bump(self):\n"
    "        {write}\n"
    "    def launch(self):\n"
    "        t = threading.Thread(target=self.run_loop, name='w')\n"
    "        t.start()\n")


class TestUnguardedSharedWrite:
    def test_positive_two_roots_no_lock(self):
        src = SHARED_WRITE.format(write="self.count += 1")
        hits = findings_of(src, "unguarded-shared-write")
        assert len(hits) == 1
        msg = hits[0].message
        # actionable: names the attribute, the roots, and a candidate lock
        assert "Worker.count" in msg
        assert "thread:w" in msg and "main" in msg
        assert "Worker._lock" in msg

    def test_negative_common_lock(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def run_loop(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
            "    def launch(self):\n"
            "        t = threading.Thread(target=self.run_loop)\n"
            "        t.start()\n")
        assert rules_of(src) == []

    def test_negative_single_root(self):
        src = (
            "class Tally:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "    def bump(self):\n"
            "        self.n += 1\n")
        assert rules_of(src) == []

    def test_init_writes_exempt(self):
        # construction happens-before publication: __init__ writes never
        # count as racing sites (were they counted, __init__'s main root
        # would race the spawn-only _run_loop below)
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "    def _run_loop(self):\n"
            "        self.count += 1\n"
            "    def launch(self):\n"
            "        t = threading.Thread(target=self._run_loop, name='w')\n"
            "        t.start()\n")
        assert rules_of(src) == []

    def test_guarded_by_annotation_enforced(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0  # tpusync: guarded-by=_lock\n"
            "    def bump(self):\n"
            "        self.count += 1\n")
        hits = findings_of(src, "unguarded-shared-write")
        # single-root, but the declared guard makes EVERY bare write a
        # finding — and the message names the missing lock
        assert len(hits) == 1
        assert "_lock" in hits[0].message
        assert "Worker.bump" in hits[0].message

    def test_guarded_by_annotation_satisfied(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0  # tpusync: guarded-by=_lock\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n")
        assert rules_of(src) == []

    def test_suppression(self):
        # suppressing the thread-side write removes that site from the
        # race set; the lone remaining main-root site is then clean
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "    def run_loop(self):\n"
            "        self.count += 1  "
            "# tpusync: disable=unguarded-shared-write\n"
            "    def bump(self):\n"
            "        self.count += 1\n"
            "    def launch(self):\n"
            "        t = threading.Thread(target=self.run_loop, name='w')\n"
            "        t.start()\n")
        assert rules_of(src) == []

    def test_multiline_comment_suppression(self):
        # a comment-only disable line covers the next CODE line, however
        # many why-comment lines sit in between
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "    def run_loop(self):\n"
            "        # tpusync: disable=unguarded-shared-write — safe:\n"
            "        # publication is fenced by the queue join\n"
            "        self.count += 1\n"
            "    def bump(self):\n"
            "        self.count += 1\n"
            "    def launch(self):\n"
            "        t = threading.Thread(target=self.run_loop, name='w')\n"
            "        t.start()\n")
        assert rules_of(src) == []


class TestLockOrderInversion:
    def test_positive_two_lock_cycle(self):
        src = (
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
            "def f():\n"
            "    with a:\n"
            "        with b:\n"
            "            pass\n"
            "def g():\n"
            "    with b:\n"
            "        with a:\n"
            "            pass\n")
        hits = findings_of(src, "lock-order-inversion")
        assert len(hits) == 1
        assert "a -> b" in hits[0].message or "b -> a" in hits[0].message
        assert "deadlock" in hits[0].message

    def test_negative_consistent_order(self):
        src = (
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
            "def f():\n"
            "    with a:\n"
            "        with b:\n"
            "            pass\n"
            "def g():\n"
            "    with a:\n"
            "        with b:\n"
            "            pass\n")
        assert rules_of(src) == []

    def test_positive_nonreentrant_reacquire(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n")
        hits = findings_of(src, "lock-order-inversion")
        assert len(hits) == 1
        assert "self-deadlock" in hits[0].message

    def test_negative_rlock_reacquire(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n")
        assert findings_of(src, "lock-order-inversion") == []

    def test_three_lock_cycle_across_modules(self):
        # A→B in one module, B→C and C→A in another: one cycle, found on
        # the whole-program graph, with every hop named
        m1 = SyncModule("pkg/m1.py", (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "C = threading.Lock()\n"
            "def ab():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"))
        m2 = SyncModule("pkg/m2.py", (
            "from pkg.m1 import A, B, C\n"
            "def bc():\n"
            "    with B:\n"
            "        with C:\n"
            "            pass\n"
            "def ca():\n"
            "    with C:\n"
            "        with A:\n"
            "            pass\n"))
        program = build_program([m1, m2])
        cycles = program.lock_cycles()
        assert len(cycles) == 1
        assert len(cycles[0]) == 3


class TestBlockingUnderLock:
    def test_positive_sleep_under_lock(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n")
        hits = findings_of(src, "blocking-under-lock")
        assert len(hits) == 1
        assert "time.sleep" in hits[0].message
        assert "C._lock" in hits[0].message

    def test_negative_sleep_outside(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "        time.sleep(1)\n")
        assert findings_of(src, "blocking-under-lock") == []

    def test_positive_unbounded_queue_get(self):
        src = (
            "import threading, queue\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.q = queue.Queue()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            return self.q.get()\n")
        assert len(findings_of(src, "blocking-under-lock")) == 1

    def test_negative_cond_wait_idiom(self):
        # `with cond: cond.wait()` releases the lock while waiting — the
        # condition-variable idiom is not a blocking-under-lock
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cond = threading.Condition()\n"
            "    def f(self):\n"
            "        with self._cond:\n"
            "            self._cond.wait()\n")
        assert findings_of(src, "blocking-under-lock") == []

    def test_suppression(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)  # tpusync: disable=blocking-under-lock\n")
        assert findings_of(src, "blocking-under-lock") == []


class TestSignalUnsafeHandler:
    def test_positive_lock_in_handler(self):
        src = (
            "import signal, threading\n"
            "_lock = threading.Lock()\n"
            "def handler(signum, frame):\n"
            "    with _lock:\n"
            "        pass\n"
            "signal.signal(signal.SIGTERM, handler)\n")
        hits = findings_of(src, "signal-unsafe-handler")
        assert len(hits) == 1
        assert "SIGTERM" in hits[0].message
        assert "handler" in hits[0].message

    def test_positive_io_through_helper(self):
        # transitive: the handler's call closure does the IO
        src = (
            "import signal\n"
            "def dump():\n"
            "    with open('/tmp/x', 'w') as fh:\n"
            "        fh.write('x')\n"
            "def handler(signum, frame):\n"
            "    dump()\n"
            "signal.signal(signal.SIGUSR1, handler)\n")
        hits = findings_of(src, "signal-unsafe-handler")
        assert len(hits) == 1
        assert "open()" in hits[0].message

    def test_negative_flag_set_only(self):
        src = (
            "import signal\n"
            "STOP = False\n"
            "def handler(signum, frame):\n"
            "    global STOP\n"
            "    STOP = True\n"
            "signal.signal(signal.SIGTERM, handler)\n")
        assert findings_of(src, "signal-unsafe-handler") == []

    def test_thread_root_annotation_creates_handler(self):
        # the annotation declares a root the AST can't see (C callback,
        # RPC dispatch) — signal:* roots get handler checking too
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "# tpusync: thread-root=signal:SIGPROF\n"
            "def on_prof_tick():\n"
            "    with _lock:\n"
            "        pass\n")
        hits = findings_of(src, "signal-unsafe-handler")
        assert len(hits) == 1
        assert "SIGPROF" in hits[0].message


class TestCallbackUnderLock:
    def test_positive(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.on_done = None\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            self.on_done()\n")
        hits = findings_of(src, "callback-under-lock")
        assert len(hits) == 1
        assert "on_done" in hits[0].message
        assert "C._lock" in hits[0].message

    def test_negative_outside_lock(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.on_done = None\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "        self.on_done()\n")
        assert findings_of(src, "callback-under-lock") == []

    def test_suppression(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.on_done = None\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            self.on_done()  # tpusync: disable=callback-under-lock\n")
        assert findings_of(src, "callback-under-lock") == []


# ---------------------------------------------------------------------------
# the thread-root graph on a miniature program


class TestThreadRootGraph:
    def mini(self):
        main_mod = SyncModule("app/main.py", (
            "import threading\n"
            "from app.work import Pump\n"
            "def run():\n"
            "    p = Pump()\n"
            "    t = threading.Thread(target=p.loop, name='pump')\n"
            "    t.start()\n"))
        work_mod = SyncModule("app/work.py", (
            "class Pump:\n"
            "    def loop(self):\n"
            "        while True:\n"
            "            self._tick()\n"
            "    def _tick(self):\n"
            "        pass\n"))
        return build_program([main_mod, work_mod])

    def fn(self, program, qualname):
        return next(f for f in program.functions if f.qualname == qualname)

    def test_spawn_target_gets_thread_root(self):
        program = self.mini()
        assert "thread:pump" in self.fn(program, "Pump.loop").roots

    def test_roots_propagate_to_callees(self):
        program = self.mini()
        # _tick is private and only called from the spawned loop: it runs
        # on the pump thread (plus main, since loop is a public method)
        assert "thread:pump" in self.fn(program, "Pump._tick").roots

    def test_public_defs_rooted_at_main(self):
        program = self.mini()
        assert "main" in self.fn(program, "run").roots

    def test_root_census(self):
        census = self.mini().root_census()
        assert census["thread:pump"] == 2      # loop + _tick
        assert census["main"] >= 2

    def test_lock_registry(self):
        m = SyncModule("m.py", (
            "import threading\n"
            "G = threading.RLock()\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"))
        program = build_program([m])
        kinds = {lid.display: kind for lid, kind in program.locks.items()}
        assert kinds == {"G": "RLock", "C._lock": "Lock"}
        assert LockId("cls", "m.py", "C", "_lock") in program.locks


# ---------------------------------------------------------------------------
# repo-wide gate


class TestRepoGate:
    def test_rule_registry_complete(self):
        import tools.tpusync.rules  # noqa: F401

        assert {r.name for r in RULES} == {
            "unguarded-shared-write", "lock-order-inversion",
            "blocking-under-lock", "signal-unsafe-handler",
            "callback-under-lock"}

    def test_seeded_race_detected(self, tmp_path):
        """The injected-race fixture: a two-root unguarded write must exit
        1 and the diagnostic must name the function, the candidate lock
        and the racing thread roots."""
        bad = tmp_path / "racy.py"
        bad.write_text(
            "import threading\n"
            "class Pump:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.total = 0\n"
            "    def loop(self):\n"
            "        self.total += 1\n"
            "    def add(self, n):\n"
            "        self.total += n\n"
            "    def launch(self):\n"
            "        t = threading.Thread(target=self.loop, name='pump')\n"
            "        t.start()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpusync", str(bad),
             "--baseline", ".tpusync-baseline.json",
             "--root", str(tmp_path)],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 1
        assert "unguarded-shared-write" in proc.stdout
        assert "Pump.total" in proc.stdout          # the attribute
        assert "Pump.loop" in proc.stdout           # a racing function
        assert "thread:pump" in proc.stdout         # the spawned root
        assert "main" in proc.stdout                # ... racing main
        assert "Pump._lock" in proc.stdout          # the candidate guard

    def test_stale_baseline_rots(self, tmp_path):
        """Baseline rot parity with the other gates: an entry for a file
        that no longer produces findings fails the gate until pruned.
        Runs on a tiny synthetic scope — rot semantics live in the shared
        baseline machinery, so a one-file tree exercises them fully."""
        import json

        (tmp_path / "clean.py").write_text("def ok():\n    return 1\n")
        stale = tmp_path / "stale.json"
        stale.write_text(json.dumps({
            "version": 1, "tool": "tpusync",
            "counts": {"clean.py::blocking-under-lock": 3}}))

        def run(*extra):
            return subprocess.run(
                [sys.executable, "-m", "tools.tpusync",
                 str(tmp_path / "clean.py"), "--root", str(tmp_path),
                 "--baseline", str(stale), *extra],
                cwd=REPO, capture_output=True, text=True, timeout=600)

        proc = run()
        assert proc.returncode == 1
        assert "stale" in proc.stdout
        # --prune-baseline ratchets it away, then the gate is green
        assert run("--prune-baseline").returncode == 0
        assert run().returncode == 0

    def test_sync_script_gate(self):
        """scripts/sync.sh — the CI entry point — must pass on the tree:
        the committed host-orchestration scope + committed zero-debt
        baseline analyze clean. A new unguarded write / lock cycle /
        blocking call under a lock fails this test (and therefore
        tier-1)."""
        proc = subprocess.run(
            ["bash", "scripts/sync.sh"],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, \
            f"scripts/sync.sh failed:\n{proc.stdout}\n{proc.stderr}"
