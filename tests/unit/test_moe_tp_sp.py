"""MoE / TP / SP tests — analogs of reference tests/unit/moe/test_moe.py,
moe/test_moe_tp.py, and the (post-reference) Ulysses sequence-parallel tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.parallel.moe import top1gating, top2gating, _capacity

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests



def _engine(preset="tiny", tp=1, sp=1, ep=1, zero=0, gas=1,
            sequence_parallel_impl="ulysses", **model_kw):
    model = create_model(preset, **model_kw)
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": gas,
           "steps_per_print": 1000,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": zero},
           "parallel": {"tensor_parallel_size": tp,
                        "sequence_parallel_size": sp,
                        "expert_parallel_size": ep,
                        "sequence_parallel_impl": sequence_parallel_impl}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def _token_batch(engine, seq=16, seed=0, vocab=256):
    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    ids = jax.random.randint(jax.random.PRNGKey(seed), (gas, gb, seq), 0, vocab)
    return {"input_ids": ids}


class TestGating:
    def test_capacity(self):
        assert _capacity(64, 8, 1.0) == 8
        assert _capacity(64, 8, 1.25) == 10
        assert _capacity(4, 8, 1.0) == 4  # min_capacity floor

    def test_top1_dispatch_conservation(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
        out = top1gating(logits, capacity_factor=2.0)
        # each token dispatched to at most one (expert, slot)
        per_token = out.dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 1).all()
        # with generous capacity nothing is dropped
        assert float(per_token.sum()) == 64
        # each (expert, slot) holds at most one token
        per_slot = out.dispatch.sum(axis=0)
        assert (np.asarray(per_slot) <= 1).all()

    def test_top1_capacity_drops(self):
        # all tokens prefer expert 0 -> capacity limits dispatch
        logits = jnp.zeros((64, 8)).at[:, 0].set(10.0)
        out = top1gating(logits, capacity_factor=1.0)
        cap = _capacity(64, 8, 1.0)
        assert float(out.dispatch.sum()) == cap
        # aux loss is high when load is imbalanced
        balanced = top1gating(jax.random.normal(jax.random.PRNGKey(0), (64, 8)))
        assert float(out.aux_loss) > float(balanced.aux_loss)

    def test_top2_two_experts_per_token(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
        out = top2gating(logits, capacity_factor=2.0)
        per_token = out.dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 2).all()
        assert float(per_token.sum()) > 64  # most tokens get 2 experts
        # combine weights normalized <= 1
        tot = out.combine.sum(axis=(1, 2))
        assert (np.asarray(tot) <= 1.0 + 1e-5).all()

    def test_gate_values_match_softmax(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
        out = top1gating(logits, capacity_factor=4.0)
        gates = jax.nn.softmax(logits, axis=-1)
        picked = np.asarray(out.combine.sum(axis=(1, 2)))
        expect = np.asarray(gates.max(axis=-1))
        np.testing.assert_allclose(picked, expect, rtol=1e-5)


class TestMoETraining:
    def test_moe_model_trains(self):
        engine = _engine("moe-tiny")
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_expert_parallel(self):
        """EP over the 8-device data axis: experts sharded, training works."""
        engine = _engine("moe-tiny", ep=8)
        # expert weight leading dim sharded over the 'expert' mesh axis
        spec = engine.plan.param_specs["layers"]["mlp"]["w_up"]
        assert "expert" in str(spec)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_ep_matches_no_ep(self):
        """EP is a layout change only: same loss trajectory as replicated."""
        e1 = _engine("moe-tiny", ep=1)
        e2 = _engine("moe-tiny", ep=8)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_moe_with_zero2(self):
        engine = _engine("moe-tiny", ep=8, zero=2)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
        assert all(np.isfinite(losses))


class TestTensorParallel:
    def test_tp_matches_single(self):
        e1 = _engine("tiny", tp=1)
        e2 = _engine("tiny", tp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_tp_param_layout(self):
        e = _engine("tiny", tp=2)
        wq_spec = e.plan.param_specs["layers"]["attn"]["wq"]
        assert "model" in str(wq_spec)


class TestSequenceParallel:
    def test_sp_matches_single(self):
        e1 = _engine("tiny", sp=1)
        e2 = _engine("tiny", sp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_sp_tp_compose(self):
        e1 = _engine("tiny")
        e2 = _engine("tiny", sp=2, tp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_3d_zero1_pp_tp(self):
        """3D composition: ZeRO-1 x PP x TP on the 8-device mesh (the
        reference's supported combination — PP x ZeRO>=2 is asserted out
        there too, pipe/engine.py:56)."""
        model = create_model("tiny", num_layers=4)
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 2,
               "steps_per_print": 1000,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 1},
               "parallel": {"pipeline_parallel_size": 2,
                            "tensor_parallel_size": 2,
                            "sequence_parallel_size": 1}}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_pp_zero2_rejected(self):
        model = create_model("tiny", num_layers=4)
        with pytest.raises(ValueError, match="ZeRO stage <= 1"):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                        "zero_optimization": {"stage": 2},
                        "parallel": {"pipeline_parallel_size": 2}})


class TestMoEV2:
    def test_ep_smaller_than_dp_matches_dense(self):
        """ep=2 < total dp=8: experts shard over the 'expert' axis, each
        expert replicated across 4 'data' ranks — trajectory identical to
        no-EP (reference expert-data-parallel groups, groups.py:156)."""
        e1 = _engine("moe-tiny", ep=1)
        e2 = _engine("moe-tiny", ep=2)
        assert int(e2.mesh.shape["expert"]) == 2
        assert int(e2.mesh.shape["data"]) == 4
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_no_drop_keeps_every_token(self):
        from deepspeed_tpu.parallel.moe import top1gating

        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
        # heavily skewed: without capacity all tokens must still dispatch
        logits = logits.at[:, 0].add(5.0)
        out = top1gating(logits, capacity_factor=1.0, drop_tokens=False)
        assert float(out.dispatch.sum()) == 64.0
        dropped = top1gating(logits, capacity_factor=1.0, drop_tokens=True)
        assert float(dropped.dispatch.sum()) < 64.0

    def test_no_drop_top2(self):
        from deepspeed_tpu.parallel.moe import top2gating

        logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
        logits = logits.at[:, 0].add(5.0)
        out = top2gating(logits, capacity_factor=0.5, drop_tokens=False)
        # every token keeps both its experts
        assert float(out.dispatch.sum()) == 128.0

    def test_rts_top2_rejected(self):
        from deepspeed_tpu.parallel.moe import moe_mlp

        x = jnp.zeros((1, 8, 16))
        router = jnp.zeros((16, 4))
        experts = {"w_up": jnp.zeros((4, 16, 32)),
                   "w_down": jnp.zeros((4, 32, 16))}
        with pytest.raises(ValueError, match="top-1 only"):
            moe_mlp(x, router, experts, "gelu", top_k=2, use_rts=True,
                    rng=jax.random.PRNGKey(0))

    def test_rts_random_selection(self):
        from deepspeed_tpu.parallel.moe import top1gating

        logits = jnp.zeros((64, 2)).at[:, 0].add(1.0)  # all want expert 0
        seq = top1gating(logits, capacity_factor=1.0)
        rts = top1gating(logits, capacity_factor=1.0, use_rts=True,
                         rng=jax.random.PRNGKey(3))
        C = 32
        assert float(seq.dispatch.sum()) == C and float(rts.dispatch.sum()) == C
        # sequential keeps the FIRST C tokens; RTS keeps a random subset
        seq_tokens = np.asarray(seq.dispatch.sum(axis=(1, 2)))
        rts_tokens = np.asarray(rts.dispatch.sum(axis=(1, 2)))
        assert (seq_tokens[:C] == 1).all()
        assert not (rts_tokens[:C] == 1).all()

    def test_pr_moe_residual_trains(self):
        engine = _engine("moe-tiny", ep=1, moe_use_residual=True,
                         moe_top_k=1)
        assert "res_mlp" in engine.params["layers"]
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestSparseDispatch:
    """Sparse scatter/gather dispatch == dense einsum dispatch (the GShard
    formulation) — values AND gradients, across gating variants."""

    def _setup(self, T=64, H=16, F=32, E=4, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 5)
        x = jax.random.normal(ks[0], (2, T // 2, H), jnp.float32)
        router = jax.random.normal(ks[1], (H, E), jnp.float32)
        experts = {"w_up": jax.random.normal(ks[2], (E, H, F)) * 0.1,
                   "w_down": jax.random.normal(ks[3], (E, F, H)) * 0.1,
                   "w_gate": jax.random.normal(ks[4], (E, H, F)) * 0.1}
        return x, router, experts

    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("cap", [0.5, 1.25])
    def test_values_match(self, top_k, cap):
        from deepspeed_tpu.parallel.moe import moe_mlp

        x, router, experts = self._setup()
        outs = {}
        for impl in ("sparse", "einsum"):
            out, aux = moe_mlp(x, router, experts, "gelu", top_k=top_k,
                               capacity_factor=cap, dispatch_impl=impl)
            outs[impl] = (np.asarray(out), float(aux))
        np.testing.assert_allclose(outs["sparse"][0], outs["einsum"][0],
                                   rtol=1e-5, atol=1e-6)
        assert outs["sparse"][1] == outs["einsum"][1]

    @pytest.mark.parametrize("variant", ["rts", "nodrop", "swiglu"])
    def test_variants_match(self, variant):
        from deepspeed_tpu.parallel.moe import moe_mlp

        x, router, experts = self._setup(seed=3)
        kw = dict(top_k=1, capacity_factor=0.5)
        act = "gelu"
        if variant == "rts":
            kw.update(use_rts=True, rng=jax.random.PRNGKey(7))
        elif variant == "nodrop":
            kw.update(drop_tokens=False)
        else:
            act = "swiglu"
        a, aux_a = moe_mlp(x, router, experts, act,
                           dispatch_impl="sparse", **kw)
        b, aux_b = moe_mlp(x, router, experts, act,
                           dispatch_impl="einsum", **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match(self):
        from deepspeed_tpu.parallel.moe import moe_mlp

        x, router, experts = self._setup(seed=5)

        def loss(impl, xx, rt, ex):
            out, aux = moe_mlp(xx, rt, ex, "gelu", top_k=2,
                               capacity_factor=1.0, dispatch_impl=impl)
            return (out ** 2).sum() + aux

        for arg in range(3):
            gs = jax.grad(lambda *a: loss("sparse", *a), argnums=arg)(
                x, router, experts)
            ge = jax.grad(lambda *a: loss("einsum", *a), argnums=arg)(
                x, router, experts)
            jax.tree.map(lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5), gs, ge)

    @pytest.mark.parametrize("ep", [1, 2])
    def test_engine_trajectory_sparse_vs_einsum(self, ep):
        """Full engine: an MoE model trains identically under either
        dispatch (same losses), sparse being the default — including under
        REAL expert parallelism, where the gather/scatter dispatch must
        produce the same cross-device exchange as the einsum's
        constraint-lowered all-to-all."""
        losses = {}
        for impl in ("sparse", "einsum"):
            eng = _engine(preset="moe-tiny", ep=ep, moe_dispatch=impl)
            losses[impl] = [float(eng.train_batch(batch=_token_batch(eng)))
                            for _ in range(3)]
        np.testing.assert_allclose(losses["sparse"], losses["einsum"],
                                   rtol=2e-5, atol=1e-6)


class TestRingAttention:
    def test_ring_matches_dense_attention(self):
        """ring_attention over the seq axis == plain causal attention."""
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.ring import ring_attention
        from deepspeed_tpu.models.transformer import dot_product_attention

        mesh = mesh_mod.build_mesh(ParallelConfig(sequence_parallel_size=4,
                                                  data_parallel_size=2))
        mesh_mod.set_mesh(mesh)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (2, 64, 4, 16))
        k = jax.random.normal(ks[1], (2, 64, 4, 16))
        v = jax.random.normal(ks[2], (2, 64, 4, 16))
        with mesh:
            out = jax.jit(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
        ref = dot_product_attention(q, k, v, None, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ring_gradients_match(self):
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.ring import ring_attention
        from deepspeed_tpu.models.transformer import dot_product_attention

        mesh = mesh_mod.build_mesh(ParallelConfig(sequence_parallel_size=4,
                                                  data_parallel_size=2))
        mesh_mod.set_mesh(mesh)
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (1, 32, 2, 16))
        k = jax.random.normal(ks[1], (1, 32, 2, 16))
        v = jax.random.normal(ks[2], (1, 32, 2, 16))
        with mesh:
            g1 = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
                ring_attention(q, k, v) ** 2), argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(lambda q, k, v: jnp.sum(
            dot_product_attention(q, k, v, None, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{name}")

    def test_ring_training_matches_dense(self):
        """End-to-end: sp=4 ring training trajectory == single-replica.
        Runs in a subprocess: compiling the ring step after other shard_map
        compiles in one process can abort inside the XLA CPU compiler
        (compile-order-dependent partitioner crash; standalone it is
        stable)."""
        import os
        import subprocess
        import sys
        import textwrap

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        script = textwrap.dedent("""
            import os
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import sys; sys.path.insert(0, %r)
            import jax.numpy as jnp
            import numpy as np
            import deepspeed_tpu
            from deepspeed_tpu.models import create_model
            from deepspeed_tpu.parallel import mesh as mesh_mod

            def run(par):
                mesh_mod.reset_mesh()
                model = create_model("tiny", dtype=jnp.float32)
                cfg = {"train_micro_batch_size_per_gpu": 4,
                       "steps_per_print": 1000,
                       "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                       "zero_optimization": {"stage": 0},
                       "parallel": par}
                engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
                ids = jax.random.randint(jax.random.PRNGKey(0), (1, 8, 16), 0, 250)
                return [float(engine.train_batch(batch={"input_ids": ids}))
                        for _ in range(3)]

            l1 = run({"sequence_parallel_size": 1})
            l2 = run({"sequence_parallel_size": 4, "data_parallel_size": 2,
                      "sequence_parallel_impl": "ring"})
            np.testing.assert_allclose(l1, l2, rtol=1e-4)
            print("RING-E2E-OK")
        """ % repo)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True, timeout=420)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "RING-E2E-OK" in out.stdout

    def test_ring_rejects_padding_mask(self):
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.ring import ring_attention

        mesh = mesh_mod.build_mesh(ParallelConfig(sequence_parallel_size=2,
                                                  data_parallel_size=4))
        mesh_mod.set_mesh(mesh)
        q = jnp.zeros((1, 32, 2, 16))
        with pytest.raises(NotImplementedError, match="padding masks"):
            ring_attention(q, q, q, mask=jnp.ones((1, 32)))

    def test_ring_rejects_custom_attention_scale(self):
        """A model with cfg.attention_scale (GPT-Neo uses 1.0) must refuse
        ring SP instead of silently falling back to 1/sqrt(head_dim)."""
        from deepspeed_tpu.config.config import ParallelConfig
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      forward, init_params)
        from deepspeed_tpu.parallel import mesh as mesh_mod
        from deepspeed_tpu.parallel.ring import set_ring_attention

        mesh = mesh_mod.build_mesh(ParallelConfig(sequence_parallel_size=2,
                                                  data_parallel_size=4))
        mesh_mod.set_mesh(mesh)
        set_ring_attention(True)
        try:
            cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                                    num_layers=2, num_heads=2, max_seq_len=32,
                                    attention_scale=1.0)
            params = init_params(jax.random.PRNGKey(0), cfg)
            ids = jnp.zeros((1, 32), jnp.int32)
            with pytest.raises(NotImplementedError,
                               match="custom attention_scale"):
                forward(params, ids, cfg)
        finally:
            set_ring_attention(False)
