"""MoE / TP / SP tests — analogs of reference tests/unit/moe/test_moe.py,
moe/test_moe_tp.py, and the (post-reference) Ulysses sequence-parallel tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.parallel.moe import top1gating, top2gating, _capacity


def _engine(preset="tiny", tp=1, sp=1, ep=1, zero=0, gas=1, **model_kw):
    model = create_model(preset, **model_kw)
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": gas,
           "steps_per_print": 1000,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": zero},
           "parallel": {"tensor_parallel_size": tp,
                        "sequence_parallel_size": sp,
                        "expert_parallel_size": ep}}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def _token_batch(engine, seq=16, seed=0, vocab=256):
    gas = engine.gradient_accumulation_steps()
    gb = engine.train_batch_size() // gas
    ids = jax.random.randint(jax.random.PRNGKey(seed), (gas, gb, seq), 0, vocab)
    return {"input_ids": ids}


class TestGating:
    def test_capacity(self):
        assert _capacity(64, 8, 1.0) == 8
        assert _capacity(64, 8, 1.25) == 10
        assert _capacity(4, 8, 1.0) == 4  # min_capacity floor

    def test_top1_dispatch_conservation(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
        out = top1gating(logits, capacity_factor=2.0)
        # each token dispatched to at most one (expert, slot)
        per_token = out.dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 1).all()
        # with generous capacity nothing is dropped
        assert float(per_token.sum()) == 64
        # each (expert, slot) holds at most one token
        per_slot = out.dispatch.sum(axis=0)
        assert (np.asarray(per_slot) <= 1).all()

    def test_top1_capacity_drops(self):
        # all tokens prefer expert 0 -> capacity limits dispatch
        logits = jnp.zeros((64, 8)).at[:, 0].set(10.0)
        out = top1gating(logits, capacity_factor=1.0)
        cap = _capacity(64, 8, 1.0)
        assert float(out.dispatch.sum()) == cap
        # aux loss is high when load is imbalanced
        balanced = top1gating(jax.random.normal(jax.random.PRNGKey(0), (64, 8)))
        assert float(out.aux_loss) > float(balanced.aux_loss)

    def test_top2_two_experts_per_token(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
        out = top2gating(logits, capacity_factor=2.0)
        per_token = out.dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 2).all()
        assert float(per_token.sum()) > 64  # most tokens get 2 experts
        # combine weights normalized <= 1
        tot = out.combine.sum(axis=(1, 2))
        assert (np.asarray(tot) <= 1.0 + 1e-5).all()

    def test_gate_values_match_softmax(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
        out = top1gating(logits, capacity_factor=4.0)
        gates = jax.nn.softmax(logits, axis=-1)
        picked = np.asarray(out.combine.sum(axis=(1, 2)))
        expect = np.asarray(gates.max(axis=-1))
        np.testing.assert_allclose(picked, expect, rtol=1e-5)


class TestMoETraining:
    def test_moe_model_trains(self):
        engine = _engine("moe-tiny")
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_expert_parallel(self):
        """EP over the 8-device data axis: experts sharded, training works."""
        engine = _engine("moe-tiny", ep=8)
        # expert weight leading dim sharded over the 'expert' mesh axis
        spec = engine.plan.param_specs["layers"]["mlp"]["w_up"]
        assert "expert" in str(spec)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_ep_matches_no_ep(self):
        """EP is a layout change only: same loss trajectory as replicated."""
        e1 = _engine("moe-tiny", ep=1)
        e2 = _engine("moe-tiny", ep=8)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_moe_with_zero2(self):
        engine = _engine("moe-tiny", ep=8, zero=2)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
        assert all(np.isfinite(losses))


class TestTensorParallel:
    def test_tp_matches_single(self):
        e1 = _engine("tiny", tp=1)
        e2 = _engine("tiny", tp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_tp_param_layout(self):
        e = _engine("tiny", tp=2)
        wq_spec = e.plan.param_specs["layers"]["attn"]["wq"]
        assert "model" in str(wq_spec)


class TestSequenceParallel:
    def test_sp_matches_single(self):
        e1 = _engine("tiny", sp=1)
        e2 = _engine("tiny", sp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_sp_tp_compose(self):
        e1 = _engine("tiny")
        e2 = _engine("tiny", sp=2, tp=2)
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_3d_zero1_pp_tp(self):
        """3D composition: ZeRO-1 x PP x TP on the 8-device mesh (the
        reference's supported combination — PP x ZeRO>=2 is asserted out
        there too, pipe/engine.py:56)."""
        model = create_model("tiny", num_layers=4)
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 2,
               "steps_per_print": 1000,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 1},
               "parallel": {"pipeline_parallel_size": 2,
                            "tensor_parallel_size": 2,
                            "sequence_parallel_size": 1}}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_pp_zero2_rejected(self):
        model = create_model("tiny", num_layers=4)
        with pytest.raises(ValueError, match="ZeRO stage <= 1"):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                        "zero_optimization": {"stage": 2},
                        "parallel": {"pipeline_parallel_size": 2}})


class TestMoEV2:
    def test_ep_smaller_than_dp_matches_dense(self):
        """ep=2 < total dp=8: experts shard over the 'expert' axis, each
        expert replicated across 4 'data' ranks — trajectory identical to
        no-EP (reference expert-data-parallel groups, groups.py:156)."""
        e1 = _engine("moe-tiny", ep=1)
        e2 = _engine("moe-tiny", ep=2)
        assert int(e2.mesh.shape["expert"]) == 2
        assert int(e2.mesh.shape["data"]) == 4
        batch = _token_batch(e1)
        l1 = [float(e1.train_batch(batch=batch)) for _ in range(3)]
        l2 = [float(e2.train_batch(batch=batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_no_drop_keeps_every_token(self):
        from deepspeed_tpu.parallel.moe import top1gating

        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
        # heavily skewed: without capacity all tokens must still dispatch
        logits = logits.at[:, 0].add(5.0)
        out = top1gating(logits, capacity_factor=1.0, drop_tokens=False)
        assert float(out.dispatch.sum()) == 64.0
        dropped = top1gating(logits, capacity_factor=1.0, drop_tokens=True)
        assert float(dropped.dispatch.sum()) < 64.0

    def test_no_drop_top2(self):
        from deepspeed_tpu.parallel.moe import top2gating

        logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
        logits = logits.at[:, 0].add(5.0)
        out = top2gating(logits, capacity_factor=0.5, drop_tokens=False)
        # every token keeps both its experts
        assert float(out.dispatch.sum()) == 128.0

    def test_rts_top2_rejected(self):
        from deepspeed_tpu.parallel.moe import moe_mlp

        x = jnp.zeros((1, 8, 16))
        router = jnp.zeros((16, 4))
        experts = {"w_up": jnp.zeros((4, 16, 32)),
                   "w_down": jnp.zeros((4, 32, 16))}
        with pytest.raises(ValueError, match="top-1 only"):
            moe_mlp(x, router, experts, "gelu", top_k=2, use_rts=True,
                    rng=jax.random.PRNGKey(0))

    def test_rts_random_selection(self):
        from deepspeed_tpu.parallel.moe import top1gating

        logits = jnp.zeros((64, 2)).at[:, 0].add(1.0)  # all want expert 0
        seq = top1gating(logits, capacity_factor=1.0)
        rts = top1gating(logits, capacity_factor=1.0, use_rts=True,
                         rng=jax.random.PRNGKey(3))
        C = 32
        assert float(seq.dispatch.sum()) == C and float(rts.dispatch.sum()) == C
        # sequential keeps the FIRST C tokens; RTS keeps a random subset
        seq_tokens = np.asarray(seq.dispatch.sum(axis=(1, 2)))
        rts_tokens = np.asarray(rts.dispatch.sum(axis=(1, 2)))
        assert (seq_tokens[:C] == 1).all()
        assert not (rts_tokens[:C] == 1).all()

    def test_pr_moe_residual_trains(self):
        engine = _engine("moe-tiny", ep=1, moe_use_residual=True,
                         moe_top_k=1)
        assert "res_mlp" in engine.params["layers"]
        batch = _token_batch(engine)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
