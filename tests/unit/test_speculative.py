"""Speculative decoding + parallel-sampling fork tests (ISSUE-10).

Coverage map:
  * n-gram drafter host semantics (longest-first lookup, most recent
    occurrence, cap/no-match behavior);
  * greedy speculative serving bit-identical to offline ``generate()``
    across ragged batches AND under mid-stream preemption/recompute;
  * the RNG satellite: spec-on and spec-off streams bit-identical at
    temperature (token keys derive from the emitted-token index, not the
    iteration count);
  * rejection-sampling statistical test: verify-sampled tokens follow the
    target softmax (deterministic seeds — no flake);
  * fork-then-diverge COW: shared-block refcounts, sibling isolation
    (bit-equality with solo submits), mid-stream fork inheritance;
  * scheduler integration: rollback block accounting, pool-pressure
    auto-disable, EOS/budget mid-verify;
  * draft-model drafter: draft==target accepts everything under greedy,
    state released, same bit-identity;
  * jit stability: ONE verify program across occupancy/acceptance mixes;
  * the acceptance smoke: 16 concurrent requests with a repetitive-text
    workload, --spec ngram bit-identical to the plain path, one verify
    compile, emitted-tokens-per-dispatch > 1.5.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import (ObservabilityConfig, ServingConfig,
                                         SpeculativeConfig)
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, reset_session)
from deepspeed_tpu.serving import ServingEngine
from deepspeed_tpu.serving.speculative import (Drafter, NgramDrafter,
                                               request_stream)


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


@pytest.fixture(scope="module")
def draft_tiny_engine():
    # a second engine over the SAME preset: the ideal drafter (acceptance
    # 1.0 under greedy) and a vocab-compatible stand-in for a small model
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


@pytest.fixture
def obs_session(tmp_path):
    reset_session()
    sess = configure_observability(ObservabilityConfig(
        enabled=True, output_dir=str(tmp_path / "obs"),
        flight_recorder=False))
    yield sess
    reset_session()


def serving(tiny_engine, spec="off", draft_engine=None, **cfg):
    defaults = dict(block_size=16, num_blocks=64, max_seqs=4,
                    max_model_len=128, prefill_chunk=16, max_queue=64)
    defaults.update(cfg)
    speculative = (spec if isinstance(spec, dict)
                   else {"mode": spec, "num_draft_tokens": 4})
    return ServingEngine(tiny_engine,
                         ServingConfig(speculative=speculative, **defaults),
                         draft_engine=draft_engine)


def mixed_prompts(n=8, repetitive=4, seed=0):
    """Ragged prompt mix: ``repetitive`` tiled-pattern prompts (the
    speculation workload) + random-token prompts."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(repetitive):
        pat = rng.randint(0, 250, (rng.randint(4, 8),))
        out.append(np.tile(pat, 6)[: rng.randint(18, 40)])
    for _ in range(n - repetitive):
        out.append(rng.randint(0, 250, (rng.randint(5, 30),)))
    return out


# ---------------------------------------------------------------------------
# n-gram drafter (host-side)
# ---------------------------------------------------------------------------


class TestNgramDrafter:
    def _prop(self, ctx, k=4, **kw):
        return NgramDrafter(**kw)._lookup(np.asarray(ctx, np.int32), k)

    def test_repetitive_context_proposes_continuation(self):
        #        0  1  2  3  4  5  6  7  8
        ctx = [7, 8, 9, 1, 7, 8, 9, 1, 7]   # suffix [1, 7] seen at 3..4
        assert self._prop(ctx, k=3).tolist() == [8, 9, 1]

    def test_longest_ngram_wins(self):
        # suffix tried at n=3 first: [5, 6, 7] matches once; a 1-gram
        # match elsewhere must not shadow it
        ctx = [5, 6, 7, 0, 7, 2, 5, 6, 7]
        assert self._prop(ctx, k=2, ngram_max=3).tolist() == [0, 7]

    def test_most_recent_occurrence_preferred(self):
        ctx = [3, 1, 3, 2, 3]          # 1-gram "3" at 0 and 2: use 2
        assert self._prop(ctx, k=1, ngram_max=1).tolist() == [2]

    def test_no_match_proposes_nothing(self):
        assert self._prop([1, 2, 3, 4, 5], k=4).size == 0

    def test_cap_respected_and_tail_truncates(self):
        ctx = [4, 4, 4, 4]
        assert self._prop(ctx, k=2, ngram_max=1).size <= 2

    def test_propose_uses_full_stream(self):
        from deepspeed_tpu.serving.scheduler import Request

        r = Request(rid=0, prompt=np.array([1, 2, 3]), max_new_tokens=8)
        r.generated = [4, 5]
        assert request_stream(r).tolist() == [1, 2, 3, 4, 5]
        props = NgramDrafter().propose([r], [3])
        assert len(props) == 1

    def test_bad_ngram_bounds_rejected(self):
        with pytest.raises(ValueError):
            NgramDrafter(ngram_max=2, ngram_min=3)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


class TestSpeculativeConfig:
    def test_bad_mode_rejected(self):
        with pytest.raises(ConfigError):
            SpeculativeConfig(mode="beam").validate()

    def test_k_bounds(self):
        with pytest.raises(ConfigError):
            SpeculativeConfig(mode="ngram", num_draft_tokens=0).validate()

    def test_nested_dict_coerces(self):
        cfg = ServingConfig(speculative={"mode": "ngram",
                                         "num_draft_tokens": 2})
        cfg.validate()
        assert isinstance(cfg.speculative, SpeculativeConfig)
        assert cfg.speculative.num_draft_tokens == 2

    def test_k_must_fit_model_len(self):
        with pytest.raises(ConfigError):
            ServingConfig(max_model_len=16, block_size=16, prefill_chunk=16,
                          speculative={"mode": "ngram",
                                       "num_draft_tokens": 16}).validate()

    def test_draft_needs_draft_engine(self, tiny_engine):
        with pytest.raises(ValueError):
            serving(tiny_engine, spec="draft")


# ---------------------------------------------------------------------------
# bit-identity: greedy speculation == generate(), spec-on == spec-off
# ---------------------------------------------------------------------------


class TestSpecBitIdentity:
    # tier-1 budget: the 16-request acceptance smoke (below) covers greedy
    # ngram bit-identity at larger scale; this staggered-admission variant
    # rides the slow suite
    @pytest.mark.slow
    def test_greedy_ngram_matches_generate_ragged(self, tiny_engine):
        prompts = mixed_prompts(8, repetitive=4)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=8))[0]
                for p in prompts]
        srv = serving(tiny_engine, spec="ngram")
        handles = []
        for i, p in enumerate(prompts):      # staggered admissions
            handles.append(srv.submit(p, max_new_tokens=8))
            if i % 3 == 2:
                srv.step()
        srv.run()
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i],
                                          err_msg=f"request {i}")
        assert srv._spec_dispatches > 0

    def test_greedy_spec_survives_preemption_recompute(self, tiny_engine):
        """A pool far too small for the load forces mid-stream eviction +
        recompute WITH speculation on — outputs must stay bit-identical
        (the stored pending token + positional rollback contract)."""
        prompts = mixed_prompts(6, repetitive=3, seed=3)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=10))[0]
                for p in prompts]
        srv = serving(tiny_engine, spec="ngram", num_blocks=7, max_seqs=3,
                      max_model_len=64, prefix_cache=False)
        handles = [srv.submit(p, max_new_tokens=10) for p in prompts]
        srv.run()
        assert srv.sched.preemption_count > 0, \
            "pool was meant to be too small — no preemption exercised"
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i],
                                          err_msg=f"request {i}")

    def test_temperature_stream_bit_stable_spec_on_off(self, tiny_engine):
        """The RNG satellite: same (engine seed, request seed) through the
        spec-off and spec-on paths produces the SAME sampled stream —
        token keys derive from the emitted-token index, so accepting K at
        a time cannot shift anyone's draws."""
        prompts = mixed_prompts(6, repetitive=4, seed=7)
        outs = {}
        for mode in ("off", "ngram"):
            srv = serving(tiny_engine, spec=mode)
            hs = [srv.submit(p, max_new_tokens=8, temperature=0.8,
                             top_k=20, seed=100 + i)
                  for i, p in enumerate(prompts)]
            srv.run()
            outs[mode] = [h.result() for h in hs]
            if mode == "ngram":
                assert srv._spec_accepted > 0, \
                    "no draft ever accepted — the bit-stability claim " \
                    "was not exercised at temperature"
        for i, (a, b) in enumerate(zip(outs["off"], outs["ngram"])):
            np.testing.assert_array_equal(a, b, err_msg=f"request {i}")


# ---------------------------------------------------------------------------
# rejection sampling: spec-sampled tokens follow the target softmax
# ---------------------------------------------------------------------------


class TestRejectionStatistics:
    @pytest.mark.slow   # 512 verify dispatches — statistical, not a gate
    def test_verify_samples_match_target_softmax(self, tiny_engine):
        """512 verify draws (8 rows × 64 dispatches, distinct seeds) at one
        fixed context, temperature=1/top_k=5: the empirical distribution
        must match softmax(top-5 logits). Keys are deterministic — this
        test cannot flake."""
        import jax

        from deepspeed_tpu.models.transformer import forward as fwd
        from deepspeed_tpu.serving import paged_kv

        eng = tiny_engine
        cfg = eng.model.config
        BS, NB, R = 16, 16, 8
        arena = paged_kv.init_paged_cache(cfg, NB + 1, BS, jnp.float32)
        alloc = paged_kv.BlockAllocator(NB)
        prompt = (np.arange(12) * 3) % 250
        n = prompt.size
        MAXB = 64 // BS
        blocks = alloc.alloc(2)
        bt1 = np.zeros((1, MAXB), np.int32)
        bt1[0, :2] = blocks
        prefill = paged_kv.build_prefill_program(cfg)
        chunk = np.zeros((1, 16), np.int32)
        chunk[0, :n] = prompt
        key = jax.random.PRNGKey(0)
        z1, zi, o1 = (np.zeros((1,), np.float32), np.zeros((1,), np.int32),
                      np.ones((1,), np.float32))
        tok, _, arena = prefill(eng.params, arena, bt1, chunk,
                                np.asarray(0, np.int32),
                                np.asarray(n, np.int32),
                                z1, zi, o1, zi, key)
        pending = int(np.asarray(tok)[0])

        # target distribution after the pending token: plain (cache-free)
        # forward over prompt+pending, last position, temp 1 / top-5
        logits = np.asarray(fwd(
            eng.params, np.asarray([list(prompt) + [pending]], np.int32),
            cfg)[0][0, -1], np.float64)
        top5 = np.argsort(logits)[::-1][:5]
        z = logits[top5] - logits[top5].max()
        probs = np.exp(z) / np.exp(z).sum()

        verify = paged_kv.build_verify_program(cfg, 2)
        btR = np.tile(bt1, (R, 1))
        lengths = np.full((R,), n, np.int32)
        tokens = np.zeros((R, 2), np.int32)
        tokens[:, 0] = pending        # every row: plain decode semantics
        n_valid = np.ones((R,), np.int32)
        temps = np.ones((R,), np.float32)
        topks = np.full((R,), 5, np.int32)
        topps = np.ones((R,), np.float32)
        steps = np.zeros((R,), np.int32)
        counts = {int(t): 0 for t in top5}
        draws = 0
        for it in range(64):
            seeds = np.arange(it * R, (it + 1) * R, dtype=np.int32)
            # base-key reuse is the verify contract: randomness comes from
            # fold_in(seeds, token_index), and seeds change per iteration
            sampled, arena = verify(  # tpulint: disable=key-reuse
                eng.params, arena, btR, lengths, tokens, n_valid, temps,
                topks, topps, seeds, steps, key)
            for t in np.asarray(sampled)[:, 0]:
                counts[int(t)] = counts.get(int(t), 0) + 1
                draws += 1
        assert draws == 512
        for t, p_want in zip(top5, probs):
            p_got = counts[int(t)] / draws
            assert abs(p_got - p_want) < 0.06, \
                (f"token {t}: empirical {p_got:.3f} vs softmax "
                 f"{p_want:.3f} — spec sampling is off-distribution")
        # nothing outside the top-5 support may ever be drawn
        assert sum(counts[int(t)] for t in top5) == draws


# ---------------------------------------------------------------------------
# parallel-sampling fork (COW)
# ---------------------------------------------------------------------------


class TestForkCOW:
    def test_submit_n_greedy_identical_and_shared(self, tiny_engine):
        srv = serving(tiny_engine)
        p = mixed_prompts(1, repetitive=0, seed=11)[0]
        want = np.asarray(tiny_engine.generate(p[None],
                                               max_new_tokens=6))[0]
        handles = srv.submit(p, max_new_tokens=6, n=3)
        assert len(handles) == 3
        # step until the fork lands, then assert the sharing is real
        for _ in range(200):
            srv.step()
            if srv._forks == 2:
                break
        assert srv._forks == 2
        parent = handles[0]._req
        shared = [b for b in parent.blocks if srv.alloc.refcount(b) >= 3]
        assert shared, "fork did not share the parent's blocks"
        srv.run()
        for h in handles:   # greedy: every sibling == the parent == offline
            np.testing.assert_array_equal(h.result(), want)

    @pytest.mark.slow   # tier-1 keeps the greedy-vs-oracle variant above
    def test_fork_siblings_bit_identical_to_solo_seeds(self, tiny_engine):
        """Sibling i (seed s+i) must produce EXACTLY what a separately
        submitted request with seed s+i produces — shared blocks, COW and
        scheduling are invisible to the sampled stream."""
        srv = serving(tiny_engine)
        p = mixed_prompts(1, repetitive=0, seed=12)[0]
        handles = srv.submit(p, max_new_tokens=6, temperature=0.9,
                             top_k=30, seed=40, n=3)
        srv.run()
        outs = [h.result() for h in handles]
        assert srv._cow_copies > 0, "no divergent write ever went COW"
        solo = serving(tiny_engine)
        for i, o in enumerate(outs):
            h = solo.submit(p, max_new_tokens=6, temperature=0.9,
                            top_k=30, seed=40 + i)
            solo.run()
            np.testing.assert_array_equal(o, h.result(),
                                          err_msg=f"sibling {i}")
        # at temperature the samples should actually be distinct
        assert len({tuple(o.tolist()) for o in outs}) > 1

    def test_siblings_never_observe_each_others_writes(self, tiny_engine):
        """Greedy + n=4 over a SHARED prompt: if any sibling's write leaked
        into another's blocks, the deterministic outputs would diverge
        from the offline oracle."""
        srv = serving(tiny_engine, max_seqs=6)
        p = mixed_prompts(1, repetitive=1, seed=13)[0]
        want = np.asarray(tiny_engine.generate(p[None],
                                               max_new_tokens=8))[0]
        handles = srv.submit(p, max_new_tokens=8, n=4)
        srv.run()
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want,
                                          err_msg=f"sibling {i}")

    def test_midstream_fork_inherits_and_diverges(self, tiny_engine):
        srv = serving(tiny_engine)
        p = mixed_prompts(1, repetitive=0, seed=14)[0]
        parent = srv.submit(p, max_new_tokens=8, temperature=0.7, seed=3)
        got = []
        for t in parent.stream():
            got.append(t)
            if len(got) == 3:
                sibs = parent.fork(2)
                break
        srv.run()
        pout = parent.result()
        for i, sh in enumerate(sibs):
            sout = sh.result()
            assert sout[:3].tolist() == pout[:3].tolist(), \
                f"sibling {i} lost the inherited tokens"
            assert len(sout) == 8
        # divergence is expected at temperature with distinct seeds
        assert any(sh.result().tolist() != pout.tolist() for sh in sibs)

    def test_fork_requires_decoding_parent(self, tiny_engine):
        srv = serving(tiny_engine)
        h = srv.submit(mixed_prompts(1)[0], max_new_tokens=4)
        with pytest.raises(ValueError):
            h.fork(2)       # still queued
        srv.run()
        with pytest.raises(ValueError):
            h.fork(2)       # already finished

    def test_fork_rejects_short_seeds_list_before_any_sibling(
            self, tiny_engine):
        srv = serving(tiny_engine)
        h = srv.submit(mixed_prompts(1, seed=16)[0], max_new_tokens=8,
                       temperature=0.5, seed=3)
        while not h._req.generated:
            srv.step()
        before = srv.in_flight()
        with pytest.raises(ValueError, match="seeds"):
            h.fork(3, seeds=[7])   # must fail BEFORE creating sibling 0
        assert srv.in_flight() == before
        assert srv._forks == 0
        srv.run()
        assert len(h.result()) == 8

    def test_fork_only_report_has_no_speculation_line(self, tiny_engine,
                                                      obs_session, tmp_path):
        """Parallel sampling without speculation is COW sharing — forks
        belong on the sharing line, not a phantom speculation line."""
        from deepspeed_tpu.observability.report import report

        # the registry is a process singleton and this test renders a
        # report from its ABSOLUTE contents — spec/fork counters left by
        # earlier test modules (rlhf rollouts speculate) would paint a
        # phantom speculation line. Render from a pristine registry.
        get_registry().reset()
        srv = serving(tiny_engine)   # spec off
        handles = srv.submit(mixed_prompts(1, seed=17)[0],
                             max_new_tokens=4, n=2)
        srv.run()
        [h.result() for h in handles]
        srv.close()
        path = str(tmp_path / "metrics.jsonl")
        get_registry().dump_jsonl(path)
        out = report([path])
        assert "speculation:" not in out
        assert "forks=1" in out

    def test_cancel_parent_before_fork_cancels_siblings(self, tiny_engine):
        from deepspeed_tpu.serving import RequestCancelled

        srv = serving(tiny_engine)
        handles = srv.submit(mixed_prompts(1)[0], max_new_tokens=4, n=3)
        assert handles[0].cancel()
        for h in handles:
            assert h.done
            with pytest.raises(RequestCancelled):
                h.result()
        assert srv.in_flight() == 0

    def test_no_block_leak_after_forked_run(self, tiny_engine):
        srv = serving(tiny_engine, prefix_cache=False)
        handles = srv.submit(mixed_prompts(1, seed=15)[0],
                             max_new_tokens=6, temperature=0.5, n=3)
        srv.run()
        [h.result() for h in handles]
        assert srv.alloc.blocks_in_use == 0
        assert srv.alloc.blocks_free == srv.alloc.capacity

    def test_pending_forks_hold_queue_capacity(self, tiny_engine):
        from deepspeed_tpu.serving import QueueFull

        srv = serving(tiny_engine, max_queue=4)
        p = mixed_prompts(1)[0]
        handles = srv.submit(p, max_new_tokens=4, n=3)
        # 1 queued parent + 2 pending siblings = 4 - 1 slots taken: one
        # more fits, the next must shed — pending siblings are in flight
        assert srv.in_flight() == 3
        h4 = srv.submit(p, max_new_tokens=4)
        with pytest.raises(QueueFull):
            srv.submit(p, max_new_tokens=4)
        with pytest.raises(QueueFull):
            srv.submit(p, max_new_tokens=4, n=1)
        srv.run()
        [h.result() for h in handles + [h4]]

    def test_forked_siblings_report_ttft(self, tiny_engine):
        srv = serving(tiny_engine)
        handles = srv.submit(mixed_prompts(1, seed=21)[0],
                             max_new_tokens=5, temperature=0.7, n=3)
        srv.run()
        for h in handles:
            h.result()
            assert h._req.first_token_s is not None
            assert h._req.ttft_s is not None and h._req.ttft_s >= 0
        # the sibling's TTFT clock starts at the client's submit: it
        # cannot beat the parent, whose prefill it waited through
        parent = handles[0]._req
        for h in handles[1:]:
            assert h._req.ttft_s >= parent.ttft_s

    def test_cancel_counters_balance_with_forks(self, tiny_engine,
                                                obs_session):
        srv = serving(tiny_engine)
        p = mixed_prompts(1)[0]
        # parent cancel cascades to 2 pending siblings: 3 cancellations
        handles = srv.submit(p, max_new_tokens=4, n=3)
        assert handles[0].cancel()
        # a pre-fork sibling cancelled directly also counts
        h2 = srv.submit(p, max_new_tokens=4, n=2)
        assert h2[1].cancel()
        srv.run()
        h2[0].result()
        assert srv.sched.cancelled_count == 4
        c = get_registry().counter("serving/requests_cancelled")
        assert c is not None and c.value() == 4
        sub = get_registry().counter(
            "serving/requests_submitted").value(tenant="default")
        done = get_registry().counter(
            "serving/requests_completed").value(tenant="default")
        assert sub == done + c.value()   # the ledger balances


# ---------------------------------------------------------------------------
# scheduler integration: rollback, pressure, EOS/budget
# ---------------------------------------------------------------------------


class _WrongDrafter(Drafter):
    """Adversarial drafter: always proposes an off-by-one token — every
    draft must be rejected, every verify must still emit exactly the
    non-speculative token."""

    name = "wrong"

    def propose(self, reqs, caps):
        return [np.full((k,), int(request_stream(r)[-1] + 1) % 7, np.int32)
                if k > 0 else np.zeros((0,), np.int32)
                for r, k in zip(reqs, caps)]


class TestSpecScheduling:
    def test_always_rejected_drafter_still_lossless(self, tiny_engine):
        prompts = mixed_prompts(4, repetitive=2, seed=21)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=6))[0]
                for p in prompts]
        srv = serving(tiny_engine, spec="ngram")
        srv._drafter = _WrongDrafter()
        srv.sched.on_release = srv._drafter.release
        handles = [srv.submit(p, max_new_tokens=6) for p in prompts]
        srv.run()
        assert srv._spec_proposed > 0 and srv._spec_accepted == 0
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i],
                                          err_msg=f"request {i}")
        # rollback returned every speculative block: nothing may leak
        cached = (srv.prefix.cached_blocks if srv.prefix else 0)
        assert srv.alloc.blocks_in_use == cached

    def test_verify_respects_max_new_budget(self, tiny_engine):
        srv = serving(tiny_engine, spec="ngram")
        p = np.tile(np.array([5, 6, 7]), 10)     # highly repetitive
        h = srv.submit(p, max_new_tokens=3)
        srv.run()
        assert len(h.result()) == 3

    def test_eos_mid_verify_stops_exactly_like_generate(self, tiny_engine):
        p = np.tile(np.array([5, 6, 7]), 8)
        full = np.asarray(tiny_engine.generate(p[None],
                                               max_new_tokens=10))[0]
        eos = int(full[4])     # an actual mid-stream token as EOS
        want = list(full[:list(full).index(eos) + 1])
        srv = serving(tiny_engine, spec="ngram")
        h = srv.submit(p, max_new_tokens=10, eos_token_id=eos)
        srv.run()
        assert h.result().tolist() == want

    def test_pool_pressure_disables_rows_not_correctness(self, tiny_engine):
        """min_free_blocks above the whole pool: speculation globally
        backs off (caps 0 → plain decode inside the verify program) and
        output stays exact."""
        prompts = mixed_prompts(3, repetitive=2, seed=22)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=6))[0]
                for p in prompts]
        srv = serving(tiny_engine,
                      spec={"mode": "ngram", "num_draft_tokens": 4,
                            "min_free_blocks": 10_000})
        handles = [srv.submit(p, max_new_tokens=6) for p in prompts]
        srv.run()
        assert srv._spec_proposed == 0      # the guard held
        assert srv._spec_dispatches > 0     # the verify still decoded
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i])

    def test_truncate_blocks_rollback_accounting(self):
        from deepspeed_tpu.serving.paged_kv import BlockAllocator
        from deepspeed_tpu.serving.scheduler import Request, Scheduler

        sched = Scheduler(ServingConfig(
            block_size=4, num_blocks=16, max_seqs=2, max_model_len=32,
            prefill_chunk=4, max_queue=8))
        r = Request(rid=0, prompt=np.arange(4), max_new_tokens=8)
        r.blocks = sched.alloc.alloc(5)
        assert sched.truncate_blocks(r, 9) == 2     # 9 tokens → 3 blocks
        assert len(r.blocks) == 3
        assert sched.alloc.blocks_in_use == 3
        assert sched.truncate_blocks(r, 12) == 0    # already covered

    def test_try_extend_blocks_never_preempts(self):
        from deepspeed_tpu.serving.scheduler import Request, Scheduler

        sched = Scheduler(ServingConfig(
            block_size=4, num_blocks=8, max_seqs=2, max_model_len=32,
            prefill_chunk=4, max_queue=8))
        victim = Request(rid=0, prompt=np.arange(4), max_new_tokens=8)
        victim.blocks = sched.alloc.alloc(8)
        sched.running[0] = victim
        victim.row = 0
        victim.state = "decode"
        sched._admit_index[victim.rid] = 0
        asker = Request(rid=1, prompt=np.arange(4), max_new_tokens=8)
        assert not sched.try_extend_blocks(asker, 8)
        assert victim.state == "decode"             # nobody was evicted
        assert len(victim.blocks) == 8
        assert sched.preemption_count == 0


# ---------------------------------------------------------------------------
# draft-model drafter
# ---------------------------------------------------------------------------


class TestDraftModelDrafter:
    def test_draft_equals_target_accepts_everything(self, tiny_engine,
                                                    draft_tiny_engine):
        prompts = mixed_prompts(5, repetitive=2, seed=31)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=8))[0]
                for p in prompts]
        srv = serving(tiny_engine, spec="draft",
                      draft_engine=draft_tiny_engine)
        srv._drafter.engine.params = tiny_engine.params   # identical draft
        handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
        srv.run()
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i],
                                          err_msg=f"request {i}")
        assert srv._spec_proposed > 0
        assert srv._spec_accepted == srv._spec_proposed, \
            "an identical draft model must be accepted in full under greedy"
        assert srv._spec_emitted / srv._spec_dispatches > 2.0

    @pytest.mark.slow   # draft-path coverage gates via accepts_everything
    def test_draft_state_and_blocks_released(self, tiny_engine,
                                             draft_tiny_engine):
        srv = serving(tiny_engine, spec="draft",
                      draft_engine=draft_tiny_engine, prefix_cache=False)
        hs = [srv.submit(p, max_new_tokens=5)
              for p in mixed_prompts(3, repetitive=1, seed=32)]
        srv.run()
        [h.result() for h in hs]
        assert srv._drafter._state == {}
        assert srv.alloc.blocks_in_use == 0

    @pytest.mark.slow   # the ngram preemption/recompute variant gates
    def test_draft_survives_preemption(self, tiny_engine,
                                       draft_tiny_engine):
        """Draft KV shares the pool: under pressure the drafter backs off
        and preempted requests recompute — output must stay exact and the
        pool must balance afterwards."""
        prompts = mixed_prompts(4, repetitive=2, seed=33)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=8))[0]
                for p in prompts]
        srv = serving(tiny_engine, spec="draft",
                      draft_engine=draft_tiny_engine, num_blocks=12,
                      max_seqs=2, max_model_len=64, prefix_cache=False)
        srv._drafter.engine.params = tiny_engine.params
        handles = [srv.submit(p, max_new_tokens=8) for p in prompts]
        srv.run()
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(), want[i],
                                          err_msg=f"request {i}")
        assert srv.alloc.blocks_in_use == 0

    def test_vocab_mismatch_rejected(self, tiny_engine):
        from deepspeed_tpu.serving.speculative import make_drafter

        class FakeEngine:
            class model:
                class config:
                    vocab_size = 17
            class config:
                dtype = jnp.float32

        cfg = ServingConfig(speculative={"mode": "draft"})
        cfg.validate()
        with pytest.raises(ValueError):
            make_drafter(cfg, tiny_engine, None, 8,
                         draft_engine=FakeEngine())


# ---------------------------------------------------------------------------
# jit stability + the acceptance smoke
# ---------------------------------------------------------------------------


class TestSpecJit:
    def test_one_verify_program_across_acceptance_mixes(self, tiny_engine,
                                                        obs_session):
        """Occupancy, proposal counts and acceptance mixes are DATA: the
        verify program must compile exactly once (recompile-watchdog
        counter), exactly like the plain decode program."""
        compiles = get_registry().counter("xla/compiles")
        before = compiles.value(where="serving/verify")
        srv = serving(tiny_engine, spec="ngram")
        prompts = mixed_prompts(7, repetitive=4, seed=41)
        handles = []
        for i, p in enumerate(prompts):
            handles.append(srv.submit(
                p, max_new_tokens=5, temperature=0.0 if i % 2 else 0.5,
                top_k=0 if i % 3 else 7, seed=i))
            srv.step()
        srv.run()
        assert compiles.value(where="serving/verify") - before == 1
        steady = get_registry().counter("xla/steady_state_recompiles")
        assert steady.value(where="serving/verify") == 0


class TestSpecSmoke:
    def test_sixteen_request_spec_acceptance(self, tiny_engine, obs_session,
                                             tmp_path):
        """The ISSUE-10 acceptance smoke: the 16-request serving smoke
        re-run with --spec ngram on a repetitive-text workload — greedy
        outputs bit-identical to the non-speculative path (== offline
        generate()), ONE verify program across every per-row acceptance
        mix, emitted-tokens-per-target-dispatch > 1.5, and the speculation
        metrics render in the report CLI."""
        compiles = get_registry().counter("xla/compiles")
        before = compiles.value(where="serving/verify")
        srv = serving(tiny_engine, spec="ngram", block_size=16,
                      num_blocks=64, max_seqs=8, max_model_len=128,
                      prefill_chunk=16, max_queue=64)
        prompts = mixed_prompts(16, repetitive=16, seed=5)
        want = [np.asarray(tiny_engine.generate(p[None],
                                                max_new_tokens=8))[0]
                for p in prompts]
        handles = []
        for i, p in enumerate(prompts):          # staggered arrivals
            handles.append(srv.submit(p, max_new_tokens=8,
                                      tenant=f"t{i % 3}"))
            if i % 4 == 3:
                srv.step()
        srv.run()

        # 1) bit-identical to the non-speculative path (== generate())
        for i, (p, h) in enumerate(zip(prompts, handles)):
            np.testing.assert_array_equal(
                h.result(), want[i], err_msg=f"request {i} diverged")

        # 2) ONE verify program across varying per-row acceptance counts
        assert compiles.value(where="serving/verify") - before == 1

        # 3) the speculative win on repetitive text
        epd = srv._spec_emitted / srv._spec_dispatches
        assert epd > 1.5, f"emitted/dispatch {epd:.2f} <= 1.5"
        assert srv._spec_accepted > 0

        # 4) metrics flow and render
        reg = get_registry()
        assert reg.gauge("serving/spec_emitted_per_dispatch").value() > 1.5
        srv.close()
        from deepspeed_tpu.observability.report import report

        path = str(tmp_path / "metrics.jsonl")
        reg.dump_jsonl(path)
        out = report([path])
        assert "speculation:" in out
        assert "emitted_per_dispatch" in out


# ---------------------------------------------------------------------------
# audit integration
# ---------------------------------------------------------------------------


class TestSpecAudit:
    # tier-1's tpucost repo gate already traces all three spec entries
    # against the committed baseline; the direct audit run rides slow
    @pytest.mark.slow
    def test_verify_and_draft_entries_registered_clean(self, tiny_engine,
                                                       draft_tiny_engine):
        from tools.tpuaudit.core import run_audit
        from tools.tpuaudit.registry import get_entry_points

        srv = serving(tiny_engine, spec="draft",
                      draft_engine=draft_tiny_engine)
        names = ["serving/verify", "serving/draft_decode",
                 "serving/draft_prefill"]
        eps = get_entry_points(names)
        assert [ep.name for ep in eps] == names
        assert all(ep.donate_argnums == (1,) for ep in eps)  # arenas
        findings = run_audit(eps, publish_metrics=False)
        assert findings == [], [f"{f.entry}:{f.check}" for f in findings]
        del srv
