"""Request tracing + serving goodput (ISSUE-15).

Coverage map:
  * ``ServeGoodput`` bucket math under a fake clock — buckets sum to wall
    EXACTLY, compile seconds dedup out of the phase that contained them,
    idle accumulates between iterations, SLO burn rates;
  * ``RequestTracer`` unit semantics — deterministic head sampling, tail
    retention of outliers at sample rate 0, per-trace event cap with the
    terminal event never dropped;
  * single-engine trace assembly — causal chain (submitted → admitted →
    prefill → decode → finished), fork lineage (``submit(n=)``), deadline
    and preemption outliers, flight-ring terminal events WITHOUT tracing;
  * the chaos-gate scenario — 16 staggered requests through a 3-replica
    disaggregated fleet with a mid-stream replica kill: every request's
    chain is complete, the killed replica's requests resubmit under the
    SAME trace_id (attempt + 1), handoff export/import stitch across two
    replicas, the Chrome trace loads, and serving goodput buckets sum to
    wall per replica;
  * the disabled path — enabling tracing adds ZERO dispatches and ZERO
    compiles (recompile-watchdog-counted) and leaves streams bit-identical;
  * report CLI sections, crash-dump in-flight tail, metricsdoc gate, and
    the rollout-manifest trace_ids column.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import (FleetConfig, ObservabilityConfig,
                                         ServingConfig)
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, get_session,
                                         reset_session)
from deepspeed_tpu.observability.reqtrace import RequestTracer
from deepspeed_tpu.observability.servegoodput import BUCKETS, ServeGoodput
from deepspeed_tpu.serving import ServingEngine
from deepspeed_tpu.serving.fleet import (ROLE_DECODE, ROLE_PREFILL,
                                         FleetRouter, build_replicas)

SCFG = dict(block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16, max_queue=64)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module", autouse=True)
def _registry_hygiene():
    """The MetricsRegistry is a process singleton: serving counters this
    module increments (forks, requests_*) would leak into later test
    files that assert ABSOLUTE counter values (test_speculative's report
    renders). Restore the pristine registry after the module."""
    yield
    get_registry().reset()


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


@pytest.fixture
def traced_session(tmp_path):
    reset_session()
    sess = configure_observability(ObservabilityConfig(
        enabled=True, output_dir=str(tmp_path / "obs"),
        request_tracing=True, serve_goodput=True, flight_recorder=False))
    yield sess
    reset_session()


def serving(tiny_engine, clock=None, **cfg):
    defaults = dict(SCFG)
    defaults.update(cfg)
    return ServingEngine(tiny_engine, ServingConfig(**defaults),
                         **({"clock": clock} if clock else {}))


def mk_prompts(n, lo=4, hi=50, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 50, size=rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# ServeGoodput bucket math (fake clock, device-free)
# ---------------------------------------------------------------------------


class TestServeGoodputMath:
    def test_buckets_sum_to_wall_exactly(self):
        clk = FakeClock()
        a = ServeGoodput(registry=get_registry(), replica="7", clock=clk)
        # iteration 1: prefill 0.4s + 0.1s host remainder
        a.iteration_begin(clk.t)
        a.note_phase("prefill", 0.4)
        clk.advance(0.5)
        a.iteration_end(clk.t)
        # 0.3s idle gap
        clk.advance(0.3)
        # iteration 2: decode 0.2 + sample_host 0.05 + 0.05 remainder
        a.iteration_begin(clk.t)
        a.note_phase("decode", 0.2)
        a.note_phase("sample_host", 0.05)
        clk.advance(0.3)
        a.iteration_end(clk.t)
        tot = a.totals()
        assert tot["wall_s"] == pytest.approx(1.1, abs=1e-12)
        assert sum(tot["buckets"].values()) == pytest.approx(
            tot["wall_s"], abs=1e-12)
        b = tot["buckets"]
        assert b["prefill"] == pytest.approx(0.4)
        assert b["idle"] == pytest.approx(0.3)
        assert b["decode"] == pytest.approx(0.2)
        assert b["sample_host"] == pytest.approx(0.05)
        assert b["scheduling_host"] == pytest.approx(0.15)
        assert set(b) == set(BUCKETS)

    def test_compile_dedup_inside_phase(self):
        """Compile seconds noted mid-iteration land in the compile bucket
        and are DEDUCTED from the phase span that contained them — the
        same wall second is never counted twice."""
        clk = FakeClock()
        a = ServeGoodput(registry=get_registry(), clock=clk)
        a.iteration_begin(clk.t)
        a.note_compile(1.0)          # fired inside the prefill dispatch
        a.note_phase("prefill", 1.2)  # span duration INCLUDES the compile
        clk.advance(1.3)
        a.iteration_end(clk.t)
        tot = a.totals()
        b = tot["buckets"]
        assert b["compile"] == pytest.approx(1.0)
        assert b["prefill"] == pytest.approx(0.2)
        assert b["scheduling_host"] == pytest.approx(0.1)
        assert sum(b.values()) == pytest.approx(tot["wall_s"], abs=1e-12)

    def test_mid_iteration_read_stays_consistent(self):
        """A concurrent dump_metrics can read totals() while an iteration
        is open: the open iteration's accounted phases extend the wall so
        buckets still sum to wall and the fraction never exceeds 1."""
        clk = FakeClock()
        a = ServeGoodput(registry=get_registry(), clock=clk)
        a.iteration_begin(clk.t)
        a.note_phase("prefill", 0.4)
        tot = a.totals()                       # mid-iteration read
        assert tot["wall_s"] == pytest.approx(0.4, abs=1e-12)
        assert sum(tot["buckets"].values()) == pytest.approx(
            tot["wall_s"], abs=1e-12)
        assert tot["goodput_fraction"] <= 1.0
        clk.advance(0.5)
        a.iteration_end(clk.t)
        tot = a.totals()
        assert sum(tot["buckets"].values()) == pytest.approx(
            tot["wall_s"], abs=1e-12)

    def test_goodput_fraction_and_tokens(self):
        clk = FakeClock()
        a = ServeGoodput(registry=get_registry(), clock=clk)
        a.iteration_begin(clk.t)
        a.note_phase("decode", 0.5)
        a.note_tokens(10)
        clk.advance(1.0)
        a.iteration_end(clk.t)
        tot = a.totals()
        assert tot["goodput_fraction"] == pytest.approx(0.5)
        assert tot["tokens_per_sec"] == pytest.approx(10.0)

    def test_slo_burn_rates(self):
        a = ServeGoodput(registry=get_registry(), ttft_slo_ms=100.0,
                         tpot_slo_ms=10.0, slo_budget=0.1)
        for ttft in (50, 150, 80, 90):       # 1/4 breach
            a.note_request(ttft_ms=ttft, tpot_ms=5.0)
        tot = a.totals()
        assert tot["ttft_slo_burn_rate"] == pytest.approx(2.5)  # 0.25/0.1
        assert tot["tpot_slo_burn_rate"] == pytest.approx(0.0)

    def test_reset_restarts_window(self):
        clk = FakeClock()
        a = ServeGoodput(registry=get_registry(), clock=clk)
        a.iteration_begin(clk.t)
        a.note_phase("prefill", 1.0)
        clk.advance(1.0)
        a.iteration_end(clk.t)
        a.reset()
        assert a.totals()["wall_s"] == 0.0
        clk.advance(5.0)
        a.iteration_begin(clk.t)
        clk.advance(0.25)
        a.iteration_end(clk.t)
        tot = a.totals()
        # the 5s pre-reset gap is NOT idle — the window restarted
        assert tot["wall_s"] == pytest.approx(0.25)
        assert tot["buckets"]["idle"] == 0.0


# ---------------------------------------------------------------------------
# RequestTracer unit semantics (device-free)
# ---------------------------------------------------------------------------


class TestRequestTracerUnit:
    def test_head_sampling_deterministic(self):
        clk = FakeClock()
        rt_all = RequestTracer(sample_rate=1.0, clock=clk)
        rt_none = RequestTracer(sample_rate=0.0, clock=clk)
        assert all(rt_all.start().sampled for _ in range(8))
        assert not any(rt_none.start().sampled for _ in range(8))

    def test_tail_retention_keeps_outliers_at_rate_zero(self):
        clk = FakeClock()
        rt = RequestTracer(sample_rate=0.0, clock=clk)
        plain = rt.start()
        assert rt.finish(plain, "finished") is False   # unsampled, normal
        late = rt.start()
        assert rt.finish(late, "deadline_exceeded") is True
        pre = rt.start()
        rt.preempted(pre, clk.t, replica=0)
        assert rt.finish(pre, "finished") is True
        res = rt.start()
        rt.resubmitted(res, clk.t, replica=1)
        assert res.attempt == 2
        assert rt.finish(res, "finished") is True
        recs = rt.snapshot()
        assert {tuple(r["outlier"]) for r in recs} == {
            ("deadline_exceeded",), ("preempted",), ("resubmitted",)}
        assert rt.dropped == 1 and rt.retained == 3

    def test_ttft_slo_outlier(self):
        rt = RequestTracer(sample_rate=0.0, ttft_slo_ms=10.0,
                           clock=FakeClock())
        fast = rt.start()
        assert rt.finish(fast, "finished", ttft_s=0.005) is False
        slow = rt.start()
        assert rt.finish(slow, "finished", ttft_s=0.5) is True
        assert rt.snapshot()[0]["outlier"] == ["ttft_slo"]

    def test_event_cap_never_drops_terminal(self):
        rt = RequestTracer(sample_rate=1.0, max_events=8, clock=FakeClock())
        tr = rt.start()
        for i in range(20):
            rt.event(tr, "decode", iter=i)
        rt.finish(tr, "finished")
        rec = rt.snapshot()[0]
        assert rec["dropped_events"] == 13   # 20 - (8 - 1 submitted)
        assert rec["events"][-1]["kind"] == "finished"

    def test_finish_idempotent_first_state_wins(self):
        rt = RequestTracer(sample_rate=1.0, clock=FakeClock())
        tr = rt.start()
        assert rt.finish(tr, "shed") is True
        assert rt.finish(tr, "cancelled") is False
        assert rt.snapshot()[0]["state"] == "shed"

    def test_chrome_export_loads(self, tmp_path):
        clk = FakeClock()
        rt = RequestTracer(sample_rate=1.0, clock=clk)
        tr = rt.start()
        rt.interval(tr, "prefill", 0.0, 0.5, replica="2")
        rt.finish(tr, "finished")
        path = str(tmp_path / "chrome.json")
        rt.export_chrome_trace(path)
        d = json.load(open(path))
        names = {e["name"] for e in d["traceEvents"]}
        assert {"thread_name", "prefill", "submitted", "finished"} <= names
        x = [e for e in d["traceEvents"] if e["name"] == "prefill"][0]
        assert x["ph"] == "X" and x["dur"] == pytest.approx(0.5e6)
        assert x["pid"] == 2    # replica of first service


# ---------------------------------------------------------------------------
# single-engine trace assembly
# ---------------------------------------------------------------------------


class TestSingleEngineTraces:
    def test_lifecycle_causal_chain(self, tiny_engine, traced_session):
        srv = serving(tiny_engine)
        hs = [srv.submit(p, max_new_tokens=5) for p in mk_prompts(3)]
        srv.run()
        [h.result() for h in hs]
        rt = traced_session.reqtrace
        recs = rt.snapshot()
        assert len(recs) == 3 and rt.started == 3
        for r in recs:
            kinds = [e["kind"] for e in r["events"]]
            assert kinds[0] == "submitted"
            assert "admitted" in kinds and "prefill_chunk" in kinds
            assert kinds[-1] == "finished"
            assert r["phases"]["prefill"] > 0
            assert r["phases"]["decode"] > 0
            assert r["tokens"] == 5 and r["ttft_ms"] is not None
            assert r["replicas"] == ["0"]
        # the retained records stream to the session's reqtrace JSONL
        jsonl = os.path.join(traced_session.output_dir, "reqtrace.jsonl")
        lines = [json.loads(x) for x in open(jsonl)]
        assert {x["trace_id"] for x in lines} == \
            {r["trace_id"] for r in recs}
        srv.close()

    def test_fork_lineage(self, tiny_engine, traced_session):
        srv = serving(tiny_engine)
        hs = srv.submit(np.arange(1, 24), max_new_tokens=4, n=3)
        srv.run()
        [h.result() for h in hs]
        recs = traced_session.reqtrace.snapshot()
        parents = [r for r in recs if r.get("forks")]
        children = [r for r in recs if r.get("fork_of")]
        assert len(parents) == 1 and len(children) == 2
        assert set(parents[0]["forks"]) == \
            {c["trace_id"] for c in children}
        assert all(c["fork_of"] == parents[0]["trace_id"]
                   for c in children)
        srv.close()

    def test_deadline_outlier_and_flight_ring(self, tiny_engine, tmp_path):
        """Deadline expiry: the trace retains as an outlier even at sample
        rate 0, and the flight ring carries a req_terminal event even
        WITHOUT tracing (the satellite-2 contract)."""
        # arm 1: tracing OFF, flight recorder ON — ring still names victims
        reset_session()
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "o1"),
            flight_recorder=True, flight_sigusr1=False))
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk)
        h = srv.submit(np.arange(1, 20), max_new_tokens=50, deadline_s=5.0)
        srv.step()
        clk.advance(10.0)
        srv.step()
        assert h.state == "deadline_exceeded"
        ring = sess.recorder.snapshot()
        term = [e for e in ring if e.get("kind") == "req_terminal"]
        assert term and term[0]["event"] == "deadline_exceeded"
        assert term[0]["trace_id"] is None       # tracing was off
        srv.close()
        # arm 2: tracing ON at sample rate 0 — outlier retained anyway
        reset_session()
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "o2"),
            request_tracing=True, trace_sample_rate=0.0,
            flight_recorder=False))
        clk = FakeClock()
        srv = serving(tiny_engine, clock=clk)
        h = srv.submit(np.arange(1, 20), max_new_tokens=50, deadline_s=5.0)
        srv.step()
        clk.advance(10.0)
        srv.step()
        assert h.state == "deadline_exceeded"
        recs = sess.reqtrace.snapshot()
        assert len(recs) == 1
        assert recs[0]["state"] == "deadline_exceeded"
        assert "deadline_exceeded" in recs[0]["outlier"]
        srv.close()
        reset_session()

    def test_preemption_outlier_retained(self, tiny_engine, tmp_path):
        reset_session()
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "obs"),
            request_tracing=True, trace_sample_rate=0.0,
            flight_recorder=False))
        # a pool far too small for the load — evictions guaranteed (two
        # concurrent ~100-token sequences need ~14 of the 8 blocks)
        srv = serving(tiny_engine, num_blocks=8, max_seqs=2,
                      prefix_cache=False)
        hs = [srv.submit(p, max_new_tokens=40)
              for p in mk_prompts(4, lo=40, hi=60, seed=3)]
        srv.run()
        [h.result() for h in hs]
        assert srv.sched.preemption_count > 0
        recs = sess.reqtrace.snapshot()
        preempted = [r for r in recs if "preempted" in r.get("outlier", [])]
        assert preempted
        r = preempted[0]
        kinds = [e["kind"] for e in r["events"]]
        assert "preempted" in kinds
        # recompute re-admits: at least two admitted events on the chain
        assert kinds.count("admitted") >= 2
        assert r["preemptions"] >= 1
        srv.close()
        reset_session()


# ---------------------------------------------------------------------------
# zero-overhead disabled path (watchdog-asserted)
# ---------------------------------------------------------------------------


class TestDisabledPathZeroOverhead:
    def test_tracing_adds_zero_dispatch_zero_compile(self, tiny_engine,
                                                     tmp_path):
        """The acceptance bar: the SAME engine, the SAME workload, run
        first with tracing/goodput disabled and then enabled — identical
        iteration and prefill-dispatch counts, identical streams, and the
        recompile watchdog counts ZERO new compiles (tracing never touches
        a program)."""
        def run_load(srv):
            it0 = srv._iterations
            pc0 = srv.prefill_chunks_run
            hs = [srv.submit(p, max_new_tokens=6, seed=i)
                  for i, p in enumerate(mk_prompts(5, seed=9))]
            srv.run()
            outs = [np.asarray(h.result()) for h in hs]
            return srv._iterations - it0, srv.prefill_chunks_run - pc0, outs

        reset_session()
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "off"),
            flight_recorder=False))
        # prefix_cache off: the second pass over identical prompts would
        # otherwise hit the now-warm cache and take the COW path — a
        # workload difference, not a tracing one
        srv = serving(tiny_engine, prefix_cache=False)
        iters_off, chunks_off, outs_off = run_load(srv)
        assert srv._serve_acct is None       # gate off → wired nothing
        compiles = get_registry().counter("xla/compiles")
        before = sum(compiles.series().values())
        # flip tracing + goodput ON for the same engine, same workload
        reset_session()
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "on"),
            request_tracing=True, serve_goodput=True,
            flight_recorder=False))
        iters_on, chunks_on, outs_on = run_load(srv)
        after = sum(compiles.series().values())
        assert after - before == 0           # zero recompiles
        assert (iters_on, chunks_on) == (iters_off, chunks_off)
        for a, b in zip(outs_on, outs_off):
            np.testing.assert_array_equal(a, b)
        assert len(sess.reqtrace.snapshot()) == 5   # tracing DID run
        assert srv._serve_acct is not None
        srv.close()
        reset_session()


# ---------------------------------------------------------------------------
# the chaos-gate scenario: fleet kill + disagg handoffs, traced
# ---------------------------------------------------------------------------


def run_staggered(router, prompts, n_new=8, temperature=0.7, stagger=2):
    handles = []
    i, it = 0, 0
    while i < len(prompts) or router.in_flight():
        if i < len(prompts) and it % stagger == 0:
            handles.append(router.submit(prompts[i], max_new_tokens=n_new,
                                         seed=i, temperature=temperature))
            i += 1
        router.step()
        it += 1
        assert it < 10_000, "fleet made no progress"
    return handles


class TestFleetChaosTraces:
    def test_sixteen_request_chaos_acceptance(self, tiny_engine,
                                              traced_session, tmp_path):
        """ISSUE-15 acceptance: 16 requests through a 3-replica
        disaggregated fleet with a mid-stream decode-replica kill. Every
        request has a complete causal chain; the killed replica's
        requests resubmit under the SAME trace_id; handoff spans stitch
        across two replicas; the Chrome trace loads; serving goodput
        buckets sum to wall per replica."""
        prompts = mk_prompts(16, seed=3, lo=4, hi=60)
        replicas = build_replicas(
            tiny_engine, ServingConfig(**SCFG), 3,
            roles=[ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE])
        router = FleetRouter(
            replicas, FleetConfig(policy="kv_occupancy", auto_revive=True,
                                  revive_after_iterations=8),
            fault_plan=[{"kind": "replica_kill", "step": 12, "replica": 1}])
        try:
            hs = run_staggered(router, prompts)
            assert all(h.state == "finished" for h in hs)
            assert replicas[1].deaths == 1
            assert sum(h.resubmits for h in hs) >= 1
            rt = traced_session.reqtrace
            recs = rt.snapshot()
            assert len(recs) == 16
            assert len({r["trace_id"] for r in recs}) == 16
            # every request: a complete causal chain
            for r in recs:
                kinds = [e["kind"] for e in r["events"]]
                assert kinds[0] == "submitted"
                assert "routed" in kinds and "admitted" in kinds
                assert "prefill_chunk" in kinds
                assert kinds[-1] == "finished"
            # the killed replica's requests: SAME trace_id, attempt + 1
            resub = [r for r in recs if r["resubmits"]]
            assert resub
            for r in resub:
                assert r["attempt"] == 1 + r["resubmits"]
                assert any(e["kind"] == "resubmitted"
                           for e in r["events"])
            # handoff spans stitch across replicas: export on the prefill
            # replica, import on the decode replica
            handed = [r for r in recs if r["handoffs"]]
            assert handed
            for r in handed[:4]:
                ev = {e["kind"]: e for e in r["events"]}
                assert "handoff_export" in ev and "handoff_import" in ev
                assert ev["handoff_export"]["replica"] == "0"
                assert ev["handoff_import"]["replica"] != "0"
                assert r["phases"]["handoff"] > 0
                assert len(set(r["replicas"])) >= 2
            # Chrome trace loads with per-trace rows
            path = str(tmp_path / "chaos_chrome.json")
            rt.export_chrome_trace(path)
            d = json.load(open(path))
            assert len(d["traceEvents"]) > 16
            assert {e["name"] for e in d["traceEvents"]} >= {
                "thread_name", "submitted", "prefill_chunk", "finished"}
            # serving goodput: buckets sum to wall per replica
            seen = 0
            for r in router.replicas:
                acct = r.engine._serve_acct
                if acct is None:
                    continue
                tot = acct.totals()
                assert sum(tot["buckets"].values()) == pytest.approx(
                    tot["wall_s"], abs=1e-6)
                seen += 1
            assert seen >= 3
        finally:
            router.close()

    def test_shed_trace_retained(self, tiny_engine, traced_session):
        """An admission-shed request leaves a retained 'shed' trace (tail
        retention) and a flight-style terminal state."""
        from deepspeed_tpu.serving.fleet import Overloaded

        replicas = build_replicas(tiny_engine, ServingConfig(**SCFG), 2)
        router = FleetRouter(replicas, FleetConfig(policy="kv_occupancy"))
        try:
            hs = [router.submit(p, max_new_tokens=8)
                  for p in mk_prompts(4, seed=5)]
            router.run()
            [h.result() for h in hs]
            assert router._tpot_estimate() is not None
            with pytest.raises(Overloaded):
                router.submit(np.arange(1, 30), max_new_tokens=64,
                              deadline_s=1e-9)
            shed = [r for r in traced_session.reqtrace.snapshot()
                    if r["state"] == "shed"]
            assert len(shed) == 1
            assert shed[0]["outlier"] == ["shed"]
            assert shed[0]["events"][-1]["reason"] == "deadline_infeasible"
        finally:
            router.close()


# ---------------------------------------------------------------------------
# report CLI + crash dump + manifest + metricsdoc
# ---------------------------------------------------------------------------


class TestReporting:
    def test_report_sections_render(self, tiny_engine, traced_session,
                                    tmp_path):
        from deepspeed_tpu.observability.report import report

        srv = serving(tiny_engine)
        hs = [srv.submit(p, max_new_tokens=4) for p in mk_prompts(2)]
        srv.run()
        [h.result() for h in hs]
        srv.close()
        traced_session.dump_metrics()
        out = report([
            os.path.join(traced_session.output_dir, "reqtrace.jsonl"),
            traced_session.metrics_path()])
        assert "== request traces ==" in out
        assert "== serving goodput ==" in out
        assert "req-" in out
        # bucket columns render
        for col in ("prefill", "decode", "scheduling_host", "idle"):
            assert col in out

    def test_crash_dump_inflight_trace_tail(self, tiny_engine, tmp_path):
        from deepspeed_tpu.observability.report import crash_report

        reset_session()
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "obs"),
            request_tracing=True, flight_recorder=True,
            flight_sigusr1=False))
        srv = serving(tiny_engine)
        srv.submit(np.arange(1, 40), max_new_tokens=32)
        for _ in range(3):
            srv.step()      # mid-flight: prefill done, decoding
        bundle = sess.crash_dump("test-serving-hang")
        assert bundle
        man = json.load(open(os.path.join(bundle, "MANIFEST.json")))
        traces = man["request_traces"]
        assert len(traces) == 1
        assert traces[0]["trace_id"].startswith("req-")
        assert traces[0]["last_event"] is not None
        out = crash_report(bundle)
        assert "== in-flight requests ==" in out
        assert traces[0]["trace_id"] in out
        srv.close()
        reset_session()

    def test_rollout_manifest_trace_ids(self, tiny_engine, traced_session):
        from deepspeed_tpu.rlhf.rollout import (RolloutCollector,
                                                RolloutManifest)

        srv = serving(tiny_engine)
        coll = RolloutCollector(srv, group_n=2, temperature=0.7,
                                max_new_tokens=4)
        prompts = mk_prompts(2, seed=11)
        _, manifest = coll.collect(prompts, iteration=0)
        assert len(manifest.trace_ids) == 2
        assert all(len(row) == 2 for row in manifest.trace_ids)
        ids = {t for row in manifest.trace_ids for t in row}
        retained = {r["trace_id"]
                    for r in traced_session.reqtrace.snapshot()}
        assert ids <= retained                # cross-referencable
        # JSON round-trip keeps the column; old manifests (no column)
        # still load
        m2 = RolloutManifest.from_json(manifest.to_json())
        assert m2.trace_ids == manifest.trace_ids
        legacy = json.loads(manifest.to_json())
        legacy.pop("trace_ids")
        m3 = RolloutManifest(**legacy)
        assert m3.trace_ids == []
        srv.close()

    def test_metricsdoc_gate_clean_and_detects(self, tmp_path):
        from tools.tpulint.metricsdoc import (DEFAULT_DOC, DEFAULT_PATHS,
                                              find_undocumented, main)

        # the repo gate: every literal metric name is documented
        paths = [p for p in DEFAULT_PATHS if os.path.exists(p)]
        assert find_undocumented(paths, DEFAULT_DOC) == []
        assert main([]) == 0
        # the negative: an undocumented metric is flagged
        bad = tmp_path / "bad.py"
        bad.write_text("reg.counter('nope/unknown_metric').inc()\n")
        missing = find_undocumented([str(bad)], DEFAULT_DOC)
        assert [m[0] for m in missing] == ["nope/unknown_metric"]
        # doc-pattern semantics: brace alternation + wildcard + labels
        doc = tmp_path / "doc.md"
        doc.write_text(
            "| x | — | `a/{b,c}_d`, `e/<stat>{agg=min,max}`, `f/g/*` |\n")
        ok = tmp_path / "ok.py"
        ok.write_text("reg.gauge('a/b_d')\nreg.gauge('a/c_d')\n"
                      "reg.gauge('e/anything')\nreg.gauge('f/g/deep/x')\n")
        assert find_undocumented([str(ok)], str(doc)) == []

    def test_config_validation(self):
        with pytest.raises(ConfigError):
            ObservabilityConfig(trace_sample_rate=1.5).validate()
        with pytest.raises(ConfigError):
            ObservabilityConfig(trace_max_events=2).validate()
        with pytest.raises(ConfigError):
            ObservabilityConfig(serve_slo_budget=0.0).validate()
        ObservabilityConfig(request_tracing=True, serve_goodput=True,
                            trace_sample_rate=0.25).validate()
