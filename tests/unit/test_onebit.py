"""1-bit / compressed-gradient tests — analog of reference
tests/unit/runtime/half_precision/onebit/test_onebit.py (warmup equivalence +
compressed-stage convergence) plus primitive-level checks of the
error-feedback collective."""

import jax
from deepspeed_tpu.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.compressed import (compressed_allreduce_flat,
                                           tree_flatten_pad,
                                           tree_unflatten_like)
from deepspeed_tpu.models import create_model
from deepspeed_tpu.parallel import mesh as mesh_mod

pytestmark = pytest.mark.slow  # heavy virtual-mesh trajectory tests



class TestCompressedAllreduce:
    def _run(self, per_rank, worker=None, server=None):
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs), ("data",))
        W, n = per_rank.shape
        worker = worker if worker is not None else jnp.zeros((W, n))
        server = server if server is not None else jnp.zeros((n,))

        def body(v, w, s):
            out, w2, s2 = compressed_allreduce_flat(v[0], w[0], s, "data")
            return out[None], w2[None], s2

        fn = shard_map(body, mesh=mesh,
                           in_specs=(P("data"), P("data"), P("data")),
                           out_specs=(P("data", None), P("data"), P("data")),
                           check_vma=False)
        out, w2, s2 = fn(per_rank, worker, server)
        return np.asarray(out), np.asarray(w2.reshape(W, n)), np.asarray(s2)

    def test_approximates_mean(self):
        rng = np.random.RandomState(0)
        per_rank = jnp.asarray(rng.randn(8, 64).astype(np.float32))
        out, _, _ = self._run(per_rank)
        want = np.asarray(per_rank).mean(0)
        # every rank sees the same result
        assert np.allclose(out, out[0:1], atol=0)
        # int8 two-phase quantization error is bounded by ~2 * max|v|/127
        err = np.abs(out[0] - want).max()
        assert err < 2.5 * np.abs(per_rank).max() / 127, err

    def test_error_feedback_accumulates(self):
        # constant input: residual feedback must drive the LONG-Run average
        # toward the true mean (the whole point of error feedback)
        per_rank = jnp.asarray(
            np.random.RandomState(1).randn(8, 64).astype(np.float32))
        want = np.asarray(per_rank).mean(0)
        worker = jnp.zeros((8, 64))
        server = jnp.zeros((8,))
        outs = []
        for _ in range(30):
            out, w, s = self._run(per_rank, worker, server)
            worker, server = jnp.asarray(w), jnp.asarray(s.reshape(-1))
            outs.append(out[0])
        avg = np.stack(outs).mean(0)
        direct_err = np.abs(outs[0] - want).max()
        fb_err = np.abs(avg - want).max()
        assert fb_err < direct_err * 0.5, (fb_err, direct_err)

    def test_flatten_roundtrip(self):
        tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                "b": {"c": jnp.ones((5,), jnp.bfloat16)}}
        flat, _, n = tree_flatten_pad(tree, 8)
        assert flat.shape[0] % 8 == 0 and n == 11
        back = tree_unflatten_like(flat, tree)
        for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32))


def _train(opt_type, steps, freeze_step=2, seed=0):
    mesh_mod.reset_mesh()
    model = create_model("tiny", dtype=jnp.float32, max_seq_len=64)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": opt_type,
                      "params": {"lr": 1e-3, "freeze_step": freeze_step}},
        "zero_optimization": {"stage": 0},
        "parallel": {"data_parallel_size": 8},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = jax.random.randint(jax.random.PRNGKey(seed), (1, 16, 32), 0, 250)
    return [float(engine.train_batch(batch={"input_ids": ids}))
            for _ in range(steps)]


class TestOnebitTraining:
    def test_warmup_matches_dense_exactly(self):
        dense = _train("adam", 3)
        onebit = _train("onebitadam", 3, freeze_step=100)  # all warmup
        np.testing.assert_allclose(dense, onebit, rtol=1e-6)

    def test_compressed_stage_converges(self):
        dense = _train("adam", 12)
        onebit = _train("onebitadam", 12, freeze_step=2)
        # loss still goes down and tracks dense within a few percent
        assert onebit[-1] < onebit[0]
        assert abs(onebit[-1] - dense[-1]) / dense[-1] < 0.05, (onebit, dense)

    def test_zero2_rejected(self):
        model = create_model("tiny", dtype=jnp.float32)
        with pytest.raises(ValueError, match="ZeRO stage <= 1"):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "onebitadam",
                                      "params": {"lr": 1e-3}},
                        "zero_optimization": {"stage": 2}})

    def test_tp_rejected(self):
        model = create_model("tiny", dtype=jnp.float32)
        with pytest.raises(ValueError, match="data-parallel only"):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "onebitadam",
                                      "params": {"lr": 1e-3}},
                        "parallel": {"tensor_parallel_size": 2}})

    def test_cpuadam_without_offload_rejected(self):
        model = create_model("tiny", dtype=jnp.float32)
        with pytest.raises(ValueError, match="cpuadam"):
            deepspeed_tpu.initialize(
                model=model,
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "cpuadam",
                                      "params": {"lr": 1e-3}}})


class TestZeroOneAdam:
    """0/1 Adam (reference fp16/onebit/zoadam.py): exponential
    variance-update schedule + dense-on-variance-steps comm. The local-step
    policy is a documented deviation (SPMD keeps params replicated)."""

    def test_var_step_schedule_matches_reference_loop(self):
        from deepspeed_tpu.runtime.optimizer import zero_one_var_step

        for scaler in (3, 16):
            # reference zoadam.py:270 counter/interval state machine
            interval, counter = 1, 0
            hits = set()
            for s in range(1, 2001):
                if s % interval == 0:
                    hits.add(s)
                    counter += 1
                    if counter == scaler:
                        counter = 0
                        interval *= 2
            fn = jax.jit(jax.vmap(
                lambda c, _s=scaler: zero_one_var_step(c, _s, 10**6)))
            mask = np.asarray(fn(jnp.arange(2000)))
            got = {int(i) + 1 for i in np.nonzero(mask)[0]}
            assert got == hits, (scaler, sorted(got ^ hits)[:10])
        # frozen after var_freeze_step
        assert not bool(zero_one_var_step(jnp.int32(50), 16, 50))

    def test_variance_frozen_between_hits(self):
        from deepspeed_tpu.runtime.optimizer import zero_one_adam_transform

        tx = zero_one_adam_transform(b1=0.9, b2=0.999, eps=1e-8,
                                     weight_decay=0.0, var_freeze_step=10**6,
                                     var_update_scaler=2)
        p = {"w": jnp.ones((4,))}
        state = tx.init(p)
        g = {"w": jnp.full((4,), 0.5)}
        nus = []
        for _ in range(8):
            _, state = tx.update(g, state, p)
            nus.append(float(state["nu"]["w"][0]))
        # hits at steps 1,2 (interval 1), 4,6 (interval 2), 8 (interval 4):
        # nu changes exactly there and holds in between
        assert nus[0] != 0 and nus[1] != nus[0]
        assert nus[2] == nus[1]            # step 3: frozen
        assert nus[3] != nus[2]            # step 4: hit
        assert nus[4] == nus[3]
        assert nus[5] != nus[4]            # step 6: hit
        assert nus[6] == nus[5]
        assert nus[7] != nus[6]            # step 8: hit

    def test_zerooneadam_trains(self, devices8):
        import deepspeed_tpu
        from deepspeed_tpu.models import create_model

        model = create_model("tiny", dtype=jnp.float32)
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000,
            "optimizer": {"type": "zerooneadam",
                          "params": {"lr": 5e-3, "freeze_step": 2,
                                     "var_update_scaler": 2}}})
        ids = np.random.RandomState(0).randint(0, 256, (1, 16, 16))
        losses = [float(engine.train_batch(batch={"input_ids": ids}))
                  for _ in range(8)]
        assert losses[-1] < losses[0]
        assert all(np.isfinite(losses))
