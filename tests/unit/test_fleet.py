"""Serving-fleet tests — the data-plane router over N engine replicas.

Coverage map (the ISSUE-11 checklist):
  * FleetConfig validation + ReplicaHealth snapshot semantics;
  * routing policies: round-robin cycling, least-queue, KV-occupancy,
    session affinity (warm-replica follow + overload/death fallbacks), and
    the headline: affinity's prefix-cache hit rate strictly beats
    round-robin on a shared-system-prompt workload;
  * resilience: deterministic ``replica_kill`` fault mid-stream → drain +
    resubmission bit-identical to an uninterrupted single engine,
    resubmission-budget exhaustion, fleet-unavailable;
  * prefill/decode disaggregation: jitted kv_export/kv_import roundtrip,
    handoff of a request whose last block is COW-shared with the prefix
    cache, cancel racing a handoff, decode-pool preemption AFTER adoption
    (recompute on the destination), full-pool fallback to decoding in
    place;
  * the acceptance smoke: ≥12 staggered mixed-length requests through a
    3-replica fleet AND a disaggregated 1-prefill+1-decode pair, outputs
    bit-identical to one ``ServingEngine`` — including with a replica kill
    injected mid-stream — at temperature (the sampling stream depends only
    on (engine seed, request seed, token index), never on which replica
    runs it);
  * the ``== fleet serving ==`` report section (device-free).
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import FleetConfig, ServingConfig
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.serving import RequestCancelled, ServingEngine
from deepspeed_tpu.serving.fleet import (ROLE_DECODE, ROLE_MIXED,
                                         ROLE_PREFILL, ArenaHandoff,
                                         FleetRouter, FleetUnavailable,
                                         Replica, build_replicas)
from deepspeed_tpu.serving.fleet.disagg import HandoffGeometryError

SCFG = dict(block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16, max_queue=64)


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


def mk_fleet(engine, n=3, roles=None, policy="kv_occupancy", fault_plan=None,
             fleet_cfg=None, clock=None, **cfg):
    kwargs = dict(SCFG)
    kwargs.update(cfg)
    replicas = build_replicas(engine, ServingConfig(**kwargs), n,
                              roles=roles, clock=clock)
    fc = fleet_cfg or FleetConfig(policy=policy)
    rkw = {"clock": clock} if clock is not None else {}
    return (FleetRouter(replicas, fc, fault_plan=fault_plan, **rkw),
            replicas)


class FakeClock:
    """Injectable router/engine clock (sleep-free lifecycle tests)."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def mk_prompts(n, lo=4, hi=40, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 50, size=rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def oracle_outputs(engine, prompts, n_new=12, temperature=0.0, **cfg):
    kwargs = dict(SCFG)
    kwargs.update(cfg)
    solo = ServingEngine(engine, ServingConfig(**kwargs))
    outs = []
    try:
        for i, p in enumerate(prompts):
            outs.append(solo.submit(p, max_new_tokens=n_new, seed=i,
                                    temperature=temperature).result())
    finally:
        solo.close()
    return outs


def run_staggered(router, prompts, n_new=12, stagger=2, temperature=0.0):
    """Submit one request every ``stagger`` router iterations while the
    fleet keeps stepping — deterministic mid-stream arrivals."""
    handles = []
    i, it = 0, 0
    while i < len(prompts) or router.in_flight():
        if i < len(prompts) and it % stagger == 0:
            handles.append(router.submit(prompts[i], max_new_tokens=n_new,
                                         seed=i, temperature=temperature))
            i += 1
        router.step()
        it += 1
        assert it < 10_000, "fleet made no progress"
    return handles


# ---------------------------------------------------------------------------
# config + health (device-free where possible)
# ---------------------------------------------------------------------------


class TestFleetConfig:
    def test_bad_policy_rejected(self):
        with pytest.raises(ConfigError, match="policy"):
            FleetConfig(policy="random").validate()

    def test_bounds(self):
        with pytest.raises(ConfigError):
            FleetConfig(affinity_overload=0.0).validate()
        with pytest.raises(ConfigError):
            FleetConfig(affinity_overload=1.5).validate()
        with pytest.raises(ConfigError):
            FleetConfig(max_resubmits=-1).validate()
        FleetConfig().validate()   # defaults valid

    def test_replica_role_rejected(self):
        with pytest.raises(ValueError, match="role"):
            Replica(engine=None, index=0, role="verifier")


class TestReplicaHealth:
    def test_load_key_orders_by_occupancy_then_queue(self):
        from deepspeed_tpu.serving.fleet import ReplicaHealth

        low = ReplicaHealth(index=1, role=ROLE_MIXED, alive=True,
                            arena_occupancy=0.1, in_flight=9)
        high = ReplicaHealth(index=0, role=ROLE_MIXED, alive=True,
                             arena_occupancy=0.9, in_flight=0)
        assert low.load_key < high.load_key
        tie_a = ReplicaHealth(index=0, role=ROLE_MIXED, alive=True,
                              arena_occupancy=0.5, in_flight=2)
        tie_b = ReplicaHealth(index=1, role=ROLE_MIXED, alive=True,
                              arena_occupancy=0.5, in_flight=1)
        assert tie_b.load_key < tie_a.load_key

    def test_snapshot_tracks_engine(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=1)
        try:
            r = replicas[0]
            h0 = r.health()
            assert h0.alive and h0.in_flight == 0 and h0.kv_blocks_in_use == 0
            router.submit(np.arange(1, 20, dtype=np.int32),
                          max_new_tokens=4)
            router.step()
            h1 = r.health()
            assert h1.in_flight == 1 and h1.kv_blocks_in_use > 0
            assert 0.0 < h1.arena_occupancy <= 1.0
            r.kill("test")
            assert not r.health().alive
        finally:
            router.close()


# ---------------------------------------------------------------------------
# routing policies
# ---------------------------------------------------------------------------


class TestRoutingPolicies:
    def test_round_robin_cycles(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=3, policy="round_robin")
        try:
            prompts = mk_prompts(6, lo=18, hi=20)
            hs = [router.submit(p, max_new_tokens=2) for p in prompts]
            picked = [h._fr.replica.index for h in hs]
            assert picked == [0, 1, 2, 0, 1, 2]
            for h in hs:
                h.result()
        finally:
            router.close()

    def test_least_queue_picks_emptiest(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=3, policy="least_queue")
        try:
            p = np.arange(1, 20, dtype=np.int32)
            h0 = router.submit(p, max_new_tokens=4)
            assert h0._fr.replica.index == 0           # all empty → index tie
            h1 = router.submit(p, max_new_tokens=4)
            assert h1._fr.replica.index == 1           # 0 now has one in flight
            h2 = router.submit(p, max_new_tokens=4)
            assert h2._fr.replica.index == 2
            for h in (h0, h1, h2):
                h.result()
        finally:
            router.close()

    def test_kv_occupancy_avoids_full_replica(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=2, policy="kv_occupancy")
        try:
            long_p = np.arange(1, 65, dtype=np.int32)   # 4 blocks resident
            h0 = router.submit(long_p, max_new_tokens=2)
            router.step()                               # blocks land on 0
            h1 = router.submit(np.arange(1, 20, dtype=np.int32),
                               max_new_tokens=2)
            assert h1._fr.replica.index == 1            # 0 is occupied
            for h in (h0, h1):
                h.result()
        finally:
            router.close()

    def test_affinity_follows_warm_replica(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=3, policy="affinity")
        try:
            sys_prompt = np.arange(1, 40, dtype=np.int32)   # > one block
            h0 = router.submit(sys_prompt, max_new_tokens=2)
            first = h0._fr.replica.index
            h0.result()
            # same first block → same replica, counted as a warm decision
            h1 = router.submit(
                np.concatenate([sys_prompt[:16],
                                np.arange(50, 70, dtype=np.int32)]),
                max_new_tokens=2)
            assert h1._fr.replica.index == first
            h1.result()
            assert router._decisions[("affinity", "affinity_warm")] == 1
            assert router._decisions[("affinity", "affinity_cold")] == 1
            # short prompts can't key a block → load-based fallback reason
            router.submit(np.arange(1, 5, dtype=np.int32),
                          max_new_tokens=2).result()
            assert router._decisions[("affinity", "affinity_short")] == 1
        finally:
            router.close()

    def test_affinity_overload_spills(self, tiny_engine):
        router, replicas = mk_fleet(
            tiny_engine, n=2,
            fleet_cfg=FleetConfig(policy="affinity",
                                  affinity_overload=0.01))
        try:
            sys_prompt = np.arange(1, 40, dtype=np.int32)
            h0 = router.submit(sys_prompt, max_new_tokens=4)
            first = h0._fr.replica.index
            router.step()                      # warm replica now > 1% full
            h1 = router.submit(sys_prompt, max_new_tokens=4)
            assert h1._fr.replica.index != first
            assert router._decisions[("affinity", "affinity_overload")] == 1
            for h in (h0, h1):
                h.result()
        finally:
            router.close()

    def test_affinity_prefix_hits_beat_round_robin(self, tiny_engine):
        """The cross-replica admission hint pays: on a shared-system-prompt
        workload, affinity routing lands every request on the replica whose
        prefix cache is warm, so its fleet-wide prefix-hit tokens strictly
        exceed round-robin's over the SAME workload."""
        sys_prompt = np.arange(1, 49, dtype=np.int32)      # 3 full blocks
        rng = np.random.RandomState(7)
        prompts = [np.concatenate([sys_prompt,
                                   rng.randint(50, 90, size=6 + i)
                                   .astype(np.int32)])
                   for i in range(6)]
        hits = {}
        for policy in ("round_robin", "affinity"):
            router, replicas = mk_fleet(tiny_engine, n=2, policy=policy)
            try:
                for i, p in enumerate(prompts):
                    router.submit(p, max_new_tokens=4, seed=i).result()
                hits[policy] = sum(r.engine.sched.prefix_hit_tokens
                                   for r in replicas)
            finally:
                router.close()
        assert hits["affinity"] > hits["round_robin"]

    def test_fleet_unavailable_when_all_dead(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=2)
        try:
            router.kill_replica(0)
            router.kill_replica(1)
            with pytest.raises(FleetUnavailable):
                router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=2)
        finally:
            router.close()

    def test_mismatched_geometry_rejected(self, tiny_engine):
        a = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        b_cfg = dict(SCFG)
        b_cfg["block_size"] = 8
        b = ServingEngine(tiny_engine, ServingConfig(**b_cfg))
        try:
            with pytest.raises(ValueError, match="geometry"):
                FleetRouter([Replica(a, 0), Replica(b, 1)], FleetConfig())
        finally:
            a.close()
            b.close()

    def test_disagg_needs_both_pools(self, tiny_engine):
        srv = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        try:
            with pytest.raises(ValueError, match="prefill"):
                FleetRouter([Replica(srv, 0, role=ROLE_DECODE)],
                            FleetConfig())
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# resilience: replica death → drain → bit-exact resubmission
# ---------------------------------------------------------------------------


class TestReplicaDeath:
    def test_mid_stream_kill_resubmits_bit_exact(self, tiny_engine):
        prompts = mk_prompts(6, seed=1)
        want = oracle_outputs(tiny_engine, prompts, n_new=12)
        router, replicas = mk_fleet(
            tiny_engine, n=2, policy="round_robin",
            fault_plan=[{"kind": "replica_kill", "step": 5, "replica": 1}])
        try:
            hs = [router.submit(p, max_new_tokens=12, seed=i)
                  for i, p in enumerate(prompts)]
            outs = [h.result() for h in hs]
            # the replica really died mid-run (auto-revive may have since
            # rebuilt it — deaths are the durable evidence)
            assert replicas[1].deaths == 1
            assert replicas[1].death_reason == "fault"
            assert sum(h.resubmits for h in hs) > 0
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
            # the drained replica's requests now live on the survivor;
            # no fleet request was lost or duplicated
            assert all(h.state == "finished" for h in hs)
        finally:
            router.close()

    @pytest.mark.slow   # tier-1 keeps the fault-plan kill variant above
    def test_step_exception_marks_dead_and_resubmits(self, tiny_engine):
        prompts = mk_prompts(4, seed=2)
        want = oracle_outputs(tiny_engine, prompts, n_new=8)
        router, replicas = mk_fleet(tiny_engine, n=2, policy="round_robin")
        try:
            hs = [router.submit(p, max_new_tokens=8, seed=i)
                  for i, p in enumerate(prompts)]
            orig_step = replicas[0].engine.step
            calls = {"n": 0}

            def exploding_step():
                calls["n"] += 1
                if calls["n"] == 3:
                    raise RuntimeError("synthetic device loss")
                return orig_step()

            replicas[0].engine.step = exploding_step
            outs = [h.result() for h in hs]
            assert replicas[0].deaths == 1
            assert replicas[0].death_reason == "step-exception"
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
        finally:
            router.close()

    def test_resubmit_budget_exhaustion_cancels(self, tiny_engine):
        router, replicas = mk_fleet(
            tiny_engine, n=2, policy="round_robin",
            fleet_cfg=FleetConfig(policy="round_robin", max_resubmits=0),
            fault_plan=[{"kind": "replica_kill", "step": 3, "replica": 0}])
        try:
            h = router.submit(mk_prompts(1, seed=3)[0], max_new_tokens=16)
            assert h._fr.replica.index == 0
            with pytest.raises(RequestCancelled):
                h.result()
            assert h.state == "cancelled"
        finally:
            router.close()


# ---------------------------------------------------------------------------
# disaggregation: KV handoff
# ---------------------------------------------------------------------------


class TestKVHandoffPrograms:
    def test_export_import_roundtrip(self, tiny_engine):
        """The jitted gather/scatter pair moves exactly the named blocks —
        every layer, both k and v — and touches nothing else."""
        from deepspeed_tpu.serving import paged_kv

        src = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        dst = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        try:
            rng = np.random.RandomState(0)
            shape = src._arena["k"].shape        # (L, 1+N, BS, K, D)
            src._arena = {
                "k": jnp.asarray(rng.randn(*shape).astype(np.float32)),
                "v": jnp.asarray(rng.randn(*shape).astype(np.float32))}
            blocks = [5, 2, 9]                   # deliberately out of order
            handoff = ArenaHandoff()
            dst_before = np.asarray(dst._arena["k"]).copy()
            dst_ids = handoff.transfer(src, dst, blocks)
            assert dst_ids is not None and len(dst_ids) == 3
            src_k = np.asarray(src._arena["k"])
            dst_k = np.asarray(dst._arena["k"])
            dst_v = np.asarray(dst._arena["v"])
            src_v = np.asarray(src._arena["v"])
            for s, d in zip(blocks, dst_ids):
                np.testing.assert_array_equal(dst_k[:, d], src_k[:, s])
                np.testing.assert_array_equal(dst_v[:, d], src_v[:, s])
            # blocks NOT in the transfer kept their old content
            untouched = [b for b in range(dst_k.shape[1])
                         if b not in dst_ids and b != 0]
            np.testing.assert_array_equal(dst_k[:, untouched],
                                          dst_before[:, untouched])
        finally:
            src.close()
            dst.close()

    def test_destination_dry_returns_none_no_leak(self, tiny_engine):
        small = dict(SCFG)
        small["num_blocks"] = 8
        src = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        dst = ServingEngine(tiny_engine, ServingConfig(**small))
        try:
            dst.alloc.alloc(7)       # 1 free block left, need 2
            before = dst.alloc.blocks_in_use
            assert ArenaHandoff().transfer(src, dst, [1, 2]) is None
            assert dst.alloc.blocks_in_use == before
        finally:
            src.close()
            dst.close()

    def test_geometry_mismatch_raises(self, tiny_engine):
        other = dict(SCFG)
        other["block_size"] = 8
        other["max_model_len"] = 64
        src = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        dst = ServingEngine(tiny_engine, ServingConfig(**other))
        try:
            with pytest.raises(HandoffGeometryError):
                ArenaHandoff().transfer(src, dst, [1])
        finally:
            src.close()
            dst.close()


class TestDisaggregation:
    def test_prefill_decode_split_bit_exact(self, tiny_engine):
        prompts = mk_prompts(6, seed=4)
        want = oracle_outputs(tiny_engine, prompts, n_new=10)
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE])
        try:
            hs = [router.submit(p, max_new_tokens=10, seed=i)
                  for i, p in enumerate(prompts)]
            outs = [h.result() for h in hs]
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
            assert sum(h.handoffs for h in hs) == len(prompts)
            assert replicas[0].engine.sched.handoffs_out == len(prompts)
            # the prefill engine released every handed-off request; only
            # prefix-cache pins may remain
            alloc = replicas[0].engine.alloc
            cache = replicas[0].engine.sched.prefix
            held = cache.cached_blocks if cache else 0
            assert alloc.blocks_in_use == held
        finally:
            router.close()

    def test_handoff_with_cow_shared_last_block(self, tiny_engine):
        """Two identical full-block prompts: the second admission maps the
        prefix cache's blocks (refcount > 1, last block COW-shared) — its
        handoff must export private-or-shared content correctly and release
        exactly one reference on the source."""
        prompt = np.arange(1, 33, dtype=np.int32)      # exactly 2 blocks
        want = oracle_outputs(tiny_engine, [prompt, prompt], n_new=8)
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE])
        try:
            h0 = router.submit(prompt, max_new_tokens=8, seed=0)
            np.testing.assert_array_equal(h0.result(), want[0])
            pre = replicas[0].engine.sched
            assert pre.prefix is not None and pre.prefix.cached_blocks > 0
            h1 = router.submit(prompt, max_new_tokens=8, seed=1)
            np.testing.assert_array_equal(h1.result(), want[1])
            assert pre.prefix_hits >= 1          # admission reused blocks
            assert h1.handoffs == 1
            alloc = replicas[0].engine.alloc
            assert alloc.blocks_in_use == pre.prefix.cached_blocks
            # cache entries survive with exactly their own pin reference
            for b in list(pre.prefix._entries.values()):
                assert alloc.refcount(b) == 1
        finally:
            router.close()

    def test_cancel_racing_handoff(self, tiny_engine):
        """Cancel issued the moment the handoff lands: the fleet handle is
        already rebound to the decode replica, and cancelling must free the
        imported blocks there (and nothing on the prefill side twice)."""
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE],
                                    prefix_cache=False)
        try:
            h = router.submit(np.arange(1, 40, dtype=np.int32),
                              max_new_tokens=32)
            while h.handoffs == 0 and not h.done:
                router.step()
            assert h._fr.replica.index == 1
            assert h.cancel() is True
            with pytest.raises(RequestCancelled):
                h.result()
            router.step()
            assert replicas[0].engine.alloc.blocks_in_use == 0
            assert replicas[1].engine.alloc.blocks_in_use == 0
            # ledger: the handoff is not a completion, the cancel is one
            assert replicas[0].engine.sched.handoffs_out == 1
            assert replicas[1].engine.sched.cancelled_count == 1
        finally:
            router.close()

    def test_cancel_during_prefill_before_handoff(self, tiny_engine):
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE],
                                    prefix_cache=False)
        try:
            h = router.submit(np.arange(1, 120, dtype=np.int32),
                              max_new_tokens=8)
            router.step()                       # first chunk only (of 8)
            assert h.handoffs == 0
            assert h.cancel() is True
            router.step()
            assert replicas[0].engine.alloc.blocks_in_use == 0
            assert replicas[0].engine.sched.handoffs_out == 0
        finally:
            router.close()

    def test_deadline_survives_handoff(self, tiny_engine):
        """The remaining deadline crosses the handoff: the adopted request
        must keep its EDF priority on the decode replica, not sort last as
        deadline-less."""
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE])
        try:
            h = router.submit(np.arange(1, 40, dtype=np.int32),
                              max_new_tokens=8, deadline_s=60.0)
            for _ in range(200):
                router.step()
                if h.handoffs:
                    break
            assert h.handoffs == 1
            dec = replicas[1].engine.sched
            adopted = (list(dec.queued) + list(dec.running.values()))
            assert len(adopted) == 1
            assert adopted[0].deadline_s is not None
            assert adopted[0].deadline_s <= replicas[1].engine.clock() + 60.0
            h.result()
        finally:
            router.close()

    @pytest.mark.slow   # tier-1 keeps the disagg smoke + COW-handoff
    def test_decode_pool_preemption_after_adoption_bit_exact(self,
                                                            tiny_engine):
        """Pressure on the decode pool preempts ADOPTED requests: the
        recompute source (original prompt + streamed tokens) was carried
        through the handoff, so eviction+recompute on the destination still
        reproduces the uninterrupted stream bit-exactly."""
        prompts = [np.arange(1, 40 + 7 * i, dtype=np.int32)
                   for i in range(4)]
        want = oracle_outputs(tiny_engine, prompts, n_new=24)
        # decode pool sized to admit all four, then run dry as they grow
        replicas = [
            Replica(ServingEngine(tiny_engine, ServingConfig(**SCFG)),
                    0, role=ROLE_PREFILL),
            Replica(ServingEngine(
                tiny_engine,
                ServingConfig(**{**SCFG, "num_blocks": 16,
                                 "prefix_cache": False})),
                1, role=ROLE_DECODE)]
        router = FleetRouter(replicas, FleetConfig())
        try:
            hs = [router.submit(p, max_new_tokens=24, seed=i)
                  for i, p in enumerate(prompts)]
            outs = [h.result() for h in hs]
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
            dec = replicas[1].engine.sched
            assert sum(h.handoffs for h in hs) >= 1
            assert dec.preemption_count >= 1     # pressure actually hit
        finally:
            router.close()

    def test_full_decode_pool_falls_back_in_place(self, tiny_engine):
        """A handoff the decode pool cannot take decodes on the prefill
        replica — degraded but live, and still bit-exact."""
        prompt = np.arange(1, 40, dtype=np.int32)
        want = oracle_outputs(tiny_engine, [prompt], n_new=8)
        replicas = [
            Replica(ServingEngine(tiny_engine, ServingConfig(**SCFG)),
                    0, role=ROLE_PREFILL),
            Replica(ServingEngine(
                tiny_engine,
                ServingConfig(**{**SCFG, "num_blocks": 8,
                                 "prefix_cache": False})),
                1, role=ROLE_DECODE)]
        router = FleetRouter(replicas, FleetConfig())
        try:
            replicas[1].engine.alloc.alloc(8)    # decode pool fully booked
            h = router.submit(prompt, max_new_tokens=8, seed=0)
            np.testing.assert_array_equal(h.result(), want[0])
            assert h.handoffs == 0
            assert router._handoff_fallbacks == 1
        finally:
            router.close()

    def test_parallel_sampling_rejected_on_disagg(self, tiny_engine):
        router, _ = mk_fleet(tiny_engine, n=2,
                             roles=[ROLE_PREFILL, ROLE_DECODE])
        try:
            with pytest.raises(NotImplementedError):
                router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=4, n=2)
        finally:
            router.close()


# ---------------------------------------------------------------------------
# the acceptance smoke (ISSUE-11): ≥12 staggered mixed-length requests,
# 3-replica fleet AND disaggregated pair, bit-identical to a single engine,
# including with a deterministic mid-stream replica kill
# ---------------------------------------------------------------------------


class TestFleetAcceptanceSmoke:
    N_REQ = 12
    N_NEW = 12
    TEMP = 0.7   # the sampling stream must survive rebinding, not just argmax

    def _prompts(self):
        return mk_prompts(self.N_REQ, lo=4, hi=60, seed=11)

    def test_three_replica_fleet_with_kill_bit_exact(self, tiny_engine):
        prompts = self._prompts()
        want = oracle_outputs(tiny_engine, prompts, n_new=self.N_NEW,
                              temperature=self.TEMP)
        router, replicas = mk_fleet(
            tiny_engine, n=3, policy="kv_occupancy",
            fault_plan=[{"kind": "replica_kill", "step": 9, "replica": 1}])
        try:
            hs = run_staggered(router, prompts, n_new=self.N_NEW,
                               temperature=self.TEMP)
            assert replicas[1].deaths == 1        # the fault actually fired
            resubmitted = sum(h.resubmits for h in hs)
            assert resubmitted > 0                # ... mid-stream
            for i, (h, exp) in enumerate(zip(hs, want)):
                np.testing.assert_array_equal(
                    np.asarray(h.tokens, np.int32), exp,
                    err_msg=f"request {i} diverged from the single engine")
            # every alive replica's pool drained back to its cache pins
            for r in replicas:
                if r.alive:
                    held = (r.engine.sched.prefix.cached_blocks
                            if r.engine.sched.prefix else 0)
                    assert r.engine.alloc.blocks_in_use == held
        finally:
            router.close()

    def test_disaggregated_pair_bit_exact(self, tiny_engine):
        prompts = self._prompts()
        want = oracle_outputs(tiny_engine, prompts, n_new=self.N_NEW,
                              temperature=self.TEMP)
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE])
        try:
            hs = run_staggered(router, prompts, n_new=self.N_NEW,
                               temperature=self.TEMP)
            for i, (h, exp) in enumerate(zip(hs, want)):
                np.testing.assert_array_equal(
                    np.asarray(h.tokens, np.int32), exp,
                    err_msg=f"request {i} diverged across the handoff")
            assert sum(h.handoffs for h in hs) == self.N_REQ
        finally:
            router.close()


# ---------------------------------------------------------------------------
# fault plan + report section (device-free)
# ---------------------------------------------------------------------------


class TestReplicaKillFault:
    def test_fires_once_at_scheduled_iteration(self):
        from deepspeed_tpu.observability.faultinject import FaultInjector

        inj = FaultInjector(plan=[{"kind": "replica_kill", "step": 3,
                                   "replica": 2}], rank=0, restart=0)
        killed = []
        for it in range(6):
            inj.before_router_step(it, killed.append)
        assert killed == [2]

    def test_not_applied_by_train_step_hook(self):
        from deepspeed_tpu.observability.faultinject import FaultInjector

        inj = FaultInjector(plan=[{"kind": "replica_kill", "step": 0,
                                   "replica": 0}], rank=0, restart=0)
        inj.before_step(0, engine=None)      # train-side hook: not its fault
        killed = []
        inj.before_router_step(0, killed.append)
        assert killed == [0]


class TestFleetServingReport:
    def _records(self):
        lbl = {"replica": "0", "role": "prefill"}
        lbl2 = {"replica": "1", "role": "decode"}
        return [
            {"type": "gauge", "name": "fleet_serving/replicas_alive",
             "labels": {}, "value": 2},
            {"type": "gauge", "name": "fleet_serving/requests_in_flight",
             "labels": {}, "value": 0},
            {"type": "gauge", "name": "fleet_serving/queue_depth",
             "labels": lbl, "value": 1},
            {"type": "gauge", "name": "fleet_serving/arena_occupancy",
             "labels": lbl, "value": 0.5},
            {"type": "gauge", "name": "fleet_serving/arena_occupancy",
             "labels": lbl2, "value": 0.25},
            {"type": "gauge", "name": "fleet_serving/kv_blocks_in_use",
             "labels": lbl2, "value": 8},
            {"type": "counter", "name": "fleet_serving/routing_decisions",
             "labels": {"policy": "affinity", "reason": "affinity_warm",
                        "replica": "0"}, "value": 5},
            {"type": "counter", "name": "fleet_serving/routing_decisions",
             "labels": {"policy": "affinity", "reason": "disagg_decode",
                        "replica": "1"}, "value": 6},
            {"type": "counter", "name": "fleet_serving/handoffs",
             "labels": {}, "value": 6},
            {"type": "histogram", "name": "fleet_serving/handoff_ms",
             "labels": {}, "count": 6, "mean": 2.5, "min": 1.0, "max": 9.0},
            {"type": "gauge", "name": "fleet_serving/handoff_p50_ms",
             "labels": {}, "value": 2.0},
            {"type": "gauge", "name": "fleet_serving/handoff_p99_ms",
             "labels": {}, "value": 8.8},
            {"type": "counter", "name": "fleet_serving/replica_deaths",
             "labels": {"reason": "fault"}, "value": 1},
            {"type": "counter", "name": "fleet_serving/resubmits",
             "labels": {}, "value": 3},
            # the self-healing / overload block (ISSUE-12)
            {"type": "gauge", "name": "fleet_serving/health_state",
             "labels": lbl, "value": 1},
            {"type": "gauge", "name": "fleet_serving/health_state",
             "labels": lbl2, "value": 3},
            {"type": "counter", "name": "fleet_serving/health_verdicts",
             "labels": {"verdict": "slow"}, "value": 2},
            {"type": "counter", "name": "fleet_serving/quarantines",
             "labels": {"reason": "slow"}, "value": 2},
            {"type": "counter", "name": "fleet_serving/revivals",
             "labels": {}, "value": 1},
            {"type": "counter",
             "name": "fleet_serving/probation_graduations",
             "labels": {}, "value": 1},
            {"type": "counter", "name": "fleet_serving/handoff_failures",
             "labels": {}, "value": 1},
            {"type": "counter", "name": "fleet_serving/shed",
             "labels": {"reason": "deadline_infeasible"}, "value": 4},
            {"type": "counter", "name": "fleet_serving/shed",
             "labels": {"reason": "degraded"}, "value": 2},
            {"type": "gauge", "name": "fleet_serving/degraded_mode",
             "labels": {}, "value": 2},
        ]

    def test_section_renders_everything(self):
        from deepspeed_tpu.observability.report import summarize_fleet_serving

        text = summarize_fleet_serving(self._records())
        assert "== fleet serving ==" in text
        assert "replicas_alive=2" in text
        assert "prefill" in text and "decode" in text
        assert "affinity/disagg_decode=6" in text
        assert "affinity/affinity_warm=5" in text
        assert "handoffs: count=6" in text
        assert "p50=2.00ms" in text and "p99=8.80ms" in text
        assert "1 replica death(s)" in text and "fault=1" in text
        assert "3 in-flight request(s) resubmitted" in text
        # the self-healing / overload block
        assert "serving" in text and "probation" in text  # state column
        assert "verdicts: slow=2" in text
        assert "quarantines=2" in text and "revivals=1" in text
        assert "graduations=1" in text
        assert "handoff_failures=1" in text
        assert "6 request(s) shed under overload" in text
        assert "deadline_infeasible=4" in text and "degraded=2" in text
        assert "degraded_mode=2" in text
        assert "affinity hints off" in text

    def test_absent_without_fleet_metrics(self):
        from deepspeed_tpu.observability.report import summarize_fleet_serving

        assert summarize_fleet_serving(
            [{"type": "gauge", "name": "serving/queue_depth",
              "labels": {}, "value": 1}]) == ""

    def test_report_cli_end_to_end(self, tmp_path):
        from deepspeed_tpu.observability.report import report

        path = tmp_path / "metrics.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in self._records()))
        assert "== fleet serving ==" in report([str(path)])


# ---------------------------------------------------------------------------
# replica construction
# ---------------------------------------------------------------------------


class TestBuildReplicas:
    def test_shares_compiled_programs(self, tiny_engine):
        replicas = build_replicas(tiny_engine, ServingConfig(**SCFG), 3)
        try:
            first = replicas[0].engine
            for r in replicas[1:]:
                assert r.engine._prefill is first._prefill
                assert r.engine._decode is first._decode
                assert r.engine is not first
                assert r.engine.alloc is not first.alloc
        finally:
            for r in replicas:
                r.engine.close()

    def test_roles_length_checked(self, tiny_engine):
        with pytest.raises(ValueError, match="roles"):
            build_replicas(tiny_engine, ServingConfig(**SCFG), 2,
                           roles=[ROLE_MIXED])


# ---------------------------------------------------------------------------
# close-time telemetry
# ---------------------------------------------------------------------------


class TestFleetCloseGauges:
    def test_close_publishes_fleet_wide_latency(self, tiny_engine, tmp_path):
        """Every replica's close() sets the same unlabeled serving/* latency
        gauges; the router must publish the POOLED reservoirs last so the
        dump describes the fleet, not whichever replica closed last."""
        from deepspeed_tpu.config.config import ObservabilityConfig
        from deepspeed_tpu.observability import (configure_observability,
                                                 get_registry, reset_session)
        from deepspeed_tpu.serving.api import _percentile

        reset_session()
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "obs"),
            flight_recorder=False))
        try:
            router, replicas = mk_fleet(tiny_engine, n=2,
                                        policy="round_robin")
            hs = [router.submit(p, max_new_tokens=6, seed=i)
                  for i, p in enumerate(mk_prompts(4, seed=5))]
            router.run()
            [h.result() for h in hs]
            per_replica = [list(r.engine._ttft_samples) for r in replicas]
            assert all(per_replica)      # round-robin spread the load
            pooled = [s for xs in per_replica for s in xs]
            router.close()
            got = get_registry().gauge("serving/ttft_p50_ms").value()
            assert got == _percentile(pooled, 0.50)
            # the pooled median must differ from at least one replica's own
            # close-time value, or this test could not catch last-writer-wins
            assert any(_percentile(xs, 0.50) != got for xs in per_replica) \
                or len(set(pooled)) == 1
        finally:
            reset_session()


# ---------------------------------------------------------------------------
# ISSUE-12: replica lifecycle — quarantine → probation → graduation,
# revival, circuit breaker (sleep-free: fault-injected step-time penalties
# ride the health data-plane, never the wall clock)
# ---------------------------------------------------------------------------


# warmup 3 swallows every compile-heavy first dispatch (prefill, decode —
# an SLO of 2s with ms-scale real steps then only ever convicts the
# injected 10s penalty); lifecycle tests also run prefix_cache=False so a
# late COW-program compile can never land in a sampled step
HEAL_CFG = dict(policy="round_robin", health_window=2, step_time_slo_s=2.0,
                health_warmup_steps=3, quarantine_iterations=4,
                revive_after_iterations=2, probation_requests=2,
                probation_share=0.25, breaker_incidents=4)


class TestReplicaLifecycle:
    def test_slow_replica_quarantined_then_graduates(self, tiny_engine):
        """The full state machine on one replica: a step-time SLO breach
        quarantines it (alive, no new traffic), the backoff expires into
        probation, and clean completions graduate it back to full
        weight."""
        router, replicas = mk_fleet(
            tiny_engine, n=2, fleet_cfg=FleetConfig(**HEAL_CFG),
            prefix_cache=False,
            fault_plan=[{"kind": "replica_slow", "step": 0, "steps": 7,
                         "replica": 1, "sleep_s": 10.0}])
        try:
            hs = [router.submit(np.arange(1, 20, dtype=np.int32),
                                max_new_tokens=6, seed=i) for i in range(4)]
            it = 0
            while not replicas[1].quarantined:
                router.step()
                it += 1
                assert it < 50, "slow replica never quarantined"
            assert replicas[1].alive                  # quarantined ≠ dead
            assert replicas[1].quarantine_reason == "step_slo"
            assert router._quarantine_count == 1
            # no NEW traffic routes to it while quarantined...
            h_new = router.submit(np.arange(1, 20, dtype=np.int32),
                                  max_new_tokens=4)
            assert h_new._fr.replica.index == 0
            # ...but its own in-flight work keeps stepping to completion
            for h in hs:
                h.result()
            while replicas[1].quarantined:
                router.step()
                it += 1
                assert it < 200, "quarantine never expired"
            # on probation now — its own work completing during probation
            # may already have earned clean-completion credit
            assert 0 <= replicas[1].probation_left <= 2
            # clean completions graduate it (bounded: the fault window is
            # over, so probation must resolve — never re-convict)
            h_new.result()
            for _ in range(20):
                if replicas[1].probation_left == 0:
                    break
                router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=4).result()
            assert router._graduation_count == 1
            assert replicas[1].routable()
        finally:
            router.close()

    def test_probation_traffic_share_bounded(self, tiny_engine):
        """A probation replica's concurrent share stays under
        probation_share × fleet in-flight (floor one): with share 0.25 and
        ~6 in flight, at most one lands on it at a time."""
        router, replicas = mk_fleet(
            tiny_engine, n=2, policy="least_queue",
            fleet_cfg=FleetConfig(**{**HEAL_CFG, "policy": "least_queue"}))
        try:
            replicas[1].probation_left = 3           # force probation
            hs = [router.submit(np.arange(1, 20, dtype=np.int32),
                                max_new_tokens=8, seed=i)
                  for i in range(6)]
            on_probation = [h for h in hs if h._fr.replica.index == 1]
            # least_queue would have split 3/3; the probation cap allows
            # at most max(1, int(0.25 × in_flight)) concurrent
            assert len(on_probation) <= 1
            for h in hs:
                h.result()
        finally:
            router.close()

    def test_flapping_replica_respects_breaker_budget(self, tiny_engine):
        """replica_flap kills every revived incarnation; the per-replica
        circuit breaker must retire it after breaker_incidents incidents —
        revivals never exceed the budget and the fleet finishes all work
        on the survivor."""
        cfg = dict(HEAL_CFG)
        cfg.update(breaker_incidents=2, revive_after_iterations=1)
        router, replicas = mk_fleet(
            tiny_engine, n=2, fleet_cfg=FleetConfig(**cfg),
            fault_plan=[{"kind": "replica_flap", "step": 1, "steps": 60,
                         "replica": 1}])
        try:
            prompts = mk_prompts(6, seed=21)
            want = oracle_outputs(tiny_engine, prompts, n_new=10)
            hs = [router.submit(p, max_new_tokens=10, seed=i)
                  for i, p in enumerate(prompts)]
            outs = [h.result() for h in hs]
            # drive past the flap window so the breaker resolves
            for _ in range(70):
                router.step()
            assert replicas[1].retired
            assert replicas[1].death_reason.startswith("breaker")
            assert replicas[1].revivals <= cfg["breaker_incidents"]
            # retired means retired: no more revivals, ever
            revivals_at_retirement = replicas[1].revivals
            for _ in range(30):
                router.step()
            assert replicas[1].revivals == revivals_at_retirement
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
        finally:
            router.close()

    def test_revived_replica_streams_bit_exact(self, tiny_engine):
        """Requests served by a revived replica (post-kill rebuild sharing
        the survivor's compiled programs) are bit-identical to the
        single-engine oracle — revival is invisible to clients."""
        prompts = mk_prompts(8, seed=31)
        want = oracle_outputs(tiny_engine, prompts, n_new=8,
                              temperature=0.7)
        cfg = dict(HEAL_CFG)
        cfg.update(revive_after_iterations=1, probation_requests=1,
                   probation_share=1.0)
        router, replicas = mk_fleet(
            tiny_engine, n=2, fleet_cfg=FleetConfig(**cfg),
            fault_plan=[{"kind": "replica_kill", "step": 2, "replica": 1}])
        try:
            # first half rides through the kill + revival
            hs = [router.submit(p, max_new_tokens=8, seed=i,
                                temperature=0.7)
                  for i, p in enumerate(prompts[:4])]
            outs = [h.result() for h in hs]
            assert replicas[1].revivals == 1
            # second half: round_robin lands half on the REVIVED replica
            hs2 = [router.submit(p, max_new_tokens=8, seed=4 + i,
                                 temperature=0.7)
                   for i, p in enumerate(prompts[4:])]
            outs += [h.result() for h in hs2]
            assert any(h._fr.replica.index == 1 for h in hs2)
            assert router._graduation_count >= 1
            for i, (got, exp) in enumerate(zip(outs, want)):
                np.testing.assert_array_equal(
                    got, exp, err_msg=f"request {i} diverged after revival")
            # revival reuses the compile set: the rebuilt engine's jitted
            # callables ARE the survivor's
            assert replicas[1].engine._decode is replicas[0].engine._decode
        finally:
            router.close()

    def test_prefill_replica_graduates_via_handoffs(self, tiny_engine):
        """In a disaggregated fleet every request rebinds to a decode
        replica at handoff, so a probation PREFILL replica's service is
        its completed handoffs — it must still be able to graduate."""
        router, replicas = mk_fleet(
            tiny_engine, n=2, roles=[ROLE_PREFILL, ROLE_DECODE],
            fleet_cfg=FleetConfig(**HEAL_CFG))
        try:
            replicas[0].probation_left = 2        # prefill on probation
            for i in range(3):
                h = router.submit(np.arange(1, 40, dtype=np.int32),
                                  max_new_tokens=4, seed=i)
                h.result()
                assert h.handoffs == 1            # served via handoff
            assert replicas[0].probation_left == 0
            assert router._graduation_count == 1
        finally:
            router.close()

    def test_revival_keeps_dead_incarnations_latency_samples(
            self, tiny_engine, tmp_path):
        """Close-time fleet-wide latency gauges must pool the REPLACED
        engine's reservoirs too — a revival must not erase the requests
        its dead incarnation served."""
        from deepspeed_tpu.config.config import ObservabilityConfig
        from deepspeed_tpu.observability import (configure_observability,
                                                 get_registry,
                                                 reset_session)

        reset_session()
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "obs"),
            flight_recorder=False))
        try:
            cfg = dict(HEAL_CFG)
            cfg.update(revive_after_iterations=1, probation_requests=1)
            router, replicas = mk_fleet(tiny_engine, n=2,
                                        fleet_cfg=FleetConfig(**cfg))
            hs = [router.submit(p, max_new_tokens=6, seed=i)
                  for i, p in enumerate(mk_prompts(4, seed=71))]
            [h.result() for h in hs]
            served_before = list(replicas[1].engine._ttft_samples)
            assert served_before          # round_robin spread the load
            router.kill_replica(1)
            router.step()                 # drain + revive (backoff 1)
            while not replicas[1].alive:
                router.step()
            assert replicas[1].revivals == 1
            router.close()
            # the dead incarnation's samples survived into the pool
            assert get_registry().gauge("serving/ttft_p50_ms").value() \
                is not None
            pooled_n = len(router._replaced_engines[0]._ttft_samples)
            assert pooled_n == len(served_before)
        finally:
            reset_session()

    def test_manual_revive_refused_for_retired(self, tiny_engine):
        from deepspeed_tpu.serving.fleet.replica import ReplicaRetired

        router, replicas = mk_fleet(
            tiny_engine, n=2,
            fleet_cfg=FleetConfig(**{**HEAL_CFG, "auto_revive": False}))
        try:
            replicas[1].retire()
            with pytest.raises(ReplicaRetired):
                router.revive_replica(1)
        finally:
            router.close()

    def test_auto_revive_off_keeps_dead_replica_dead(self, tiny_engine):
        router, replicas = mk_fleet(
            tiny_engine, n=2,
            fleet_cfg=FleetConfig(**{**HEAL_CFG, "auto_revive": False}))
        try:
            router.kill_replica(1)
            h = router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=4)
            h.result()
            for _ in range(20):
                router.step()
            assert not replicas[1].alive and replicas[1].revivals == 0
        finally:
            router.close()


# ---------------------------------------------------------------------------
# ISSUE-12: overload control — deadline-infeasibility admission shedding +
# the degraded-mode ladder
# ---------------------------------------------------------------------------


class TestOverloadControl:
    def test_infeasible_deadline_shed_at_admission(self, tiny_engine):
        from deepspeed_tpu.serving.fleet import Overloaded

        router, replicas = mk_fleet(tiny_engine, n=2)
        try:
            # one finished request seeds the TPOT estimator
            router.submit(np.arange(1, 20, dtype=np.int32),
                          max_new_tokens=8).result()
            assert router._tpot_estimate() is not None
            with pytest.raises(Overloaded) as exc:
                router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=64, deadline_s=1e-9)
            assert exc.value.retry_after_s > 0
            assert router._shed_count == 1
            # the shed request never reached an engine
            assert all(r.engine.in_flight() == 0 for r in replicas)
            # a feasible deadline still admits
            h = router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=4, deadline_s=3600.0)
            h.result()
        finally:
            router.close()

    def test_parallel_sampling_scales_feasibility_estimate(self,
                                                           tiny_engine):
        """submit(n=8) decodes 8× the budget — a deadline feasible for one
        sample but not eight must shed."""
        from deepspeed_tpu.serving.fleet import Overloaded

        router, _ = mk_fleet(tiny_engine, n=2)
        try:
            router.submit(np.arange(1, 20, dtype=np.int32),
                          max_new_tokens=8).result()
            tpot = router._tpot_estimate()
            assert tpot is not None
            # feasible for one sample (queue empty): est = tpot × 8
            deadline = tpot * 8 * 4
            h = router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=8, deadline_s=deadline)
            h.result()
            with pytest.raises(Overloaded):
                router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=8, deadline_s=deadline, n=8)
        finally:
            router.close()

    def test_shed_submission_does_not_pollute_affinity(self, tiny_engine):
        """An admission-shed request must not leave an affinity hint —
        later prefix-sharers would follow it to a cold replica."""
        from deepspeed_tpu.serving.fleet import Overloaded

        router, _ = mk_fleet(tiny_engine, n=2, policy="affinity")
        try:
            router.submit(np.arange(50, 90, dtype=np.int32),
                          max_new_tokens=8).result()   # seeds TPOT
            sys_prompt = np.arange(1, 40, dtype=np.int32)
            with pytest.raises(Overloaded):
                router.submit(sys_prompt, max_new_tokens=64,
                              deadline_s=1e-9)
            key = router._affinity_key(sys_prompt)
            assert key not in router._affinity    # no hint committed
            # the next (admitted) submission is a genuine cold start
            router.submit(sys_prompt, max_new_tokens=2).result()
            assert router._decisions[("affinity", "affinity_cold")] >= 1
            assert router._decisions[("affinity", "affinity_warm")] == 0
        finally:
            router.close()

    def test_revive_before_drain_resubmits_stranded_requests(self,
                                                            tiny_engine):
        """A manual revive racing the step loop (kill not yet drained)
        must drain the dead incarnation's requests first — they would
        otherwise stay bound to the discarded engine forever."""
        fc = FleetConfig(policy="round_robin", auto_revive=False)
        router, replicas = mk_fleet(tiny_engine, n=2, fleet_cfg=fc,
                                    policy="round_robin")
        try:
            prompts = mk_prompts(2, lo=18, hi=20, seed=61)
            want = oracle_outputs(tiny_engine, prompts, n_new=6)
            hs = [router.submit(p, max_new_tokens=6, seed=i)
                  for i, p in enumerate(prompts)]
            router.step()
            router.kill_replica(1)
            # revive BEFORE any step could drain the dead incarnation
            assert not replicas[1].drained
            assert router.revive_replica(1) is True
            outs = [h.result() for h in hs]
            assert all(h.state == "finished" for h in hs)
            assert hs[1].resubmits == 1
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
        finally:
            router.close()

    def test_no_tpot_data_admits(self, tiny_engine):
        """The estimator sheds only on MEASURED evidence — a cold fleet
        admits every deadline."""
        router, _ = mk_fleet(tiny_engine, n=1)
        try:
            h = router.submit(np.arange(1, 20, dtype=np.int32),
                              max_new_tokens=4, deadline_s=1e-9)
            assert h is not None     # admitted (it will expire, not shed)
        finally:
            router.close()

    def test_degraded_ladder_climbs_sheds_and_recovers(self, tiny_engine):
        from deepspeed_tpu.serving.fleet import Overloaded

        fc = FleetConfig(policy="round_robin", overload_queue_depth=1,
                         overload_up_iterations=1,
                         overload_down_iterations=2)
        # 2 rows per replica: a 10-request burst queues deep
        router, replicas = mk_fleet(tiny_engine, n=2, fleet_cfg=fc,
                                    max_seqs=2)
        try:
            hs = [router.submit(np.arange(1, 30, dtype=np.int32),
                                max_new_tokens=8, seed=i,
                                deadline_s=(None if i % 2 else 3600.0))
                  for i in range(10)]
            seen_rungs = set()
            it = 0
            while router.in_flight():
                router.step()
                seen_rungs.add(router.degraded_mode)
                if router.degraded_mode >= 1:
                    # rung 1+: speculation suspended fleet-wide
                    assert all(r.engine.spec_suspended
                               for r in replicas if r.alive)
                it += 1
                assert it < 500
            assert 3 in seen_rungs                # the ladder reached shed
            shed = [h for h in hs if h.state == "shed"]
            assert shed                           # rung 3 shed queued work
            assert router.shed_count_total == len(shed)
            # no-deadline work was shed first (lowest priority)
            assert all(h._fr.deadline_abs is None for h in shed) \
                or len(shed) > sum(1 for h in hs
                                   if h._fr.deadline_abs is None)
            for h in shed:
                with pytest.raises(Overloaded) as exc:
                    h.result()
                assert exc.value.retry_after_s > 0
            # calm iterations walk the ladder back down, spec resumes
            for _ in range(3 * fc.overload_down_iterations + 3):
                router.step()
            assert router.degraded_mode == 0
            assert all(not r.engine.spec_suspended
                       for r in replicas if r.alive)
            # ledger: submitted == finished + cancelled + shed + deadline
            assert router.submitted_count == (
                router.finished_count + router.cancelled_count
                + router.shed_count_total
                + router.deadline_exceeded_count)
        finally:
            router.close()

    def test_rung2_spills_affinity(self, tiny_engine):
        """Degraded rung 2 stops following warm prefix-affinity hints —
        the request routes by load with reason degraded_spill."""
        router, replicas = mk_fleet(tiny_engine, n=2, policy="affinity")
        try:
            sys_prompt = np.arange(1, 40, dtype=np.int32)
            router.submit(sys_prompt, max_new_tokens=2).result()
            router._degraded = 2
            router.submit(sys_prompt, max_new_tokens=2).result()
            assert router._decisions[("affinity", "degraded_spill")] == 1
            assert router._decisions[("affinity", "affinity_warm")] == 0
        finally:
            router.close()


# ---------------------------------------------------------------------------
# ISSUE-12: handoff fault tolerance — a transfer that dies mid-flight
# retries once on another decode replica, then falls back to decoding in
# place; both sides' blocks freed exactly once
# ---------------------------------------------------------------------------


class TestHandoffFaultTolerance:
    def test_transfer_failure_frees_destination_blocks(self, tiny_engine):
        from deepspeed_tpu.serving.fleet import HandoffTransferError

        src = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        dst = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        try:
            handoff = ArenaHandoff()
            handoff.inject_fail_next = 1
            before = dst.alloc.blocks_in_use
            with pytest.raises(HandoffTransferError):
                handoff.transfer(src, dst, [1, 2, 3])
            assert dst.alloc.blocks_in_use == before   # freed exactly once
            # the seam is one-shot: the next transfer succeeds
            assert handoff.transfer(src, dst, [1, 2, 3]) is not None
        finally:
            src.close()
            dst.close()

    def test_failed_handoff_retries_on_other_decode_replica(self,
                                                           tiny_engine):
        prompts = mk_prompts(3, seed=41)
        want = oracle_outputs(tiny_engine, prompts, n_new=8)
        router, replicas = mk_fleet(
            tiny_engine, n=3,
            roles=[ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE],
            fault_plan=[{"kind": "handoff_fail", "step": 0}])
        try:
            hs = [router.submit(p, max_new_tokens=8, seed=i)
                  for i, p in enumerate(prompts)]
            outs = [h.result() for h in hs]
            assert router._handoff_failures == 1     # the fault fired
            # the retry landed every request on SOME decode replica
            assert sum(h.handoffs for h in hs) == len(prompts)
            assert router._handoff_fallbacks == 0
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
            for r in replicas:
                held = (r.engine.sched.prefix.cached_blocks
                        if r.engine.sched.prefix else 0)
                assert r.engine.alloc.blocks_in_use == held
        finally:
            router.close()

    def test_failed_handoff_falls_back_in_place(self, tiny_engine):
        """Single decode replica: the failed transfer has nowhere to
        retry — the request decodes on its prefill replica, bit-exact,
        with zero leaked blocks on either side."""
        prompt = np.arange(1, 40, dtype=np.int32)
        want = oracle_outputs(tiny_engine, [prompt], n_new=8)
        router, replicas = mk_fleet(
            tiny_engine, n=2, roles=[ROLE_PREFILL, ROLE_DECODE],
            prefix_cache=False,
            fault_plan=[{"kind": "handoff_fail", "step": 0}])
        try:
            h = router.submit(prompt, max_new_tokens=8, seed=0)
            np.testing.assert_array_equal(h.result(), want[0])
            assert h.handoffs == 0
            assert router._handoff_failures == 1
            assert router._handoff_fallbacks == 1
            router.step()
            assert replicas[0].engine.alloc.blocks_in_use == 0
            assert replicas[1].engine.alloc.blocks_in_use == 0
        finally:
            router.close()

    def test_import_exception_falls_back_no_leak(self, tiny_engine):
        """Not just the injected fault: ANY exception out of the transfer
        (kv_import raising) takes the same retry/fallback path."""
        prompt = np.arange(1, 40, dtype=np.int32)
        want = oracle_outputs(tiny_engine, [prompt], n_new=6)
        router, replicas = mk_fleet(tiny_engine, n=2,
                                    roles=[ROLE_PREFILL, ROLE_DECODE],
                                    prefix_cache=False)
        try:
            orig = router.handoff.transfer

            def exploding_transfer(src, dst, blocks):
                raise RuntimeError("synthetic kv_import device loss")

            router.handoff.transfer = exploding_transfer
            h = router.submit(prompt, max_new_tokens=6, seed=0)
            np.testing.assert_array_equal(h.result(), want[0])
            assert router._handoff_failures >= 1
            assert h.handoffs == 0
            router.handoff.transfer = orig
            router.step()
            assert replicas[1].engine.alloc.blocks_in_use == 0
        finally:
            router.close()


# ---------------------------------------------------------------------------
# ISSUE-12 satellite: a resubmission that finds every survivor full PARKS
# and retries on later iterations — it must not burn max_resubmits in one
# iteration
# ---------------------------------------------------------------------------


class TestParkedResubmission:
    def test_queuefull_parks_instead_of_cancelling(self, tiny_engine):
        prompts = mk_prompts(4, lo=18, hi=20, seed=51)
        want = oracle_outputs(tiny_engine, prompts, n_new=6, max_queue=2)
        fc = FleetConfig(policy="round_robin", max_resubmits=1,
                         auto_revive=False)
        router, replicas = mk_fleet(tiny_engine, n=2, fleet_cfg=fc,
                                    policy="round_robin", max_queue=2)
        try:
            hs = [router.submit(p, max_new_tokens=6, seed=i)
                  for i, p in enumerate(prompts)]
            # round_robin: replica 0 holds #0/#2, replica 1 holds #1/#3 —
            # the survivor is FULL (max_queue=2) when replica 1 dies
            assert [h._fr.replica.index for h in hs] == [0, 1, 0, 1]
            router.kill_replica(1)
            router.step()
            # both victims parked (not cancelled), one death each on the
            # budget ledger
            assert len(router._parked) == 2
            assert all(h._fr.resubmits == 1 for h in hs[1::2])
            outs = [h.result() for h in hs]
            # the parked pair resubmitted once survivor capacity freed,
            # without spending further budget
            assert all(h.state == "finished" for h in hs)
            assert all(h._fr.resubmits == 1 for h in hs[1::2])
            assert router.cancelled_count == 0
            for got, exp in zip(outs, want):
                np.testing.assert_array_equal(got, exp)
        finally:
            router.close()

    def test_parked_request_expires_if_deadline_passes(self, tiny_engine):
        clk = FakeClock()
        from deepspeed_tpu.serving import DeadlineExceeded

        fc = FleetConfig(policy="round_robin", auto_revive=False)
        router, replicas = mk_fleet(tiny_engine, n=2, fleet_cfg=fc,
                                    policy="round_robin", max_queue=1,
                                    clock=clk)
        try:
            h0 = router.submit(np.arange(1, 20, dtype=np.int32),
                               max_new_tokens=32)
            h1 = router.submit(np.arange(1, 20, dtype=np.int32),
                               max_new_tokens=8, deadline_s=5.0)
            assert h1._fr.replica.index == 1
            router.kill_replica(1)
            router.step()
            assert len(router._parked) == 1       # survivor full
            clk.advance(10.0)                     # deadline passes, parked
            router.step()
            assert h1.state == "deadline_exceeded"
            with pytest.raises(DeadlineExceeded):
                h1.result()
            h0.result()
            assert router.deadline_exceeded_count == 1
        finally:
            router.close()


# ---------------------------------------------------------------------------
# ISSUE-12: new fault kinds (device-free injector unit tests)
# ---------------------------------------------------------------------------


class TestNewFleetFaults:
    def test_replica_slow_penalty_window(self):
        from deepspeed_tpu.observability.faultinject import FaultInjector

        inj = FaultInjector(plan=[{"kind": "replica_slow", "step": 3,
                                   "steps": 2, "replica": 1,
                                   "sleep_s": 5.0}], rank=0, restart=0)
        assert inj.slow_penalty(2, 1) == 0.0
        assert inj.slow_penalty(3, 1) == 5.0
        assert inj.slow_penalty(4, 1) == 5.0
        assert inj.slow_penalty(5, 1) == 0.0      # window over
        assert inj.slow_penalty(3, 0) == 0.0      # other replica untouched
        assert len(inj.applied) == 1              # noted once

    def test_replica_flap_fires_across_window(self):
        from deepspeed_tpu.observability.faultinject import FaultInjector

        inj = FaultInjector(plan=[{"kind": "replica_flap", "step": 2,
                                   "steps": 3, "replica": 0}],
                            rank=0, restart=0)
        killed = []
        for it in range(8):
            inj.before_router_step(it, killed.append)
        assert killed == [0, 0, 0]                # every window iteration
        assert len(inj.applied) == 1              # noted once

    def test_handoff_fail_consumed_once(self):
        from deepspeed_tpu.observability.faultinject import FaultInjector

        inj = FaultInjector(plan=[{"kind": "handoff_fail", "step": 4}],
                            rank=0, restart=0)
        assert not inj.take_handoff_fail(3)       # not due yet
        assert inj.take_handoff_fail(6)           # due (at/after step)
        assert not inj.take_handoff_fail(7)       # consumed
