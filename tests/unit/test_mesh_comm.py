"""Mesh construction + collective-API tests on the 8-virtual-device CPU mesh —
analog of reference tests/unit/comm/test_dist.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map

from deepspeed_tpu import comm
from deepspeed_tpu.config.config import ParallelConfig
from deepspeed_tpu.parallel import mesh as mesh_mod


def build(pp=1, tp=1, sp=1, dp=0):
    return mesh_mod.build_mesh(ParallelConfig(
        pipeline_parallel_size=pp, tensor_parallel_size=tp,
        sequence_parallel_size=sp, data_parallel_size=dp))


def test_build_mesh_default(devices8):
    m = build()
    assert m.shape["data"] == 8
    assert m.shape["model"] == 1


def test_build_mesh_3d(devices8):
    m = build(pp=2, tp=2)
    assert dict(m.shape) == {"pipe": 2, "expert": 1, "data": 2, "seq": 1, "model": 2}


def test_build_mesh_invalid(devices8):
    with pytest.raises(ValueError):
        build(pp=3)


def test_all_reduce_psum(devices8):
    m = build()
    x = jnp.arange(8.0)

    f = shard_map(lambda v: comm.all_reduce(v, axis="data"),
                  mesh=m, in_specs=P("data"), out_specs=P())

    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((1,), 28.0))


def test_all_reduce_avg_max_min(devices8):
    m = build()
    x = jnp.arange(8.0)
    avg = shard_map(lambda v: comm.all_reduce(v, op=comm.ReduceOp.AVG, axis="data"),
                    mesh=m, in_specs=P("data"), out_specs=P())(x)
    np.testing.assert_allclose(np.asarray(avg), [3.5])
    mx = shard_map(lambda v: comm.all_reduce(v, op=comm.ReduceOp.MAX, axis="data"),
                   mesh=m, in_specs=P("data"), out_specs=P())(x)
    np.testing.assert_allclose(np.asarray(mx), [7.0])
    mn = shard_map(lambda v: comm.all_reduce(v, op=comm.ReduceOp.MIN, axis="data"),
                   mesh=m, in_specs=P("data"), out_specs=P())(x)
    np.testing.assert_allclose(np.asarray(mn), [0.0])


def test_all_gather(devices8):
    m = build()
    x = jnp.arange(8.0)
    f = shard_map(lambda v: comm.all_gather(v, axis="data"),
                  mesh=m, in_specs=P("data"), out_specs=P(None), check_vma=False)
    out = f(x)
    assert out.shape == (8,)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_reduce_scatter(devices8):
    m = build()
    # every shard holds the full vector; reduce_scatter sums and splits
    x = jnp.tile(jnp.arange(8.0), (8, 1))
    f = shard_map(lambda v: comm.reduce_scatter(v[0], axis="data"),
                  mesh=m, in_specs=P("data", None), out_specs=P("data"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)


def test_all_to_all(devices8):
    m = build()
    x = jnp.arange(64.0).reshape(8, 8)
    f = shard_map(lambda v: comm.all_to_all(v, axis="data", split_dim=1, concat_dim=0),
                  mesh=m, in_specs=P("data", None), out_specs=P("data", None))
    out = f(x)
    # all_to_all is its own inverse transpose-wise: verify via double application
    g = shard_map(lambda v: comm.all_to_all(v, axis="data", split_dim=0, concat_dim=1),
                  mesh=m, in_specs=P("data", None), out_specs=P("data", None))
    back = g(out)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))


def test_broadcast(devices8):
    m = build()
    x = jnp.arange(8.0)
    f = shard_map(lambda v: comm.broadcast(v, src=3, axis="data"),
                  mesh=m, in_specs=P("data"), out_specs=P("data"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ppermute_ring(devices8):
    m = build(pp=8, dp=1)
    x = jnp.arange(8.0)
    f = shard_map(lambda v: comm.send_next(v, axis="pipe"),
                  mesh=m, in_specs=P("pipe"), out_specs=P("pipe"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))
    b = shard_map(lambda v: comm.send_prev(v, axis="pipe"),
                  mesh=m, in_specs=P("pipe"), out_specs=P("pipe"))
    np.testing.assert_allclose(np.asarray(b(x)), np.roll(np.arange(8.0), -1))


def test_collectives_identity_outside_mesh():
    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(comm.all_reduce(x)), np.asarray(x))
    np.testing.assert_allclose(np.asarray(comm.all_gather(x)), np.asarray(x))


def test_groups_accessors(devices8):
    m = build(pp=2, tp=2)
    mesh_mod.set_mesh(m)
    assert mesh_mod.get_data_parallel_world_size() == 2
    assert mesh_mod.get_model_parallel_world_size() == 2
    assert mesh_mod.get_pipe_parallel_world_size() == 2
    assert mesh_mod.get_world_size() == 8


def test_comms_logger_bw_math():
    from deepspeed_tpu.comm.comms_logging import calc_bw_log
    size, algbw, busbw = calc_bw_log("all_reduce", 1000, 1e-3, 8)
    # allreduce: 2x data volume, busbw factor (n-1)/n
    assert algbw == pytest.approx(2 * 1000 / 1e-3 * 8 / 1e9)
    assert busbw == pytest.approx(algbw * 7 / 8)


def test_comm_benchmark_sweep(devices8):
    """ds_bench analog: every op sweeps and reports positive busbw with the
    logger's own bandwidth factors."""
    from deepspeed_tpu.comm.benchmark import OPS, run_comm_benchmark
    from deepspeed_tpu.config.config import ParallelConfig
    from deepspeed_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(ParallelConfig(data_parallel_size=8))
    results = run_comm_benchmark(ops=list(OPS), axis="data",
                                 minsize_log2=10, maxsize_log2=11,
                                 trials=2, warmups=1, mesh=mesh, quiet=True)
    assert len(results) == len(OPS) * 2
    for r in results:
        assert r["world"] == 8
        assert r["latency_ms"] > 0 and r["busbw_gbps"] > 0
    # all_reduce busbw factor (n-1)/n vs its algbw (values are rounded to
    # 6 decimals in the record, so compare loosely on the largest message)
    ar = [r for r in results if r["op"] == "all_reduce"][-1]
    assert abs(ar["busbw_gbps"] / ar["algbw_gbps"] - 7 / 8) < 0.1


def test_comm_benchmark_correctness(devices8):
    """The benchmarked programs compute the real collectives (a sweep that
    times wrong math would be worthless): spot-check all_reduce output."""
    import jax

    from deepspeed_tpu.comm.benchmark import _build
    from deepspeed_tpu.config.config import ParallelConfig
    from deepspeed_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(ParallelConfig(data_parallel_size=8))
    prog, x = _build("all_reduce", "data", mesh, 128, jnp.float32)
    out = np.asarray(jax.block_until_ready(prog(x)))
    np.testing.assert_allclose(out, np.full(128, 8.0))


def test_ds_ssh_cli(tmp_path, capsys):
    """ds_ssh analog: hostfile fan-out command construction + the
    missing-hostfile failure mode."""
    from deepspeed_tpu.launcher.tools import run_on_all_hosts, ssh_cli_main

    hf = tmp_path / "hostfile"
    hf.write_text("worker-0 slots=4\nworker-1 slots=4\n")
    rc = run_on_all_hosts(["echo", "hi there"], hostfile=str(hf),
                          dry_run=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert "worker-0" in out and "worker-1" in out
    assert "'hi there'" in out or "hi\\ there" in out   # quoted
    assert run_on_all_hosts(["echo"], hostfile=str(tmp_path / "nope")) == 1
    err = capsys.readouterr().err
    assert "Missing hostfile" in err
    rc = ssh_cli_main(["-f", str(hf), "--dry-run", "uptime"])
    assert rc == 0
