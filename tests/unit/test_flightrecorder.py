"""Unit tests for the flight recorder, hang watchdog and goodput accountant
(`deepspeed_tpu/observability/{flightrecorder,hangdetect,goodput}.py`) plus
the `report --crash-dump` CLI and the bench-guard satellite
(`bench_common.py`).

The acceptance paths live here:

* a deliberately stalled step (a span that heartbeats once and never again)
  fires the hang watchdog within the configured deadline and produces a
  crash bundle the `report --crash-dump` CLI parses back to the stalled
  span name;
* an enabled CPU engine run publishes `goodput/goodput_fraction` and
  `goodput/mfu` to the MetricsRegistry;
* the disabled path wires nothing (no recorder, no watchdog, no accountant,
  no tracer hook) — zero per-step overhead.

Watchdog/goodput unit tests use an injectable fake clock — no real sleeps;
the single threaded end-to-end test bounds its wait at ~2 s worst case."""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning.cost_model import PEAK_FLOPS, peak_flops_for
from deepspeed_tpu.config.config import ObservabilityConfig
from deepspeed_tpu.models import simple_model
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, get_session,
                                         reset_session)
from deepspeed_tpu.observability import flightrecorder as fr_mod
from deepspeed_tpu.observability.flightrecorder import (FlightRecorder,
                                                        find_latest_bundle)
from deepspeed_tpu.observability.goodput import GoodputAccountant
from deepspeed_tpu.observability.hangdetect import HangWatchdog
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.observability.report import crash_report, main as report_main
from deepspeed_tpu.observability.spans import SpanTracer
from deepspeed_tpu.profiling import compiled_cost

import bench_common


@pytest.fixture(autouse=True)
def _obs_isolation():
    reset_session()
    get_registry().reset()
    yield
    reset_session()
    get_registry().reset()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# flight recorder ring


class TestFlightRecorderRing:
    def test_eviction_order(self, tmp_path):
        rec = FlightRecorder(capacity=3, dump_dir=str(tmp_path))
        for i in range(5):
            rec.record("tick", i=i)
        evs = rec.snapshot()
        assert [e["i"] for e in evs] == [2, 3, 4]       # oldest evicted
        assert [e["seq"] for e in evs] == [3, 4, 5]     # seq keeps counting

    def test_span_events_mirror_open_stack(self, tmp_path):
        rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
        tr = SpanTracer(process_index=0)
        tr.on_event = rec.record_span
        outer = tr.span("train_batch", step=7).begin()
        inner = tr.span("train_batch/dispatch").begin()
        assert rec.innermost_open_span() == "train_batch/dispatch"
        (stack,) = rec.open_spans().values()
        assert stack == ["train_batch", "train_batch/dispatch"]
        inner.end()
        assert rec.innermost_open_span() == "train_batch"
        outer.end()
        assert rec.open_spans() == {}
        kinds = [e["kind"] for e in rec.snapshot()]
        assert kinds == ["span_begin", "span_begin", "span_end", "span_end"]
        assert rec.snapshot()[0]["step"] == 7

    def test_same_named_nested_spans_pop_by_identity(self, tmp_path):
        rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
        tr = SpanTracer(process_index=0)
        tr.on_event = rec.record_span
        outer = tr.span("retry").begin()
        inner = tr.span("retry").begin()
        inner.end()
        # the name-match pop would have collapsed the outer entry too
        (stack,) = rec.open_spans().values()
        assert stack == ["retry"]
        assert rec.innermost_open_span() == "retry"
        outer.end()
        assert rec.open_spans() == {}

    def test_log_lines_enter_ring(self, tmp_path):
        from deepspeed_tpu.utils.logging import logger as ds_logger

        rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        rec.attach_logging(ds_logger)
        try:
            ds_logger.warning("something went sideways")
        finally:
            rec.detach_logging(ds_logger)
        (ev,) = [e for e in rec.snapshot() if e["kind"] == "log"]
        assert ev["level"] == "WARNING" and "sideways" in ev["message"]


# ---------------------------------------------------------------------------
# crash bundles


class TestCrashBundle:
    def _bundle(self, tmp_path, **kw):
        rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path / "crash"))
        tr = SpanTracer(process_index=0)
        tr.on_event = rec.record_span
        tr.span("train_batch", step=1).begin()
        tr.span("train_batch/dispatch").begin()
        return rec, rec.dump(**kw)

    def test_dump_bundle_contents(self, tmp_path):
        rec, bundle = self._bundle(tmp_path, reason="hang")
        man = json.load(open(os.path.join(bundle, "MANIFEST.json")))
        assert man["reason"] == "hang"
        # stalled span defaults to the innermost open span
        assert man["stalled_span"] == "train_batch/dispatch"
        (stack,) = man["open_spans"].values()
        assert stack == ["train_batch", "train_batch/dispatch"]
        assert man["environment"]["python"]
        events = [json.loads(l) for l in
                  open(os.path.join(bundle, "events.jsonl"))]
        assert [e["kind"] for e in events] == ["span_begin", "span_begin"]
        stacks = open(os.path.join(bundle, "stacks.txt")).read()
        assert "test_flightrecorder" in stacks     # this very test frame
        mem = json.load(open(os.path.join(bundle, "memory.json")))
        assert mem["host_rss_bytes"] > 0
        assert rec.dumps == [bundle]
        assert find_latest_bundle(str(tmp_path / "crash")) == bundle

    def test_dump_records_exception_and_audit_entries(self, tmp_path):
        from tools.tpuaudit.registry import clear_registry, register_entry_point

        try:
            register_entry_point(
                "t/unit", fn=lambda x: x,
                args=(jax.ShapeDtypeStruct((2,), jnp.float32),),
                tags={"engine": "test"})
            rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
            try:
                raise RuntimeError("boom at step 3")
            except RuntimeError as e:
                bundle = rec.dump(reason="exception", exc=e)
            man = json.load(open(os.path.join(bundle, "MANIFEST.json")))
            assert man["exception"]["type"] == "RuntimeError"
            assert "boom at step 3" in man["exception"]["message"]
            names = [e["name"] for e in man["audit_entries"]]
            assert "t/unit" in names
        finally:
            clear_registry()

    def test_dump_never_raises(self, tmp_path):
        rec = FlightRecorder(capacity=4,
                             dump_dir=str(tmp_path / "f" / "MANIFEST.json"))
        # dump_dir collides with a FILE path component -> makedirs fails
        (tmp_path / "f").mkdir()
        (tmp_path / "f" / "MANIFEST.json").write_text("not a dir")
        assert rec.dump(reason="broken") == ""

    def test_report_crash_dump_cli_round_trip(self, tmp_path):
        """Tier-1 smoke: dump a bundle, re-read it through the installed
        CLI in a fresh process (stdlib path — no jax needed to read)."""
        _, bundle = self._bundle(tmp_path, reason="hang",
                                 extra={"waited_s": 12.5, "deadline_s": 5.0})
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.observability", "report",
             "--crash-dump", bundle],
            capture_output=True, text=True, cwd="/root/repo", env=env)
        assert r.returncode == 0, r.stderr
        assert "stalled span: train_batch/dispatch" in r.stdout
        assert "silent for 12.5s" in r.stdout
        assert "== stack digest ==" in r.stdout

    def test_crash_report_in_process(self, tmp_path):
        _, bundle = self._bundle(tmp_path, reason="sigusr1")
        out = crash_report(bundle)
        assert "reason: sigusr1" in out
        assert "train_batch > train_batch/dispatch" in out

    def test_report_main_crash_dump_errors_cleanly(self, tmp_path, capsys):
        assert report_main(["--crash-dump", str(tmp_path)]) == 1
        assert report_main(["--crash-dump"]) == 2

    def test_sigusr1_dumps(self, tmp_path):
        rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
        assert fr_mod.install_sigusr1(rec)
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 2.0
            while not rec.dumps and time.monotonic() < deadline:
                time.sleep(0.01)   # handler runs at a bytecode boundary
        finally:
            fr_mod.uninstall_sigusr1()
        assert rec.dumps
        man = json.load(open(os.path.join(rec.dumps[0], "MANIFEST.json")))
        assert man["reason"] == "sigusr1"


# ---------------------------------------------------------------------------
# hang watchdog (fake clock — no sleeps)


class TestHangWatchdog:
    def test_arm_heartbeat_fire_disarm(self, tmp_path):
        clock = FakeClock()
        rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                             clock=clock)
        reg = MetricsRegistry()
        fired = []
        wd = HangWatchdog(recorder=rec, registry=reg, timeout_factor=2.0,
                          timeout_floor_s=10.0, clock=clock,
                          on_fire=lambda **kw: fired.append(kw))
        assert not wd.check()                      # unarmed
        wd.heartbeat("train_batch/dispatch")
        clock.advance(5.0)
        assert not wd.check()                      # inside the deadline
        wd.heartbeat("train_batch/dispatch")       # heartbeat resets it
        clock.advance(9.0)
        assert not wd.check()
        clock.advance(2.0)                         # 11s silent > 10s floor
        assert wd.check()
        assert wd.fired == 1
        assert fired[0]["stalled_span"] == "train_batch/dispatch"
        assert reg.counter("hang/watchdog_fired").value(
            span="train_batch/dispatch") == 1
        man = json.load(open(os.path.join(fired[0]["bundle"],
                                          "MANIFEST.json")))
        assert man["reason"] == "hang"
        assert man["stalled_span"] == "train_batch/dispatch"
        # fired => disarmed: no repeat dumps for the same stall
        clock.advance(100.0)
        assert not wd.check()
        # a new heartbeat re-arms; disarm() suspends again
        wd.heartbeat("fwd")
        wd.disarm()
        clock.advance(1000.0)
        assert not wd.check()

    def test_deadline_follows_rolling_median(self):
        wd = HangWatchdog(timeout_factor=4.0, timeout_floor_s=1.0,
                          clock=FakeClock())
        assert wd.deadline_s() == 1.0              # floor: no history
        for secs in (2.0, 3.0, 100.0):             # median robust to outlier
            wd.note_step_time(secs)
        assert wd.deadline_s() == pytest.approx(4.0 * 3.0)
        wd2 = HangWatchdog(timeout_factor=2.0, timeout_floor_s=60.0,
                           clock=FakeClock())
        wd2.note_step_time(0.004)                  # fast steps: floor wins
        assert wd2.deadline_s() == 60.0

    def test_abort_uses_injected_exit(self, tmp_path):
        clock = FakeClock()
        codes = []
        wd = HangWatchdog(timeout_factor=2.0, timeout_floor_s=1.0,
                          abort=True, exit_code=113, clock=clock,
                          abort_fn=codes.append)
        wd.heartbeat("step")
        clock.advance(2.0)
        assert wd.check()
        assert codes == [113]

    def test_threaded_stall_detection_end_to_end(self, tmp_path):
        """The acceptance path: an enabled session with the hang watchdog
        on, a span that begins (one heartbeat) and never ends, detection
        within the configured deadline, and a bundle the report CLI parses
        back to the stalled span name."""
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path),
            hang_watchdog=True, hang_timeout_factor=2.0,
            hang_timeout_floor_s=0.05, hang_poll_interval_s=0.01))
        stuck = sess.span("train_batch/dispatch").begin()   # never ends
        deadline = time.monotonic() + 2.0
        while not sess.hang.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sess.hang.fired == 1, "watchdog did not fire in 2s"
        assert sess.hang.last_fire["stalled_span"] == "train_batch/dispatch"
        bundle = sess.hang.last_fire["bundle"]
        out = crash_report(bundle)
        assert "stalled span: train_batch/dispatch" in out
        # the stall landed in the goodput badput buckets too
        assert sess.goodput.totals()["buckets"]["stall"] > 0
        stuck.end()
        reset_session()


# ---------------------------------------------------------------------------
# goodput accounting


class TestGoodput:
    def test_bucket_classification_and_gaps(self):
        reg = MetricsRegistry()
        acc = GoodputAccountant(reg)
        # step 1: h2d 0.1s + dispatch 0.8s inside a 1.0s train_batch
        acc.on_span("begin", "train_batch", t=10.0)
        acc.on_span("end", "train_batch/h2d", t=10.1, dur_s=0.1)
        acc.on_span("end", "train_batch/dispatch", t=10.9, dur_s=0.8)
        acc.on_span("end", "train_batch", t=11.0, dur_s=1.0)
        # 0.5s gap between steps => input_wait (dataloader)
        acc.on_span("begin", "train_batch", t=11.5)
        acc.on_span("end", "train_batch/dispatch", t=12.4, dur_s=0.9)
        acc.on_span("end", "train_batch", t=12.5, dur_s=1.0)
        # a checkpoint after the second step
        acc.on_span("end", "checkpoint/save", t=13.0, dur_s=0.5)
        tot = acc.totals()
        b = tot["buckets"]
        assert tot["steps"] == 2
        assert b["compute"] == pytest.approx(1.7)
        assert b["input_wait"] == pytest.approx(0.6)   # h2d + gap
        assert b["checkpoint"] == pytest.approx(0.5)
        assert tot["wall_s"] == pytest.approx(3.0)
        assert b["other"] == pytest.approx(3.0 - 1.7 - 0.6 - 0.5)
        assert tot["goodput_fraction"] == pytest.approx(1.7 / 3.0)

    def test_compile_seconds_deducted_from_compute(self):
        acc = GoodputAccountant(MetricsRegistry(), clock=FakeClock(0.0))
        acc.on_span("begin", "train_batch", t=0.0)
        # compile attributed to an open COMPUTE span: deducted from the
        # enclosing span's duration so the seconds are not double-counted
        acc.on_compile(3.0, where="train_batch/dispatch")
        acc.on_span("end", "train_batch/dispatch", t=4.0, dur_s=4.0)
        acc.on_span("end", "train_batch", t=4.0, dur_s=4.0)
        b = acc.totals()["buckets"]
        assert b["recompile"] == pytest.approx(3.0)
        assert b["compute"] == pytest.approx(1.0)  # not double-counted
        # compile OUTSIDE any compute span (engine build, warmup): pure
        # badput, no deduction from later compute spans
        acc.on_compile(1.0, where="<untraced>")
        acc.on_span("end", "train_batch/dispatch", t=6.0, dur_s=2.0)
        b = acc.totals()["buckets"]
        assert b["recompile"] == pytest.approx(4.0)
        assert b["compute"] == pytest.approx(3.0)

    def test_gap_does_not_double_count_bucketed_work(self):
        """A checkpoint (or eval, or between-step compile) inside the
        inter-step gap must land in ONE bucket, not checkpoint+input_wait."""
        acc = GoodputAccountant(MetricsRegistry(), clock=FakeClock(0.0))
        acc.on_span("begin", "train_batch", t=0.0)
        acc.on_span("end", "train_batch/dispatch", t=1.0, dur_s=1.0)
        acc.on_span("end", "train_batch", t=1.0, dur_s=1.0)
        # 2s gap holding a 1.2s checkpoint + 0.3s eval: input_wait = 0.5
        acc.on_span("end", "checkpoint/save", t=2.2, dur_s=1.2)
        acc.on_span("end", "eval", t=2.5, dur_s=0.3)
        acc.on_span("begin", "train_batch", t=3.0)
        acc.on_span("end", "train_batch/dispatch", t=4.0, dur_s=1.0)
        acc.on_span("end", "train_batch", t=4.0, dur_s=1.0)
        b = acc.totals()["buckets"]
        assert b["checkpoint"] == pytest.approx(1.2)
        assert b["compute"] == pytest.approx(2.3)   # dispatch + eval
        assert b["input_wait"] == pytest.approx(0.5)
        assert sum(b.values()) == pytest.approx(acc.totals()["wall_s"])

    def test_stall_extends_wall_and_never_double_counts(self):
        clock = FakeClock(0.0)
        acc = GoodputAccountant(MetricsRegistry(), clock=clock)
        acc.on_span("begin", "train_batch", t=0.0)
        # the dispatch wedges for 300 silent seconds; the watchdog fires
        clock.t = 301.0
        acc.on_stall(300.0, where="train_batch/dispatch")
        tot = acc.totals()
        assert tot["wall_s"] == pytest.approx(301.0)   # silence is wall time
        assert tot["buckets"]["stall"] == pytest.approx(300.0)
        # the run RESUMES: the blocked span's duration includes the silence,
        # which must not be re-counted as compute
        acc.on_span("end", "train_batch/dispatch", t=302.0, dur_s=302.0)
        acc.on_span("end", "train_batch", t=302.0, dur_s=302.0)
        b = acc.totals()["buckets"]
        assert b["compute"] == pytest.approx(2.0)
        assert sum(b.values()) == pytest.approx(acc.totals()["wall_s"])
        # a stall BETWEEN steps must not re-count as the next gap
        clock.t = 310.0
        acc.on_stall(8.0, where="train_batch")
        acc.on_span("begin", "train_batch", t=312.0)
        b = acc.totals()["buckets"]
        assert b["input_wait"] == pytest.approx(2.0)   # only the true gap

    def test_mfu_vs_cost_model_peak_on_known_flops_jit(self):
        """MFU math against an XLA-counted FLOPs number: a 64^3 matmul is
        exactly 2*64^3 flops by cost analysis; one synthetic 1-second step
        at that workload must read flops / PEAK_FLOPS[v5e]."""
        sds = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        compiled = jax.jit(lambda a, b: a @ b).lower(sds, sds).compile()
        flops = compiled_cost(compiled)["flops"]
        assert flops == pytest.approx(2 * 64 ** 3)
        reg = MetricsRegistry()
        acc = GoodputAccountant(reg)
        peak = PEAK_FLOPS["v5e"]
        acc.set_workload(tokens_per_step=64, flops_per_step=flops,
                         peak_flops=peak, source="xla")
        acc.on_span("begin", "train_batch", t=100.0)
        acc.on_span("end", "train_batch/dispatch", t=101.0, dur_s=1.0)
        acc.on_span("end", "train_batch", t=101.0, dur_s=1.0)
        tot = acc.publish()
        assert tot["mfu"] == pytest.approx(flops / peak)
        assert tot["tokens_per_sec"] == pytest.approx(64.0)
        assert reg.gauge("goodput/mfu").value() == pytest.approx(flops / peak)
        assert reg.gauge("goodput/seconds").value(
            bucket="compute") == pytest.approx(1.0)

    def test_peak_flops_lookup(self):
        assert peak_flops_for("TPU v5e") == PEAK_FLOPS["v5e"]
        assert peak_flops_for("TPU v5p chip") == PEAK_FLOPS["v5p"]
        assert peak_flops_for(None) == 197e12
        assert peak_flops_for("cpu") == 197e12     # unknown kind => default

    def test_session_routes_compile_and_publish_into_recorder(self, tmp_path):
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path)))
        sess._on_compile(2.0, "train_batch", False)
        assert sess.goodput.totals()["buckets"]["recompile"] == 2.0
        sess.registry.gauge("x").set(1.0)
        sess.registry.publish(step=3)
        kinds = {e["kind"] for e in sess.recorder.snapshot()}
        assert {"compile", "metric_publish"} <= kinds
        reset_session()


# ---------------------------------------------------------------------------
# steady-state recompile -> goodput badput (satellite)


class TestRecompileGoodputFeed:
    def test_steady_state_counter_and_badput_feed(self, tmp_path):
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path), steady_state_step=5))
        wd = sess.watchdog
        wd.note_step(6)
        reg = sess.registry
        # two distinct compiles at one site: first silent, repeat steady
        with sess.span("train_batch"):
            jax.jit(lambda x: x + jnp.float32(41))(
                jnp.ones(3)).block_until_ready()
            jax.jit(lambda x: x + jnp.float32(43))(
                jnp.ones(3)).block_until_ready()
        assert reg.counter("recompile/steady_state").value(
            where="train_batch") >= 1
        assert reg.counter("xla/steady_state_recompiles").value(
            where="train_batch") >= 1
        assert sess.goodput.totals()["buckets"]["recompile"] > 0
        reset_session()


# ---------------------------------------------------------------------------
# engine smoke: goodput on the enabled path, nothing on the disabled path


def _engine(tmp_path, enabled):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "steps_per_print": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "observability": {"enabled": enabled,
                             "output_dir": str(tmp_path / "obs")}}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model(hidden_dim=10),
                                          config=cfg)
    return engine


class TestEngineGoodputSmoke:
    def test_enabled_run_publishes_goodput_and_mfu(self, tmp_path, devices8):
        from deepspeed_tpu.models.simple import random_batches

        engine = _engine(tmp_path, enabled=True)
        obs = engine._obs
        assert obs.recorder is not None and obs.goodput is not None
        batches = random_batches(jax.random.PRNGKey(0), 3,
                                 engine.train_batch_size())
        it = iter(batches)
        for _ in range(3):
            engine.train_batch(data_iter=it)
        reg = obs.registry
        gf = reg.gauge("goodput/goodput_fraction").value()
        assert gf is not None and 0.0 < gf <= 1.0
        assert reg.gauge("goodput/mfu").value() > 0
        assert reg.gauge("goodput/tokens_per_sec").value() > 0
        assert reg.gauge("goodput/seconds").value(bucket="compute") > 0
        assert reg.gauge("goodput/steps").value() == 3
        # the metrics dump carries the goodput gauges for the report CLI
        path = obs.dump_metrics()
        names = {json.loads(l).get("name") for l in open(path)}
        assert "goodput/goodput_fraction" in names and "goodput/mfu" in names
        from deepspeed_tpu.observability.report import report as render

        assert "== goodput ==" in render([path])

    def test_train_batch_exception_dumps_flight_record(self, tmp_path,
                                                       devices8):
        engine = _engine(tmp_path, enabled=True)
        with pytest.raises(Exception):
            # mismatched feature dim => shape error at step trace time,
            # inside the train_batch span
            engine.train_batch(batch={
                "x": jnp.ones((1, engine.train_batch_size(), 99)),
                "y": jnp.ones((1, engine.train_batch_size(), 1))})
        assert engine._obs.recorder.dumps, "no crash bundle written"
        man = json.load(open(os.path.join(engine._obs.recorder.dumps[0],
                                          "MANIFEST.json")))
        assert man["reason"] == "train_batch-exception"
        assert man["exception"]["type"]

    def test_disabled_run_wires_nothing(self, tmp_path):
        engine = _engine(tmp_path, enabled=False)
        obs = engine._obs
        assert obs.recorder is None and obs.hang is None \
            and obs.goodput is None
        assert obs.tracer.on_event is None
        assert obs.registry.on_publish is None


# ---------------------------------------------------------------------------
# bench guard satellite (bench_common.py)


class TestBenchGuard:
    def test_skip_record_carries_failure_kind(self, capsys):
        with pytest.raises(SystemExit) as e:
            bench_common.skip("m", "u", "watchdog expired", "hang")
        assert e.value.code == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["skipped"] is True and rec["failure_kind"] == "hang"
        assert rec["value"] is None and "watchdog expired" in rec["reason"]

    def test_crash_bundle_info_finds_newest(self, tmp_path):
        assert bench_common.crash_bundle_info(None) is None
        assert bench_common.crash_bundle_info(str(tmp_path)) is None
        for name, span, age in (("old", "fwd", 100), ("new", "bwd", 0)):
            d = tmp_path / f"crash-{name}"
            d.mkdir()
            (d / "MANIFEST.json").write_text(
                json.dumps({"stalled_span": span}))
            t = time.time() - age
            os.utime(d, (t, t))
        info = bench_common.crash_bundle_info(str(tmp_path))
        assert info["bundle"].endswith("crash-new")
        assert info["stalled_span"] == "bwd"
        # newer_than rejects bundles left over from a previous round — an
        # old bundle must never be presented as THIS hang's evidence
        assert bench_common.crash_bundle_info(
            str(tmp_path), newer_than=time.time() - 10) is not None
        assert bench_common.crash_bundle_info(
            str(tmp_path), newer_than=time.time() + 10) is None
        # a bundle whose manifest has no open span still reads cleanly
        (tmp_path / "crash-new" / "MANIFEST.json").write_text(
            json.dumps({"stalled_span": None}))
        assert bench_common.crash_bundle_info(
            str(tmp_path))["stalled_span"] == "<none open>"

    def test_real_bug_exit_forwards_child_stdout(self, tmp_path):
        """A child that prints a structured partial record (bench_infer's
        OOM JSON) and exits non-zero with a non-backend error must have that
        stdout forwarded by the parent, not discarded."""
        child = tmp_path / "oom.py"
        child.write_text(
            "import sys\n"
            "print('{\"oom\": true}')\n"
            "sys.stderr.write('RuntimeError: boom\\n')\n"
            "sys.exit(3)\n")
        driver = tmp_path / "driver.py"
        driver.write_text(
            "import sys\n"
            "sys.path.insert(0, '/root/repo')\n"
            "import bench_common\n"
            f"bench_common.run_watchdogged('m', 'u', {str(child)!r})\n")
        r = subprocess.run([sys.executable, str(driver)],
                           capture_output=True, text=True)
        assert r.returncode == 3
        assert '{"oom": true}' in r.stdout
        assert "boom" in r.stderr

    def test_sigusr1_then_kill_collects_dump(self, tmp_path):
        """run_child on a hung script: SIGUSR1 lets the child write its
        black box (here: a SIGUSR1 handler writing a file), SIGKILL follows,
        and the caller sees hung=True."""
        script = tmp_path / "hang.py"
        marker = tmp_path / "dumped.txt"
        script.write_text(
            "import signal, sys, time\n"
            f"f = {str(marker)!r}\n"
            "signal.signal(signal.SIGUSR1,\n"
            "              lambda s, fr: open(f, 'w').write('dump'))\n"
            "print('ready', flush=True)\n"
            "while True:\n"
            "    time.sleep(0.05)\n")
        rc, out, err, hung = bench_common.run_child(
            str(script), timeout_s=1.0, grace_s=2.0)
        assert hung and rc is None
        assert marker.exists() and marker.read_text() == "dump"


# ---------------------------------------------------------------------------
# config gates


class TestConfigGates:
    def test_new_fields_validate(self):
        from deepspeed_tpu.config.base import ConfigError

        cfg = ObservabilityConfig.from_dict({})
        assert cfg.flight_recorder and cfg.goodput
        assert not cfg.hang_watchdog            # thread+abort: opt-in
        for bad in ({"flight_ring_size": 0}, {"hang_timeout_factor": 0},
                    {"hang_timeout_floor_s": 0}, {"hang_poll_interval_s": 0},
                    {"hang_exit_code": 0}, {"hang_exit_code": 300}):
            with pytest.raises(ConfigError):
                ObservabilityConfig.from_dict(bad)

    def test_gates_off_within_enabled_session(self, tmp_path):
        sess = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path),
            flight_recorder=False, goodput=False))
        assert sess.recorder is None and sess.goodput is None
        assert sess.tracer.on_event is None
        reset_session()

    def test_session_replacement_keeps_new_publish_hook(self, tmp_path):
        """The registry is a process singleton: closing the REPLACED session
        must not sever the live session's flight-recorder publish hook."""
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "a")))
        new = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "b")))
        assert get_registry().on_publish == new._on_publish
        new.registry.gauge("g").set(1.0)
        new.registry.publish(step=1)
        assert any(e["kind"] == "metric_publish"
                   for e in new.recorder.snapshot())
        reset_session()
        assert get_registry().on_publish is None

    def test_non_current_session_does_not_steal_hooks(self, tmp_path):
        """configure_observability(..., make_current=False) promises to
        leave the current session alone — including the process-global
        publish hook and the SIGUSR1 recorder pointer."""
        live = configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "live")))
        side = configure_observability(
            ObservabilityConfig(enabled=True, output_dir=str(tmp_path / "s")),
            make_current=False)
        assert get_session() is live
        assert get_registry().on_publish == live._on_publish
        assert fr_mod._ACTIVE_RECORDER is live.recorder
        side.close(export=False)
        assert get_registry().on_publish == live._on_publish
        reset_session()
