"""Regression tests for the concurrency defects tpusync surfaced (ISSUE 18).

Every test here is **sleep-free**: instead of racing real threads against
wall-clock windows, each asserts the *locking invariant itself* at the
mutation site — instrumented locks and container shims record whether the
owning lock was held at write time, and barrier-synchronized threads make
the one genuine race (fault-plan claiming) deterministic.

The defects (each found by ``python -m tools.tpusync``):

* ``HangWatchdog._fire`` published ``last_fire``/``fired`` without the
  watchdog lock — a poller could see ``fired`` bumped with a stale
  ``last_fire``;
* ``FlightRecorder._dump`` appended to ``dumps`` with no lock, reachable
  from the watchdog thread, SIGUSR1 and a crashing trainer at once;
* ``FleetRouter._handoff_from`` mutated router state (request rebind,
  handoff tallies, probation credit) holding only the *engine* lock,
  relying on every engine step being driven from under ``step()``'s
  router lock;
* ``FaultInjector`` claimed plan entries with check-then-add on a bare
  set from three hook threads (session, fleet router, engine driver).
"""

import json
import threading

import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.config import FleetConfig, ServingConfig
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.observability.faultinject import FaultInjector
from deepspeed_tpu.observability.flightrecorder import FlightRecorder
from deepspeed_tpu.observability.hangdetect import HangWatchdog
from deepspeed_tpu.serving.fleet import FleetRouter, build_replicas

SCFG = dict(block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16, max_queue=64)


class OwnerLock:
    """Lock wrapper recording whether it is held (and by whom)."""

    def __init__(self, inner=None):
        self._inner = inner or threading.Lock()
        self.owner = None

    def __enter__(self):
        self._inner.acquire()
        self.owner = threading.current_thread()
        return self

    def __exit__(self, *exc):
        self.owner = None
        self._inner.release()

    def held_by_me(self) -> bool:
        return self.owner is threading.current_thread()


# -- HangWatchdog: fire publication is atomic ------------------------------
class _PublishTrackingWatchdog(HangWatchdog):
    """Records, for each post-init write to the fire-publication fields,
    whether the watchdog lock was held at that exact moment."""

    def __setattr__(self, name, value):
        if name in ("last_fire", "fired") and "_publog" in self.__dict__:
            self._publog.append((name, self._lock.held_by_me()))
        super().__setattr__(name, value)


def test_watchdog_fire_publishes_under_lock():
    t = [0.0]
    wd = _PublishTrackingWatchdog(timeout_floor_s=1.0, clock=lambda: t[0])
    wd._lock = OwnerLock(wd._lock)
    wd._publog = []
    wd.heartbeat("train_batch")
    t[0] = 100.0                      # way past the floor deadline
    assert wd.check() is True
    # both fields written, each under the lock, last_fire first (a poller
    # that sees `fired` bumped must find a complete last_fire)
    assert [(n, held) for n, held in wd._publog] == \
        [("last_fire", True), ("fired", True)]
    assert wd.fired == 1
    assert wd.last_fire["stalled_span"] == "train_batch"
    # second check without a new heartbeat must not re-fire (disarmed)
    assert wd.check() is False
    assert wd.fired == 1


# -- FlightRecorder: bundle list append is locked --------------------------
class _LockAssertingList(list):
    def __init__(self, lock):
        super().__init__()
        self._lock = lock
        self.append_held = []

    def append(self, item):
        # RLock._is_owned: held by the calling thread right now
        self.append_held.append(self._lock._is_owned())
        super().append(item)


def test_flightrecorder_dump_appends_under_lock(tmp_path):
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    rec.dumps = _LockAssertingList(rec._lock)
    rec.record("step", n=1)
    bundle = rec.dump(reason="test")
    assert rec.dumps.append_held == [True]
    assert list(rec.dumps) == [bundle]
    manifest = json.loads(
        (tmp_path / rec.dumps[0].split("/")[-1] / "MANIFEST.json")
        .read_text())
    assert manifest["reason"] == "test"


# -- FaultInjector: exactly-once claims across hook threads ----------------
def test_faultinjector_claim_exactly_once_across_threads():
    plan = [{"kind": "replica_kill", "step": 3, "replica": 1}]
    inj = FaultInjector(plan=plan, rank=0, restart=0)
    n = 8
    barrier = threading.Barrier(n)
    wins = []

    def worker():
        barrier.wait()                 # all contenders claim at once
        wins.append(inj._claim(0))

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert sum(wins) == 1
    assert inj._claim(0) is False      # and stays claimed


def test_faultinjector_router_hooks_note_once():
    plan = [{"kind": "replica_kill", "step": 2, "replica": 0}]
    inj = FaultInjector(plan=plan, rank=0, restart=0)
    kills = []
    barrier = threading.Barrier(2)

    def drive():
        barrier.wait()
        inj.before_router_step(2, kills.append)

    threads = [threading.Thread(target=drive) for _ in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    # the router kill switch is idempotent, but the *note* must be single:
    # the applied log is what chaos tests assert deterministic plans on
    assert len(inj.applied) == 1
    assert inj.applied[0]["kind"] == "replica_kill"


# -- FleetRouter: the handoff hook re-enters the router lock ---------------
class _LockAssertingDict(dict):
    def __init__(self, router):
        super().__init__()
        self._router = router
        self.get_held = []

    def get(self, *a, **kw):
        self.get_held.append(self._router._lock._is_owned())
        return super().get(*a, **kw)


class _FakeReq:
    rid = 999


def test_handoff_from_takes_router_lock():
    engine = init_inference("tiny", dtype=jnp.float32, max_out_tokens=32)
    replicas = build_replicas(engine, ServingConfig(**SCFG), 2)
    router = FleetRouter(replicas, FleetConfig())
    try:
        router._by_engine = _LockAssertingDict(router)
        # direct call, router lock NOT held by the caller — the prefill
        # replica invokes this hook from inside the engine's step with
        # only the ENGINE lock; the hook itself must take the router's
        router._handoff_from(replicas[0], _FakeReq())
        assert router._by_engine.get_held == [True]
    finally:
        router.close()
