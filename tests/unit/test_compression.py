"""Compression suite tests — scheduler offsets, fake-quant STE, pruning
masks, layer reduction (reference tests/unit/compression/test_compression.py
concerns re-expressed over param pytrees)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression import (CompressionScheduler, apply_compression,
                                       init_compression, layer_reduction_init)


CFG = {
    "compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 10},
            "different_groups": {
                "g0": {"params": {"start_bits": 8, "target_bits": 4},
                       "modules": ["attn", "mlp"]}},
        },
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 20},
            "different_groups": {
                "g0": {"params": {"dense_ratio": 0.5}, "modules": ["mlp"]}},
        },
        "layer_reduction": {"enabled": True, "keep_number_layer": 2,
                            "teacher_layer": [0, 3]},
    }
}


def test_plan_and_schedule():
    plan = init_compression(CFG)
    sched = CompressionScheduler(plan)
    assert sched.active_methods(5) == frozenset()
    assert sched.active_methods(10) == {"weight_quantization"}
    assert sched.active_methods(25) == {"weight_quantization",
                                        "sparse_pruning"}
    assert plan.matches("weight_quantization", "layers/attn/wq")
    assert not plan.matches("sparse_pruning", "layers/attn/wq")


def test_fake_quant_straight_through():
    plan = init_compression(CFG)
    params = {"layers": {"attn": {"wq": jnp.linspace(-1, 1, 64).reshape(8, 8)}}}

    def loss(p):
        q = apply_compression(p, plan, frozenset({"weight_quantization"}))
        return jnp.sum(q["layers"]["attn"]["wq"] ** 2)

    q = apply_compression(params, plan, frozenset({"weight_quantization"}))
    w = np.asarray(params["layers"]["attn"]["wq"])
    wq = np.asarray(q["layers"]["attn"]["wq"])
    # 4-bit: few distinct levels, bounded error
    assert len(np.unique(wq)) <= 16
    assert np.abs(wq - w).max() <= np.abs(w).max() / 7 + 1e-6
    # straight-through: grads flow as if identity-ish (non-zero everywhere)
    g = jax.grad(loss)(params)["layers"]["attn"]["wq"]
    assert float(jnp.abs(g).sum()) > 0


def test_sparse_pruning_mask():
    plan = init_compression(CFG)
    params = {"layers": {"mlp": {"w_up": jnp.asarray(
        np.random.RandomState(0).randn(16, 16), jnp.float32)}}}
    out = apply_compression(params, plan, frozenset({"sparse_pruning"}))
    w = np.asarray(out["layers"]["mlp"]["w_up"])
    sparsity = (w == 0).mean()
    assert 0.45 <= sparsity <= 0.55
    # kept entries are the largest-magnitude ones
    orig = np.abs(np.asarray(params["layers"]["mlp"]["w_up"]))
    assert orig[w != 0].min() >= orig[w == 0].max() - 1e-6


def test_inactive_is_identity():
    plan = init_compression(CFG)
    params = {"layers": {"attn": {"wq": jnp.ones((4, 4))}}}
    out = apply_compression(params, plan, frozenset())
    assert out["layers"]["attn"]["wq"] is params["layers"]["attn"]["wq"]


def test_layer_reduction():
    from deepspeed_tpu.models import create_model

    model = create_model("tiny", dtype=jnp.float32, num_layers=4)
    params = model.init(jax.random.PRNGKey(0))
    student = layer_reduction_init(params, [0, 3])
    assert student["layers"]["attn"]["wq"].shape[0] == 2
    np.testing.assert_allclose(np.asarray(student["layers"]["attn"]["wq"][1]),
                               np.asarray(params["layers"]["attn"]["wq"][3]))
    np.testing.assert_allclose(np.asarray(student["embed"]["tokens"]),
                               np.asarray(params["embed"]["tokens"]))


def test_head_pruning():
    cfg = {"compression_training": {
        "head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "g0": {"params": {"dense_ratio": 0.5, "num_heads": 4},
                       "modules": ["attn"]}}}}}
    plan = init_compression(cfg)
    w = jnp.asarray(np.random.RandomState(0).randn(16, 32), jnp.float32)
    params = {"layers": {"attn": {"wq": w}}}
    out = apply_compression(params, plan, frozenset({"head_pruning"}))
    wq = np.asarray(out["layers"]["attn"]["wq"]).reshape(16, 4, 8)
    head_zero = (wq == 0).all(axis=(0, 2))
    assert head_zero.sum() == 2  # half the heads pruned whole


def test_activation_quantization_rejected():
    import pytest

    with pytest.raises(NotImplementedError, match="activation_quantization"):
        init_compression({"compression_training": {
            "activation_quantization": {
                "shared_parameters": {"enabled": True}}}})
