"""Compression suite tests — scheduler offsets, fake-quant STE, pruning
masks, layer reduction (reference tests/unit/compression/test_compression.py
concerns re-expressed over param pytrees)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression import (CompressionScheduler, apply_compression,
                                       init_compression, layer_reduction_init)


CFG = {
    "compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 10},
            "different_groups": {
                "g0": {"params": {"start_bits": 8, "target_bits": 4},
                       "modules": ["attn", "mlp"]}},
        },
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 20},
            "different_groups": {
                "g0": {"params": {"dense_ratio": 0.5}, "modules": ["mlp"]}},
        },
        "layer_reduction": {"enabled": True, "keep_number_layer": 2,
                            "teacher_layer": [0, 3]},
    }
}


def test_plan_and_schedule():
    plan = init_compression(CFG)
    sched = CompressionScheduler(plan)
    assert sched.active_methods(5) == frozenset()
    assert sched.active_methods(10) == {"weight_quantization"}
    assert sched.active_methods(25) == {"weight_quantization",
                                        "sparse_pruning"}
    assert plan.matches("weight_quantization", "layers/attn/wq")
    assert not plan.matches("sparse_pruning", "layers/attn/wq")


def test_fake_quant_straight_through():
    plan = init_compression(CFG)
    # stacked (L, in, out) layer leaf — quantized with PER-LAYER scales
    # (the reference quantizes each module separately; per-layer scales
    # also make the transform block-streaming-invariant)
    w0 = jnp.linspace(-1, 1, 64).reshape(8, 8)
    params = {"layers": {"attn": {"wq": jnp.stack([w0, 3.0 * w0])}}}

    def loss(p):
        q = apply_compression(p, plan, frozenset({"weight_quantization"}))
        return jnp.sum(q["layers"]["attn"]["wq"] ** 2)

    q = apply_compression(params, plan, frozenset({"weight_quantization"}))
    w = np.asarray(params["layers"]["attn"]["wq"])
    wq = np.asarray(q["layers"]["attn"]["wq"])
    # 4-bit: few distinct levels PER LAYER, bounded error per layer
    for li in range(2):
        assert len(np.unique(wq[li])) <= 16
        assert (np.abs(wq[li] - w[li]).max()
                <= np.abs(w[li]).max() / 7 + 1e-6)
    # per-layer scales: layer 1 (3x magnitude) uses 3x the step size
    np.testing.assert_allclose(wq[1], 3.0 * wq[0], rtol=1e-6)
    # straight-through: grads flow as if identity-ish (non-zero everywhere)
    g = jax.grad(loss)(params)["layers"]["attn"]["wq"]
    assert float(jnp.abs(g).sum()) > 0
    # stacked biases under layers/ are never quantized (reference scope)
    bias_tree = {"layers": {"attn": {"bq": jnp.ones((4, 8))}}}
    out = apply_compression(bias_tree, plan,
                            frozenset({"weight_quantization"}))
    assert out["layers"]["attn"]["bq"] is bias_tree["layers"]["attn"]["bq"]


def test_sparse_pruning_mask():
    plan = init_compression(CFG)
    params = {"layers": {"mlp": {"w_up": jnp.asarray(
        np.random.RandomState(0).randn(16, 16), jnp.float32)}}}
    out = apply_compression(params, plan, frozenset({"sparse_pruning"}))
    w = np.asarray(out["layers"]["mlp"]["w_up"])
    sparsity = (w == 0).mean()
    assert 0.45 <= sparsity <= 0.55
    # kept entries are the largest-magnitude ones
    orig = np.abs(np.asarray(params["layers"]["mlp"]["w_up"]))
    assert orig[w != 0].min() >= orig[w == 0].max() - 1e-6


def test_inactive_is_identity():
    plan = init_compression(CFG)
    params = {"layers": {"attn": {"wq": jnp.ones((4, 4))}}}
    out = apply_compression(params, plan, frozenset())
    assert out["layers"]["attn"]["wq"] is params["layers"]["attn"]["wq"]


def test_layer_reduction():
    from deepspeed_tpu.models import create_model

    model = create_model("tiny", dtype=jnp.float32, num_layers=4)
    params = model.init(jax.random.PRNGKey(0))
    student = layer_reduction_init(params, [0, 3])
    assert student["layers"]["attn"]["wq"].shape[0] == 2
    np.testing.assert_allclose(np.asarray(student["layers"]["attn"]["wq"][1]),
                               np.asarray(params["layers"]["attn"]["wq"][3]))
    np.testing.assert_allclose(np.asarray(student["embed"]["tokens"]),
                               np.asarray(params["embed"]["tokens"]))


def test_head_pruning():
    cfg = {"compression_training": {
        "head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "g0": {"params": {"dense_ratio": 0.5, "num_heads": 4},
                       "modules": ["attn"]}}}}}
    plan = init_compression(cfg)
    w = jnp.asarray(np.random.RandomState(0).randn(16, 32), jnp.float32)
    params = {"layers": {"attn": {"wq": w}}}
    out = apply_compression(params, plan, frozenset({"head_pruning"}))
    wq = np.asarray(out["layers"]["attn"]["wq"]).reshape(16, 4, 8)
    head_zero = (wq == 0).all(axis=(0, 2))
    assert head_zero.sum() == 2  # half the heads pruned whole


CHANNEL_CFG = {"compression_training": {
    "channel_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 5},
        "different_groups": {
            "g0": {"params": {"dense_ratio": 0.5, "method": "l1"},
                   "modules": ["conv"]}}}}}


def test_channel_pruning_mask():
    """Reference channel pruning (constants.py:160, basic_layer.py:461):
    whole OUTPUT channels of conv kernels pruned by L1 norm — our HWIO
    layout puts channels on the last axis. Dense (2D) weights are never
    channel-pruned, matching the reference's Conv2d-only scope."""
    plan = init_compression(CHANNEL_CFG)
    rng = np.random.RandomState(0)
    params = {"conv1": {"w": jnp.asarray(rng.randn(3, 3, 8, 16), jnp.float32)},
              "conv_proj": {"w": jnp.asarray(rng.randn(16, 16), jnp.float32)}}
    out = apply_compression(params, plan, frozenset({"channel_pruning"}))
    w = np.asarray(out["conv1"]["w"])
    chan_zero = (w == 0).all(axis=(0, 1, 2))
    assert chan_zero.sum() == 8                       # half the channels gone
    # surviving channels untouched
    orig = np.asarray(params["conv1"]["w"])
    np.testing.assert_array_equal(w[..., ~chan_zero], orig[..., ~chan_zero])
    # kept channels are the largest by L1
    l1 = np.abs(orig).sum(axis=(0, 1, 2))
    assert l1[~chan_zero].min() >= l1[chan_zero].max()
    # 2D (non-conv) weight untouched even though the module regex matches
    np.testing.assert_array_equal(np.asarray(out["conv_proj"]["w"]),
                                  np.asarray(params["conv_proj"]["w"]))


def test_channel_pruning_schedule_and_topk_rejected():
    import pytest

    from deepspeed_tpu.compression import CompressionScheduler

    plan = init_compression(CHANNEL_CFG)
    sched = CompressionScheduler(plan)
    assert sched.active_methods(0) == frozenset()
    assert sched.active_methods(5) == {"channel_pruning"}
    bad = {"compression_training": {"channel_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {"g0": {
            "params": {"dense_ratio": 0.5, "method": "topk"},
            "modules": ["conv"]}}}}}
    with pytest.raises(NotImplementedError, match="topk"):
        apply_compression(
            {"conv": {"w": jnp.ones((3, 3, 4, 8))}},
            init_compression(bad), frozenset({"channel_pruning"}))


def test_channel_pruning_composes_with_qat():
    """channel_pruning + weight_quantization on the same conv leaf: the
    kept channels carry fake-quantized values, pruned channels stay zero."""
    cfg = {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"g0": {"params": {"target_bits": 4},
                                        "modules": ["conv"]}}},
        "channel_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"g0": {"params": {"dense_ratio": 0.5},
                                        "modules": ["conv"]}}}}}
    plan = init_compression(cfg)
    w0 = jnp.asarray(np.random.RandomState(1).randn(3, 3, 4, 8), jnp.float32)
    params = {"conv": {"w": w0}}
    both = apply_compression(params, plan,
                             frozenset({"weight_quantization",
                                        "channel_pruning"}))
    qonly = apply_compression(params, plan,
                              frozenset({"weight_quantization"}))
    wb = np.asarray(both["conv"]["w"])
    chan_zero = (wb == 0).all(axis=(0, 1, 2))
    assert chan_zero.sum() == 4
    # grads flow straight-through the composition to surviving channels
    g = jax.grad(lambda p: jnp.sum(apply_compression(
        p, plan, frozenset({"weight_quantization", "channel_pruning"})
    )["conv"]["w"] ** 2))(params)["conv"]["w"]
    assert np.isfinite(np.asarray(g)).all() and np.abs(np.asarray(g)).max() > 0
    # kept channels equal the quantize-only values (pruning masks AFTER
    # quantization, reference fix_channel_pruning order)
    np.testing.assert_allclose(wb[..., ~chan_zero],
                               np.asarray(qonly["conv"]["w"])[..., ~chan_zero])


@__import__('pytest').mark.slow
def test_channel_pruning_engine_trajectory():
    """Engine integration: a conv model trains under a scheduled
    channel_pruning config; after the schedule offset the effective conv
    weights are channel-sparse and the loss keeps improving."""
    import deepspeed_tpu
    from deepspeed_tpu.models.core import Model

    rng = np.random.RandomState(2)
    x_np = rng.randn(8, 8, 8, 4).astype(np.float32)
    y_np = rng.randn(8, 8, 8, 8).astype(np.float32)

    def init(key):
        k1, k2 = jax.random.split(key)
        return {"conv": {"w": jax.random.normal(k1, (3, 3, 4, 8)) * 0.3},
                "out": {"w": jax.random.normal(k2, (8, 8)) * 0.3}}

    def apply_fn(params, batch):
        from deepspeed_tpu.models.spatial import conv2d

        h = conv2d(batch["x"], params["conv"]["w"])
        return jnp.einsum("bhwc,cd->bhwd", jax.nn.relu(h),
                          params["out"]["w"]), None

    def loss_fn(params, batch):
        pred, _ = apply_fn(params, batch)
        return jnp.mean((pred - batch["y"]) ** 2)

    model = Model(init=init, apply=apply_fn, loss_fn=loss_fn,
                  axes={"conv": {"w": None}, "out": {"w": None}})
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 8,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "compression_training": {
            "channel_pruning": {
                "shared_parameters": {"enabled": True, "schedule_offset": 3},
                "different_groups": {"g0": {
                    "params": {"dense_ratio": 0.5}, "modules": ["conv"]}}}},
    })
    batch = {"x": jnp.asarray(x_np)[None], "y": jnp.asarray(y_np)[None]}
    losses = [float(engine.train_batch(batch={**batch})) for _ in range(10)]
    assert losses[-1] < losses[0]
    # the EFFECTIVE (compressed) weights are channel-sparse post-offset
    from deepspeed_tpu.compression import apply_compression as ac

    eff = ac(engine.params, engine._compression_plan,
             engine._compression_active)
    chan_zero = (np.asarray(eff["conv"]["w"]) == 0).all(axis=(0, 1, 2))
    assert chan_zero.sum() == 4


@__import__('pytest').mark.slow
def test_activation_quantization_forward():
    """Activation QAT (reference QuantAct): cfg.act_quant_bits fake-quants
    layer-input activations with straight-through gradients."""
    import dataclasses

    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  build_model, forward)

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=2, max_seq_len=16)
    model = build_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
    base = forward(params, ids, cfg)[0]
    qcfg = dataclasses.replace(cfg, act_quant_bits=4)
    quant = forward(params, ids, qcfg)[0]
    # quantization changes the forward...
    assert np.abs(np.asarray(base - quant)).max() > 1e-5
    # ...but not catastrophically (4-bit activations, tiny model)
    cos = float((base.ravel() @ quant.ravel()) /
                (jnp.linalg.norm(base) * jnp.linalg.norm(quant)))
    assert cos > 0.8, cos
    # straight-through: gradients flow and are finite
    g = jax.grad(lambda p: forward(p, ids, qcfg)[0].astype(
        jnp.float32).sum())(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


@__import__('pytest').mark.slow
def test_activation_quantization_schedule_drives_config():
    """The engine flips model.config.act_quant_bits when the schedule
    activates activation_quantization."""
    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    model = create_model("tiny")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "compression_training": {
            "activation_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2},
                "different_groups": {
                    "g0": {"params": {"bits": 8}, "modules": ["*"]}}}}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 16, 16))
    assert engine.model.config.act_quant_bits == 0
    losses = [float(engine.train_batch(batch={"input_ids": ids}))
              for _ in range(4)]
    assert engine.model.config.act_quant_bits == 8   # activated at step 2
    assert all(np.isfinite(losses))


@__import__('pytest').mark.slow
def test_eval_sees_compression_boundary():
    """ADVICE r3: eval must evaluate the COMPRESSED module after a schedule
    boundary, like the reference (and like the train step, which
    re-specialises at every boundary) — not a stale pre-boundary trace."""
    import deepspeed_tpu
    from deepspeed_tpu.compression import apply_compression
    from deepspeed_tpu.models import create_model

    model = create_model("tiny")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 0.0}},
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2},
                "different_groups": {
                    "g0": {"params": {"start_bits": 3, "target_bits": 3},
                           "modules": ["layers"]}}}}})
    ids = np.random.RandomState(0).randint(0, 256, (1, 16, 16))
    mb = {"input_ids": ids[0]}
    ev_before = float(engine.eval_loss(mb))      # caches the eval step
    for _ in range(4):
        engine.train_batch(batch={"input_ids": ids})  # crosses offset=2
    assert "weight_quantization" in engine._compression_active
    ev_after = float(engine.eval_loss(mb))
    # oracle: eval loss on the explicitly compressed params (lr=0 so the
    # raw params never moved — any difference is the quantization)
    want = float(engine.model.eval_loss_fn(
        apply_compression(engine.params, engine._compression_plan,
                          engine._compression_active,
                          handled_elsewhere=frozenset(
                              {"activation_quantization"})), mb))
    assert abs(ev_after - want) < 1e-5
    assert abs(ev_after - ev_before) > 1e-6      # 3-bit quant moved the loss


@__import__('pytest').mark.slow
def test_moq_eigenvalue_layer_bits():
    """MoQ: the weight-quantization schedule responds to per-layer Hessian
    eigenvalues — sensitive layers hold higher bits longer (reference
    engine.py:1479)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import create_model

    model = create_model("tiny")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {
                    "enabled": True, "schedule_offset": 0,
                    "eigenvalue": {"enabled": True, "eval_step": 2,
                                   "ramp_steps": 4, "max_iter": 4}},
                "different_groups": {
                    "g0": {"params": {"start_bits": 8, "target_bits": 4},
                           "modules": ["layers"]}}}}})
    assert engine._moq_eigenvalue is not None
    ids = np.random.RandomState(0).randint(0, 256, (1, 16, 16))
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids})
    wq = engine._compression_plan.methods["weight_quantization"]
    bits_early = wq.get("layer_bits")
    assert bits_early is not None and len(bits_early) == 2
    assert all(4 <= b <= 8 for b in bits_early)
    for _ in range(6):
        engine.train_batch(batch={"input_ids": ids})
    bits_late = wq["layer_bits"]
    # the schedule progressed: bits are non-increasing, and by step 9 (>
    # rel_max * ramp: rel < L = 2, ramp 4) EVERY layer reaches target —
    # sensitive layers quantize later, never "never"
    assert all(b2 <= b1 for b1, b2 in zip(bits_early, bits_late))
    assert bits_late == (4, 4), bits_late
