"""Engine correctness — analog of reference tests/unit/runtime/zero/test_zero.py
(ZeRO vs DDP equivalence), test_ds_initialize.py, and checkpoint tests.

The gold standard: every ZeRO stage must produce the SAME training trajectory
as plain single-replica training (the sharding plan changes where tensors live,
never the math)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import create_model, random_token_batches, simple_model
from deepspeed_tpu.models.simple import random_batches


def _make_engine(zero_stage=0, dtype_cfg=None, gas=1, model=None, clip=0.0,
                 extra=None):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": gas,
           "steps_per_print": 100,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": zero_stage},
           "gradient_clipping": clip}
    if dtype_cfg:
        cfg.update(dtype_cfg)
    if extra:
        cfg.update(extra)
    model = model or simple_model(hidden_dim=10)
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def _fixed_batches(engine, n=5):
    rng = jax.random.PRNGKey(42)
    return random_batches(rng, n, engine.train_batch_size() //
                          engine.gradient_accumulation_steps())


def _trajectory(zero_stage, gas=1, clip=0.0, steps=5):
    engine = _make_engine(zero_stage=zero_stage, gas=gas, clip=clip)
    batches = _fixed_batches(engine, steps * gas)
    losses = []
    it = iter(batches)
    for _ in range(steps):
        losses.append(float(engine.train_batch(data_iter=it)))
    final = jax.tree.map(lambda p: np.asarray(jax.device_get(p)), engine.params)
    return losses, final


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_stage0(stage):
    l0, p0 = _trajectory(0)
    ls, ps = _trajectory(stage)
    np.testing.assert_allclose(l0, ls, rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), p0, ps)


@pytest.mark.slow
def test_gradient_accumulation_equivalence():
    """gas=2 with micro_batch b must equal gas=1 with batch 2b (same samples) —
    the reference's GAS contract."""
    l1, p1 = _trajectory(0, gas=1, steps=4)
    # same data split into twice as many microbatches
    engine = _make_engine(zero_stage=0, gas=2)
    batches = _fixed_batches(engine, 100)
    # gas=1 trajectory consumed batches of size train_batch; rebuild identical
    # global batches: interleave halves
    eng1 = _make_engine(zero_stage=0, gas=1)
    big = _fixed_batches(eng1, 4)
    losses2 = []
    for b in big:
        half = b["x"].shape[0] // 2
        micro = [{k: v[:half] for k, v in b.items()},
                 {k: v[half:] for k, v in b.items()}]
        losses2.append(float(engine.train_batch(data_iter=iter(micro))))
    np.testing.assert_allclose(l1, losses2, rtol=1e-5)


def test_gradient_clipping_changes_updates():
    l_unclipped, p_unclipped = _trajectory(0, clip=0.0)
    l_clipped, p_clipped = _trajectory(0, clip=1e-3)
    diffs = jax.tree.map(lambda a, b: float(np.abs(a - b).max()),
                         p_unclipped, p_clipped)
    assert max(jax.tree.leaves(diffs)) > 1e-6


def test_bf16_training_runs():
    engine = _make_engine(zero_stage=2, dtype_cfg={"bf16": {"enabled": True}})
    assert engine.compute_dtype == jnp.bfloat16
    assert engine.opt_state.master is not None
    batches = _fixed_batches(engine, 6)
    it = iter(batches)
    losses = [float(engine.train_batch(data_iter=it)) for _ in range(6)]
    assert all(np.isfinite(losses))


def test_fp16_overflow_skips_step():
    engine = _make_engine(zero_stage=0, dtype_cfg={"fp16": {"enabled": True,
                                                            "initial_scale_power": 4,
                                                            "hysteresis": 1}})
    params_before = jax.tree.map(np.asarray, jax.device_get(engine.params))
    # poison batch -> inf loss -> overflow -> skipped update, halved scale
    gb = engine.train_batch_size()
    bad = {"x": jnp.full((gb, 10), 1e30), "y": jnp.zeros((gb, 1))}
    scale0 = engine.cur_scale
    engine.train_batch(batch=jax.tree.map(lambda x: x[None], bad))
    assert engine.skipped_steps == 1
    assert engine.cur_scale == scale0 / 2
    params_after = jax.tree.map(np.asarray, jax.device_get(engine.params))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 params_before, params_after)


@pytest.mark.slow
def test_forward_backward_step_api_matches_train_batch():
    """The reference three-call protocol must produce the same params as the
    fused train_batch path."""
    e1 = _make_engine(zero_stage=0, gas=2)
    e2 = _make_engine(zero_stage=0, gas=2)
    batches = _fixed_batches(e1, 2)  # 2 microbatches = 1 global step
    e1.train_batch(data_iter=iter(batches))

    for mb in batches:
        loss = e2.forward(mb)
        e2.backward(loss)
    assert e2.is_gradient_accumulation_boundary()
    e2.step()
    assert e2.global_steps == 1
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6),
        jax.device_get(e1.params), jax.device_get(e2.params))


@pytest.mark.slow
def test_transformer_zero3_trains():
    model = create_model("tiny")
    engine = _make_engine(zero_stage=3, model=model,
                          dtype_cfg={"bf16": {"enabled": True}})
    batches = random_token_batches(jax.random.PRNGKey(0), 8,
                                   engine.train_batch_size(), 16,
                                   model.config.vocab_size)
    # train on one repeated batch: loss must fall
    fixed = batches[0]
    losses = [float(engine.train_batch(batch=jax.tree.map(lambda x: x[None], fixed)))
              for _ in range(8)]
    assert losses[-1] < losses[0]


def test_checkpoint_roundtrip(tmp_path):
    engine = _make_engine(zero_stage=2)
    batches = _fixed_batches(engine, 4)
    it = iter(batches)
    for _ in range(2):
        engine.train_batch(data_iter=it)
    engine.save_checkpoint(str(tmp_path), tag="step2")
    assert (tmp_path / "latest").read_text() == "step2"

    loss_next = float(engine.train_batch(data_iter=it))
    params_after3 = jax.tree.map(np.asarray, jax.device_get(engine.params))

    # fresh engine restores and replays the same step
    e2 = _make_engine(zero_stage=2)
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 2
    it2 = iter(batches)
    next(it2), next(it2)  # skip consumed
    loss_next2 = float(e2.train_batch(data_iter=it2))
    assert loss_next2 == pytest.approx(loss_next, rel=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, np.asarray(b), atol=1e-6),
                 params_after3, jax.device_get(e2.params))


def test_checkpoint_reshard_across_zero_stages(tmp_path):
    """Universal-checkpoint property: save under ZeRO-3, load under ZeRO-0."""
    e3 = _make_engine(zero_stage=3)
    batches = _fixed_batches(e3, 2)
    e3.train_batch(data_iter=iter(batches))
    e3.save_checkpoint(str(tmp_path), tag="x")
    e0 = _make_engine(zero_stage=0)
    e0.load_checkpoint(str(tmp_path))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)), atol=1e-7),
        e3.params, e0.params)


def test_save_16bit_model(tmp_path):
    from deepspeed_tpu.runtime.checkpoint import load_flat_weights

    engine = _make_engine(zero_stage=3, dtype_cfg={"bf16": {"enabled": True}})
    path = engine.save_16bit_model(str(tmp_path))
    flat = load_flat_weights(path)
    assert len(flat) == len(jax.tree.leaves(engine.params))
    key = [k for k in flat if "head" in k and "w" in k][0]
    assert flat[key].dtype == jnp.bfloat16


def test_dataloader():
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = [{"x": np.full((3,), i, np.float32)} for i in range(10)]
    dl = DeepSpeedDataLoader(data, batch_size=4, shuffle=False)
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (4, 3)
    np.testing.assert_array_equal(batches[0]["x"][:, 0], [0, 1, 2, 3])
    # shuffled epochs differ
    dl2 = DeepSpeedDataLoader(data, batch_size=4, shuffle=True, seed=1)
    e1 = [b["x"][:, 0].tolist() for b in dl2]
    e2 = [b["x"][:, 0].tolist() for b in dl2]
    assert e1 != e2


@pytest.mark.slow
def test_curriculum_seqlen_truncates(tmp_path):
    from deepspeed_tpu.models import create_model

    model = create_model("tiny", dtype=jnp.float32, max_seq_len=64)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "curriculum_learning": {
                    "enabled": True, "curriculum_type": "seqlen",
                    "min_difficulty": 8, "max_difficulty": 32,
                    "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": 4,
                                        "difficulty_step": 8}}})
    gb = engine.train_batch_size()
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, gb, 32), 0, 250)
    for _ in range(6):
        loss = engine.train_batch(batch={"input_ids": ids})
        assert np.isfinite(float(loss))
    assert engine._curriculum.current_difficulty == 32


@pytest.mark.slow
def test_compression_schedule_kicks_in():
    from deepspeed_tpu.models import create_model

    model = create_model("tiny", dtype=jnp.float32)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "compression_training": {
                    "weight_quantization": {
                        "shared_parameters": {"enabled": True,
                                              "schedule_offset": 3},
                        "different_groups": {
                            "g0": {"params": {"target_bits": 8},
                                   "modules": ["attn", "mlp"]}}}}})
    gb = engine.train_batch_size()
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, gb, 16), 0, 250)
    losses = [float(engine.train_batch(batch={"input_ids": ids}))
              for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert engine._compression_active == {"weight_quantization"}


def test_flops_profile_accessor():
    from deepspeed_tpu.models import create_model

    model = create_model("tiny", dtype=jnp.float32)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
    out = engine.get_flops_profile()
    assert "attention" in out["table"]
    assert out["profile"].total_params > 0
