"""Closed-loop telemetry tests — the metric time-series store
(``observability/timeseries.py``) and the live-signal serving autotuner
(``autotuning/livetuner.py``).

Three layers, matching the subsystem's own:

* the store in isolation — bounded rings, derived stats, pattern queries,
  predecessor adoption (the soft-restart survival path), JSONL export;
* the controller on a FAKE clock — synthetic burn signals drive the full
  state machine (propose → hold → judge → keep/rollback → cooldown →
  relax) with no engine, no device, no wall time;
* the contract end-to-end on the tiny model — a fleet serving with the
  tuner ON produces token streams bit-identical to the untuned solo
  oracle (the jit-cache discipline: every online knob is data-only) with
  zero steady-state recompiles, and a disabled session wires nothing —
  no store, no controller.
"""

import json
import os
import types

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.autotuning.livetuner import (LiveTuner,
                                                RECOMMENDATIONS_FORMAT,
                                                maybe_make_tuner)
from deepspeed_tpu.config.config import (ConfigError, FleetConfig,
                                         ObservabilityConfig, ServingConfig,
                                         TuneConfig)
from deepspeed_tpu.observability import (configure_observability,
                                         get_registry, get_session,
                                         reset_session)
from deepspeed_tpu.observability.metrics import MetricsRegistry
from deepspeed_tpu.observability.timeseries import (TimeSeriesStore,
                                                    series_stats)


@pytest.fixture(autouse=True)
def _obs_isolation():
    reset_session()
    get_registry().reset()
    yield
    reset_session()
    get_registry().reset()


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


class TestTimeSeriesStore:
    def test_ring_bounded_per_series(self):
        st = TimeSeriesStore(capacity=4)
        for i in range(10):
            st.observe("a", float(i), step=i)
        assert st.window("a") == [(6, 6.0), (7, 7.0), (8, 8.0), (9, 9.0)]
        assert st.points_total == 10     # appends counted, drops not deducted

    def test_max_series_cap_counts_overflow(self):
        st = TimeSeriesStore(max_series=2)
        st.observe("a", 1.0)
        st.observe("b", 1.0)
        st.observe("c", 1.0)             # refused, counted
        st.observe("a", 2.0)             # existing series still ingests
        assert sorted(st.names()) == ["a", "b"]
        assert st.dropped_series == 1
        assert st.latest("a") == 2.0

    def test_series_stats(self):
        pts = [(i, float(v)) for i, v in enumerate([1, 2, 3, 4])]
        s = series_stats(pts, ewma_alpha=0.5)
        assert s["n"] == 4 and s["last"] == 4.0 and s["mean"] == 2.5
        assert s["min"] == 1.0 and s["max"] == 4.0
        assert s["slope"] == pytest.approx(1.0)      # perfectly linear
        assert s["first_step"] == 0 and s["last_step"] == 3
        assert series_stats([]) == {"n": 0}
        # window restricts to the newest points
        assert series_stats(pts, window=2)["mean"] == 3.5

    def test_query_patterns_match_flattened_labels(self):
        st = TimeSeriesStore()
        st.observe("serve_goodput/ttft_slo_burn_rate/replica=0", 1.0)
        st.observe("serve_goodput/ttft_slo_burn_rate/replica=1", 2.0)
        st.observe("serving/queue_depth", 3.0)
        assert len(st.query("serve_goodput/ttft_slo_burn_rate*")) == 2
        assert list(st.query("*replica=1*")) == [
            "serve_goodput/ttft_slo_burn_rate/replica=1"]
        sts = st.stats_matching("*burn*")
        assert {s["last"] for s in sts.values()} == {1.0, 2.0}

    def test_ingest_batch_uses_event_step(self):
        st = TimeSeriesStore()
        st.ingest(7, [("a", 1.0, 5), ("b", 2.0, None)])
        assert st.window("a") == [(5, 1.0)]
        assert st.window("b") == [(7, 2.0)]      # falls back to batch step
        assert st.ingests == 1

    def test_adopt_prepends_history_and_carries_counters(self):
        old = TimeSeriesStore(capacity=8)
        for i in range(3):
            old.observe("a", float(i), step=i)
        new = TimeSeriesStore(capacity=8)
        new.observe("a", 99.0, step=10)
        new.adopt(old)
        pts = new.window("a")
        assert pts == [(0, 0.0), (1, 1.0), (2, 2.0), (10, 99.0)]
        assert new.points_total == 4     # 3 adopted + 1 own

    def test_export_jsonl_round_trip(self, tmp_path):
        st = TimeSeriesStore()
        st.observe("a", 1.5, step=2)
        path = st.export_jsonl(str(tmp_path / "ts.jsonl"))
        with open(path) as fh:
            recs = [json.loads(l) for l in fh if l.strip()]
        assert recs[0]["type"] == "timeseries_meta" and recs[0]["series"] == 1
        assert recs[1] == {"type": "timeseries", "name": "a",
                           "points": [[2, 1.5]]}

    def test_publish_self_gauges(self):
        st = TimeSeriesStore()
        st.observe("a", 1.0)
        reg = MetricsRegistry()
        st.publish_self(reg)
        snap = {name: v for name, v, _ in reg.publish(0)}
        assert snap["timeseries/series"] == 1
        assert snap["timeseries/points_total"] == 1
        assert "timeseries/dropped_series" not in snap   # only when nonzero

    def test_config_validation(self):
        with pytest.raises(ConfigError):
            TuneConfig(store_capacity=1).validate()
        with pytest.raises(ConfigError):
            TuneConfig(knobs=["bogus"]).validate()
        TuneConfig().validate()


# ---------------------------------------------------------------------------
# the controller, fake clock
# ---------------------------------------------------------------------------


class FakeEngine:
    """The attribute surface the tuner touches on a ServingEngine — no
    device, no scheduler."""

    def __init__(self, drafter=None):
        self._drafter = drafter
        self.spec_suspended = False
        self.prefill_chunks_per_iter = 1
        self._serve_acct = None


TC = dict(enabled=True, controller=True, interval_iterations=4,
          hold_iterations=8)


def mk_tuner(target=None, **over):
    cfg = TuneConfig(**dict(TC, **over))
    cfg.validate()
    store = TimeSeriesStore()
    eng = target if target is not None else FakeEngine()
    tu = LiveTuner(eng, store=store, config=cfg,
                   registry=MetricsRegistry())
    return tu, eng, store


def feed(store, step, ttft=0.0, tpot=0.0, goodput=1.0):
    store.observe("serve_goodput/ttft_slo_burn_rate", ttft, step)
    store.observe("serve_goodput/tpot_slo_burn_rate", tpot, step)
    store.observe("serve_goodput/goodput_fraction", goodput, step)


def run(tu, store, n, start, **sig):
    """Advance the fake clock n iterations, feeding one signal point per
    iteration (so EWMA windows track the regime change)."""
    for it in range(start, start + n):
        feed(store, it, **sig)
        tu.on_iteration(it)
    return start + n


class TestControllerFakeClock:
    def test_off_cadence_is_a_noop(self):
        tu, _, store = mk_tuner()
        for it in range(1, 4):           # below interval_iterations=4
            feed(store, it, ttft=5.0)
            tu.on_iteration(it)
        assert tu._last_objective is None
        assert tu._pending is None and not tu.decisions

    def test_ttft_pressure_walks_chunk_budget_to_max(self):
        tu, eng, store = mk_tuner()
        run(tu, store, 120, 1, ttft=2.0, goodput=0.5)
        assert eng.prefill_chunks_per_iter == 4      # _ChunkBudgetKnob.MAX
        rep = tu.report()
        assert rep["moves"] >= 3 and rep["rollbacks"] == 0
        moves = [d for d in tu.decisions if d["kind"] == "move"]
        assert moves[0]["knob"] == "chunk_budget" \
            and moves[0]["action"] == "up" \
            and moves[0]["reason"] == "ttft_burn"
        # hold window respected: consecutive moves at least hold apart
        for a, b in zip(moves, moves[1:]):
            assert b["iteration"] - a["iteration"] >= TC["hold_iterations"]
        # every kept move judged with the evidence attached
        keep = next(d for d in tu.decisions if d["kind"] == "keep")
        assert "objective_after" in keep and keep["outcome"] == "kept"

    def test_spec_suspend_after_chunk_budget_exhausts(self):
        tu, eng, store = mk_tuner(FakeEngine(drafter=object()))
        run(tu, store, 200, 1, ttft=2.0, goodput=0.5)
        assert eng.prefill_chunks_per_iter == 4
        assert eng.spec_suspended is True
        assert ("spec", "up") in {(d["knob"], d["action"])
                                  for d in tu.decisions}

    def test_rollback_on_objective_regression_then_cooldown(self):
        tu, eng, store = mk_tuner()
        # pressure until exactly one move is pending
        it = 1
        while tu._pending is None:
            it = run(tu, store, 1, it, ttft=2.0, goodput=0.5)
        # the held move's after-evidence: goodput collapses
        while tu._rollbacks == 0:
            it = run(tu, store, 1, it, ttft=2.0, goodput=0.05)
            assert it < 200
        assert eng.prefill_chunks_per_iter == 1      # reverted
        roll = next(d for d in tu.decisions if d["kind"] == "rollback")
        assert roll["outcome"] == "rolled_back"
        assert roll["objective_delta"] < 0
        # (knob, action) cools down — sustained pressure proposes nothing
        # (the fake engine has no drafter/router, so no fallback knob)
        moves_before = tu._moves
        it = run(tu, store, 2 * TC["hold_iterations"], it,
                 ttft=2.0, goodput=0.05)
        assert tu._moves == moves_before and tu._pending is None
        # ...and re-proposes once the cooldown expires
        it = run(tu, store, 4 * TC["hold_iterations"], it,
                 ttft=2.0, goodput=0.5)
        assert tu._moves > moves_before

    def test_calm_signals_relax_back_to_defaults(self):
        tu, eng, store = mk_tuner()
        it = run(tu, store, 120, 1, ttft=2.0, goodput=0.5)
        assert eng.prefill_chunks_per_iter > 1
        it = run(tu, store, 200, it, ttft=0.0, tpot=0.0, goodput=0.9)
        assert eng.prefill_chunks_per_iter == 1
        relaxed = [d for d in tu.decisions if d["reason"] == "relax"]
        assert relaxed and all(d["knob"] == "chunk_budget" for d in relaxed)
        # settled at defaults: further calm ticks propose nothing
        moves = tu._moves
        run(tu, store, 40, it, goodput=0.9)
        assert tu._moves == moves

    def test_tpot_pressure_prefers_budget_down(self):
        tu, eng, store = mk_tuner()
        it = run(tu, store, 120, 1, ttft=2.0, goodput=0.5)
        assert eng.prefill_chunks_per_iter == 4
        run(tu, store, 60, it, ttft=0.0, tpot=2.0, goodput=0.5)
        down = [d for d in tu.decisions if d["reason"] == "tpot_burn"]
        assert down and down[0]["knob"] == "chunk_budget" \
            and down[0]["action"] == "down"
        assert eng.prefill_chunks_per_iter < 4

    def test_max_moves_caps_the_walk(self):
        tu, eng, store = mk_tuner(max_moves=1)
        run(tu, store, 200, 1, ttft=2.0, goodput=0.5)
        assert tu._moves == 1 and eng.prefill_chunks_per_iter == 2

    def test_router_knobs_walk_and_relax(self):
        """deadline_pad / overload_threshold against a fake router: the
        protective walk degrades earlier + sheds sooner, and calm relaxes
        both back to their untuned defaults."""
        router = types.SimpleNamespace(
            replicas=[], disagg=False, _degraded=0, admission_pad=0.0,
            config=types.SimpleNamespace(overload_occupancy=0.9))
        tu, _, store = mk_tuner(router,
                                knobs=["deadline_pad", "overload_threshold"])
        assert tu._router is router
        it = run(tu, store, 400, 1, ttft=2.0, goodput=0.5)
        assert router.config.overload_occupancy == pytest.approx(0.5)
        assert router.admission_pad == pytest.approx(1.0)
        run(tu, store, 600, it, ttft=0.0, goodput=0.9)
        assert router.admission_pad == pytest.approx(0.0)
        assert router.config.overload_occupancy == pytest.approx(0.9)

    def test_objective_penalizes_burn_over_ceiling_only(self):
        tu, _, _ = mk_tuner(burn_ceiling=1.0, burn_weight=2.0)
        base = dict(ttft_burn=0.0, tpot_burn=0.0, goodput=0.8,
                    occupancy=0.0, queue_depth=0.0)
        assert tu.objective(dict(base)) == pytest.approx(0.8)
        assert tu.objective(dict(base, ttft_burn=0.9)) == pytest.approx(0.8)
        assert tu.objective(dict(base, ttft_burn=1.5)) == pytest.approx(
            0.8 - 2.0 * 0.5)

    def test_export_recommendations_artifact_schema(self, tmp_path):
        tu, _, store = mk_tuner()
        run(tu, store, 120, 1, ttft=2.0, goodput=0.5)
        path = tu.export_recommendations(str(tmp_path / "rec.json"))
        with open(path) as fh:
            out = json.load(fh)
        assert out["format"] == RECOMMENDATIONS_FORMAT
        assert out["moves"] >= 1 and "objective" in out
        assert out["knobs"]["chunk_budget"] == 4.0
        assert isinstance(out["recommendations"], list)
        # the settled >1 chunk budget turns into shape-knob advice only for
        # real engines (FakeEngine has no .config) — never applied online
        assert all(r["kind"] == "shape" for r in out["recommendations"])


# ---------------------------------------------------------------------------
# gating — the disabled path constructs nothing
# ---------------------------------------------------------------------------


def _fake_obs(enabled=True, tune=None, store="auto"):
    return types.SimpleNamespace(
        enabled=enabled,
        config=types.SimpleNamespace(tune=tune),
        timeseries=TimeSeriesStore() if store == "auto" else store,
        registry=MetricsRegistry())


class TestGating:
    def test_maybe_make_tuner_requires_every_gate(self):
        on = TuneConfig(enabled=True, controller=True)
        assert maybe_make_tuner(FakeEngine(), _fake_obs(enabled=False,
                                                        tune=on)) is None
        assert maybe_make_tuner(FakeEngine(), _fake_obs(tune=None)) is None
        assert maybe_make_tuner(
            FakeEngine(), _fake_obs(tune=TuneConfig(enabled=True))) is None
        assert maybe_make_tuner(FakeEngine(),
                                _fake_obs(tune=on, store=None)) is None
        tu = maybe_make_tuner(FakeEngine(), _fake_obs(tune=on))
        assert isinstance(tu, LiveTuner)

    def test_store_allocation_gated_on_tune_enabled(self, tmp_path):
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "a")))
        assert get_session().timeseries is None
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "b"),
            tune={"enabled": True, "store_capacity": 16}))
        st = get_session().timeseries
        assert isinstance(st, TimeSeriesStore) and st.capacity == 16

    def test_session_replacement_adopts_store(self, tmp_path):
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "a"),
            tune={"enabled": True}))
        get_session().timeseries.observe("a", 1.0, step=3)
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "b"),
            tune={"enabled": True}))
        # the soft-restart survival path: rolling windows carry over
        assert get_session().timeseries.window("a") == [(3, 1.0)]


# ---------------------------------------------------------------------------
# end to end — tiny model: bit-exactness with the tuner ON, and the
# disabled path wires nothing on real engines
# ---------------------------------------------------------------------------

SCFG = dict(block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16, max_queue=64)
N_NEW = 10
TEMP = 0.7


@pytest.fixture(scope="module")
def tiny_engine():
    from deepspeed_tpu.inference import init_inference

    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


def mk_prompts(n, seed=23):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 50, size=rng.randint(4, 48)).astype(np.int32)
            for _ in range(n)]


class TestTunerEndToEnd:
    def test_disabled_session_wires_no_tuner_no_store(self, tiny_engine):
        from deepspeed_tpu.serving import ServingEngine

        assert not get_session().enabled
        srv = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        try:
            h = srv.submit(mk_prompts(1)[0], max_new_tokens=4, seed=0)
            h.result()
        finally:
            srv.close()
        assert srv._tuner is None
        assert get_session().timeseries is None

    def test_fleet_with_tuner_on_is_bit_exact_vs_oracle(self, tiny_engine,
                                                        tmp_path):
        from deepspeed_tpu.serving import ServingEngine
        from deepspeed_tpu.serving.fleet import FleetRouter, build_replicas

        prompts = mk_prompts(10)
        # oracle: solo engine, observability disabled, no tuner
        solo = ServingEngine(tiny_engine, ServingConfig(**SCFG))
        try:
            want = [solo.submit(p, max_new_tokens=N_NEW, seed=i,
                                temperature=TEMP).result()
                    for i, p in enumerate(prompts)]
        finally:
            solo.close()

        # a 1ms TTFT SLO every request breaches: sustained burn makes the
        # controller actually walk knobs mid-trace
        configure_observability(ObservabilityConfig(
            enabled=True, output_dir=str(tmp_path / "obs"),
            serve_goodput=True,
            serve_ttft_slo_ms=0.001, serve_tpot_slo_ms=1000.0,
            tune={"enabled": True, "controller": True,
                  "interval_iterations": 2, "hold_iterations": 4}))
        replicas = build_replicas(tiny_engine, ServingConfig(**SCFG), 2)
        router = FleetRouter(replicas, FleetConfig(policy="kv_occupancy"))
        try:
            handles, i, it = [], 0, 0
            while i < len(prompts) or router.in_flight():
                if i < len(prompts) and it % 2 == 0:
                    handles.append(router.submit(
                        prompts[i], max_new_tokens=N_NEW, seed=i,
                        temperature=TEMP))
                    i += 1
                router.step()
                it += 1
                assert it < 10_000, "fleet made no progress"
            got = [h.result() for h in handles]
            tuner = router._tuner
            assert tuner is not None, "tune gate on but no controller wired"
            assert tuner._last_iteration > 0
            assert tuner._moves >= 1, "sustained burn yet the tuner sat still"
        finally:
            router.close()

        # the contract: scheduling-only knobs — streams bit-identical
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
        sess = get_session()
        if sess.watchdog is not None:
            assert sess.watchdog.steady_state_compiles == 0, (
                "live tuning must never recompile a hot function")
        # close() exported the shape-knob recommendations artifact
        rec_path = os.path.join(str(tmp_path / "obs"),
                                "tune_recommendations.json")
        assert os.path.exists(rec_path)
        with open(rec_path) as fh:
            assert json.load(fh)["format"] == RECOMMENDATIONS_FORMAT
