"""tpucost unit tests: extraction helpers, roofline math, tolerance-band
baseline semantics (regression / stale-rot / prune), the injected-regression
acceptance fixture (dead donation + undeclared all-gather must fail the gate
naming entry, metric and delta), the autotuner calibration shim, and the
repo-wide gate (selftest engines vs the committed baseline — what makes
tier-1 enforce program-cost analysis)."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.tpuaudit import clear_registry, register_entry_point
from tools.tpucost import baseline as baseline_mod
from tools.tpucost import extract, roofline
from tools.tpucost.cli import main as tpucost_main
from tools.tpucost.core import cost_entry, registry_cost_vector, run_cost

REPO = Path(__file__).resolve().parents[2]


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_registry()
    yield
    clear_registry()


def sds(shape, dtype=jnp.float32, sharding=None):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def mesh2x4():
    devs = np.array(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("data", "model"))


# ---------------------------------------------------------------------------
# extraction helpers


class TestExtract:
    def test_hlo_op_census_counts_and_async_folding(self):
        text = """
HloModule m
ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %c = f32[]{} constant(1)
  %b = f32[4]{0} broadcast(f32[] %c), dimensions={}
  %ag-start = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %p0), replica_groups={{0,1}}, dimensions={0}
  %ag-done = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag-start)
  ROOT %add = f32[4]{0} add(f32[4]{0} %p0, f32[4]{0} %b)
}
"""
        census = extract.hlo_op_census(text)
        assert census["parameter"] == 1 and census["add"] == 1
        # -start counts once, -done is dropped
        assert census["all-gather"] == 1 and "all-gather-done" not in census

    def test_collective_census_bytes_and_axis(self):
        text = ("  %ag = f32[8,16]{1,0} all-gather(f32[2,16]{1,0} %x), "
                "replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}\n"
                "  %cp = bf16[32]{0} collective-permute(bf16[32]{0} %y), "
                "source_target_pairs={{0,1}}\n")
        census = extract.collective_census(
            text, axis_sizes={"data": 2, "model": 4})
        assert census["by_kind"]["all-gather"]["count"] == 1
        assert census["by_kind"]["all-gather"]["bytes"] == 8 * 16 * 4
        assert census["by_kind"]["collective-permute"]["bytes"] == 32 * 2
        # group of 4 matches exactly the model axis
        assert census["by_axis"]["model"] == 8 * 16 * 4
        assert census["total_bytes"] == 8 * 16 * 4 + 32 * 2

    def test_collective_census_iota_groups(self):
        text = ("  %ar = f32[128]{0} all-reduce(f32[128]{0} %x), "
                "replica_groups=[4,2]<=[8], to_apply=%add\n")
        census = extract.collective_census(
            text, axis_sizes={"data": 2, "model": 4})
        assert census["by_axis"] == {"data": 512.0}

    def test_cost_and_memory_analysis_on_real_program(self):
        f = jax.jit(lambda s, x: (jax.tree.map(lambda a: a + x.sum(), s),
                                  x.sum()), donate_argnums=(0,))
        args = ({"w": sds((256, 256))}, sds((64,)))
        compiled = f.trace(*args).lower().compile()
        cost = extract.cost_analysis_dict(compiled)
        assert cost["flops"] > 0 and cost["bytes_accessed"] > 0
        mem = extract.memory_analysis_dict(compiled)
        assert mem["argument_hbm_bytes"] >= 256 * 256 * 4
        # the donated state aliases its output: peak excludes one copy
        assert mem["alias_hbm_bytes"] >= 256 * 256 * 4
        assert mem["peak_hbm_bytes"] == (
            mem["argument_hbm_bytes"] + mem["output_hbm_bytes"]
            + mem["temp_hbm_bytes"] - mem["alias_hbm_bytes"])

    def test_program_hash_stable_and_distinct(self):
        assert extract.program_hash("abc") == extract.program_hash("abc")
        assert extract.program_hash("abc") != extract.program_hash("abd")


class TestRoofline:
    def test_compute_bound(self):
        b = roofline(flops=1e12, bytes_accessed=1.0, collective_bytes=0.0)
        assert b.bound == "compute" and b.mfu_ceiling == 1.0
        assert b.predicted_step_s == pytest.approx(1e12 / b.peak_flops)

    def test_hbm_bound_ceiling_below_one(self):
        b = roofline(flops=1e9, bytes_accessed=1e12, collective_bytes=0.0,
                     tokens_per_step=4096)
        assert b.bound == "hbm" and 0 < b.mfu_ceiling < 1
        assert b.predicted_tokens_per_sec == pytest.approx(
            4096 / b.predicted_step_s)

    def test_ici_bound(self):
        b = roofline(flops=1.0, bytes_accessed=1.0, collective_bytes=1e12)
        assert b.bound == "ici" and b.mfu_ceiling > 0


# ---------------------------------------------------------------------------
# baseline semantics


def _vec(entry="e", metrics=None, hlo_ops=None):
    from tools.tpucost.core import CostVector

    return CostVector(entry=entry, metrics=dict(metrics or {}),
                      hlo_ops=dict(hlo_ops or {}),
                      collectives={"total_bytes": 0.0, "by_kind": {},
                                   "by_axis": {}},
                      program_hash="h", compiled=True, predicted_step_s=1e-3,
                      mfu_ceiling=0.5, bound="hbm")


class TestBaselineSemantics:
    def test_identical_is_clean(self):
        v = _vec(metrics={"flops": 100.0, "peak_hbm_bytes": 1000.0})
        base = baseline_mod.records_of([v])
        findings, stale = baseline_mod.compare([v], base)
        assert findings == [] and stale == []

    def test_growth_beyond_band_fails_with_attribution(self):
        v0 = _vec(metrics={"flops": 100.0, "peak_hbm_bytes": 1000.0},
                  hlo_ops={"fusion": 3})
        base = baseline_mod.records_of([v0])
        v1 = _vec(metrics={"flops": 100.0, "peak_hbm_bytes": 1030.0},
                  hlo_ops={"fusion": 5, "all-gather": 1})
        findings, stale = baseline_mod.compare([v1], base)
        assert [f.key for f in findings] == ["e::peak_hbm_bytes"]
        msg = findings[0].render()
        assert "1,000 -> 1,030" in msg and "+3.00%" in msg
        assert "fusion +2" in msg and "all-gather +1" in msg

    def test_growth_within_band_is_clean(self):
        v0 = _vec(metrics={"peak_hbm_bytes": 1000.0})
        base = baseline_mod.records_of([v0])
        findings, stale = baseline_mod.compare(
            [_vec(metrics={"peak_hbm_bytes": 1015.0})], base)
        assert findings == [] and stale == []

    def test_exact_metric_any_growth_fails(self):
        v0 = _vec(metrics={"flops": 100.0})
        base = baseline_mod.records_of([v0])
        findings, _ = baseline_mod.compare([_vec(metrics={"flops": 101.0})],
                                           base)
        assert [f.key for f in findings] == ["e::flops"]

    def test_improvement_goes_stale_then_prunes(self):
        v0 = _vec(metrics={"flops": 100.0})
        base = baseline_mod.records_of([v0])
        v1 = _vec(metrics={"flops": 50.0})
        findings, stale = baseline_mod.compare([v1], base)
        assert findings == [] and stale == ["e::flops"]
        pruned = baseline_mod.pruned([v1], base)
        assert pruned["e"]["metrics"]["flops"] == 50.0
        findings, stale = baseline_mod.compare([v1], pruned)
        assert findings == [] and stale == []

    def test_prune_never_ratchets_up(self):
        v0 = _vec(metrics={"flops": 100.0})
        base = baseline_mod.records_of([v0])
        v_fat = _vec(metrics={"flops": 200.0})
        pruned = baseline_mod.pruned([v_fat], base)
        assert pruned["e"]["metrics"]["flops"] == 100.0
        findings, _ = baseline_mod.compare([v_fat], pruned)
        assert [f.key for f in findings] == ["e::flops"]

    def test_vanished_entry_stale_then_pruned_away(self):
        base = baseline_mod.records_of([_vec(metrics={"flops": 1.0})])
        findings, stale = baseline_mod.compare([], base)
        assert findings == [] and stale == ["e::flops"]
        assert baseline_mod.pruned([], base) == {}

    def test_new_entry_is_a_finding(self):
        findings, stale = baseline_mod.compare(
            [_vec(entry="new", metrics={"flops": 1.0})], {})
        assert [f.key for f in findings] == ["new::unbaselined"]

    def test_trace_error_gates(self):
        findings, _ = baseline_mod.compare([], {}, errors={"broken": "boom"})
        assert [f.key for f in findings] == ["broken::trace-error"]

    def test_out_of_scope_keys_untouched(self):
        base = baseline_mod.records_of([
            _vec(entry="a", metrics={"flops": 10.0}),
            _vec(entry="b", metrics={"flops": 10.0})])
        in_scope = lambda key: key.startswith("a::")   # noqa: E731
        findings, stale = baseline_mod.compare(
            [_vec(entry="a", metrics={"flops": 10.0})], base,
            in_scope=in_scope)
        assert findings == [] and stale == []
        pruned = baseline_mod.pruned(
            [_vec(entry="a", metrics={"flops": 10.0})], base,
            in_scope=in_scope)
        assert pruned["b"]["metrics"]["flops"] == 10.0


# ---------------------------------------------------------------------------
# cost vectors from the registry


class TestCostEntry:
    def test_vector_from_registered_entry(self):
        f = jax.jit(lambda s, x: (jax.tree.map(lambda a: a + x.sum(), s),
                                  x.sum()), donate_argnums=(0,))
        ep = register_entry_point(
            "fix/vec", fn=f, args=({"w": sds((128, 128))}, sds((8,))),
            donate_argnums=(0,), expected_collectives=None,
            tags={"tokens_per_step": 8})
        v = cost_entry(ep)
        assert v.compiled and v.metrics["flops"] > 0
        assert v.metrics["peak_hbm_bytes"] > 0
        assert v.metrics["hlo_op_count"] > 0 and v.metrics["jaxpr_eqns"] > 0
        assert v.mfu_ceiling > 0 and v.predicted_step_s > 0
        assert v.predicted_tokens_per_sec > 0
        assert len(v.program_hash) == 64

    def test_dropping_donation_grows_peak_hbm(self):
        args = ({"w": sds((256, 256))}, sds((8,)))

        def step(s, x):
            return jax.tree.map(lambda a: a + x.sum(), s), x.sum()

        donated = cost_entry(register_entry_point(
            "fix/don", fn=jax.jit(step, donate_argnums=(0,)), args=args,
            donate_argnums=(0,), expected_collectives=None))
        plain = cost_entry(register_entry_point(
            "fix/nodon", fn=jax.jit(step), args=args,
            expected_collectives=None))
        assert (plain.metrics["peak_hbm_bytes"]
                > donated.metrics["peak_hbm_bytes"])

    def test_uncompiled_entry_still_gets_flops(self):
        ep = register_entry_point(
            "fix/nocompile", fn=jax.jit(lambda x: (x @ x).sum()),
            args=(sds((64, 64)),), expected_collectives=None, compile=False)
        v = cost_entry(ep)
        assert not v.compiled
        assert v.metrics["flops"] > 0 and v.mfu_ceiling > 0
        assert "peak_hbm_bytes" not in v.metrics

    def test_registry_cost_vector_misses_return_none(self):
        assert registry_cost_vector("no/such/entry") is None

    def test_run_cost_reports_trace_errors(self):
        def boom():
            raise RuntimeError("kaput")

        ep = register_entry_point("fix/broken", build=boom,
                                  expected_collectives=None)
        vectors, errors = run_cost([ep], publish_metrics=False)
        assert vectors == [] and "kaput" in errors["fix/broken"]

    def test_publish_lands_in_metrics_registry(self):
        from deepspeed_tpu.observability import get_registry

        ep = register_entry_point(
            "pub/cost", fn=jax.jit(lambda x: x.sum()), args=(sds((32,)),),
            expected_collectives=None)
        run_cost([ep])
        g = get_registry().gauge("tpucost/pub/cost/flops")
        assert g.value() is not None and g.value() >= 0


# ---------------------------------------------------------------------------
# injected-regression acceptance fixture + CLI


class TestInjectedRegression:
    """Deliberately fatten one entry — drop its donation (peak HBM grows)
    and force an undeclared GSPMD all-gather (collective bytes grow) — and
    the gate must exit nonzero naming the entry, the metrics and the
    deltas."""

    def _register(self, fat: bool):
        mesh = mesh2x4()

        def step(state, batch):
            new = jax.tree.map(lambda a: a + batch.sum(), state)
            if fat:
                # replicate the sharded state: GSPMD inserts an all-gather
                new = {"w": jax.lax.with_sharding_constraint(
                    new["w"], NamedSharding(mesh, P(None, None)))}
            return new

        donate = () if fat else (0,)
        args = ({"w": sds((608, 608),
                          sharding=NamedSharding(mesh, P("model", None)))},
                sds((8,)))
        register_entry_point(
            "fix/step", fn=jax.jit(step, donate_argnums=donate), args=args,
            donate_argnums=donate, expected_collectives=None, mesh=mesh)

    def test_gate_names_entry_metric_and_delta(self, tmp_path, capsys):
        bl = tmp_path / "bl.json"
        self._register(fat=False)
        assert tpucost_main(["--baseline", str(bl),
                             "--write-baseline"]) == 0
        assert tpucost_main(["--baseline", str(bl)]) == 0
        capsys.readouterr()

        clear_registry()
        self._register(fat=True)
        rc = tpucost_main(["--baseline", str(bl)])
        out = capsys.readouterr().out
        assert rc == 1
        flagged = [l for l in out.splitlines() if "fix/step:" in l]
        assert any("peak_hbm_bytes" in l and "->" in l and "%" in l
                   for l in flagged), out
        assert any("collective_bytes" in l for l in flagged), out

    def test_clean_run_with_diff_and_json(self, tmp_path, capsys):
        bl = tmp_path / "bl.json"
        self._register(fat=False)
        assert tpucost_main(["--baseline", str(bl),
                             "--write-baseline"]) == 0
        capsys.readouterr()
        rc = tpucost_main(["--baseline", str(bl), "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["new_findings"] == 0
        vec = out["entries"]["fix/step"]
        assert vec["mfu_ceiling"] > 0
        rc = tpucost_main(["--baseline", str(bl), "--diff"])
        assert rc == 0
        assert "unchanged" in capsys.readouterr().out

    def test_no_entries_errors(self):
        assert tpucost_main([]) == 2

    def test_partial_entries_write_merges_into_baseline(self, tmp_path,
                                                        capsys):
        """--entries X --write-baseline must not destroy the other
        entries' committed budgets."""
        bl = tmp_path / "bl.json"
        self._register(fat=False)
        register_entry_point(
            "fix/other", fn=jax.jit(lambda x: x.sum()), args=(sds((16,)),),
            expected_collectives=None)
        assert tpucost_main(["--baseline", str(bl),
                             "--write-baseline"]) == 0
        assert tpucost_main(["--baseline", str(bl), "--entries", "fix/step",
                             "--write-baseline"]) == 0
        entries = json.loads(bl.read_text())["entries"]
        assert set(entries) == {"fix/step", "fix/other"}
        assert tpucost_main(["--baseline", str(bl)]) == 0

    def test_prune_refuses_on_broken_entry(self, tmp_path, capsys):
        bl = tmp_path / "bl.json"
        self._register(fat=False)
        assert tpucost_main(["--baseline", str(bl),
                             "--write-baseline"]) == 0

        def boom():
            raise RuntimeError("kaput")

        register_entry_point("fix/broken", build=boom,
                             expected_collectives=None)
        assert tpucost_main(["--baseline", str(bl),
                             "--prune-baseline"]) == 2


def test_report_footer_pairs_measured_mfu_with_train_step():
    """The measured goodput/mfu must be compared against the TRAIN step's
    own ceiling, not whichever program has the largest one."""
    from deepspeed_tpu.observability.report import summarize_cost

    records = [
        {"type": "gauge", "name": "goodput/mfu", "labels": {}, "value": 0.35},
        {"type": "gauge", "name": "tpucost/train/step/mfu_ceiling",
         "labels": {}, "value": 0.41},
        {"type": "gauge", "name": "tpucost/inference/prefill/mfu_ceiling",
         "labels": {}, "value": 0.99},
    ]
    out = summarize_cost(records)
    assert "measured mfu = 0.3500 vs static ceiling 0.4100 (train/step)" \
        in out
    assert "0.9900" not in out.splitlines()[-1]


# ---------------------------------------------------------------------------
# autotuner calibration shim


class TestAutotunerShim:
    def _model_info(self):
        return {"num_params": 125e6, "hidden_size": 768, "num_layers": 12,
                "seq_length": 1024, "vocab_size": 50257}

    def test_calibrate_from_vector_switches_backend(self):
        from deepspeed_tpu.autotuning.cost_model import TpuCostModel

        m = TpuCostModel(model_info=self._model_info())
        assert m.backend == "static-tables"
        vec = _vec(metrics={"flops": 1e12})
        vec.tags["tokens_per_step"] = 32 * 1024
        assert m.calibrate_from_vector(vec)
        assert m.backend == "tpucost:h"
        cfg = {"train_micro_batch_size_per_gpu": 1}
        calibrated = m.predict_throughput(cfg)
        m2 = TpuCostModel(model_info=self._model_info())
        assert calibrated != m2.predict_throughput(cfg)

    def test_calibrate_rejects_vector_without_tokens(self):
        from deepspeed_tpu.autotuning.cost_model import TpuCostModel

        m = TpuCostModel(model_info=self._model_info())
        assert not m.calibrate_from_vector(_vec(metrics={"flops": 1e12}))
        assert m.backend == "static-tables"

    def test_tune_records_cost_backend(self, tmp_path):
        from deepspeed_tpu.autotuning.autotuner import Autotuner

        vec = _vec(metrics={"flops": 1e12})
        vec.tags["tokens_per_step"] = 32 * 1024
        tuner = Autotuner(
            {"autotuning": {"model_info": self._model_info()}},
            results_dir=str(tmp_path), runner=lambda name, cfg: 1.0)
        best, val = tuner.tune(
            space={"train_micro_batch_size_per_gpu": [1, 2]},
            tuner_type="model_based", num_trials=2, cost_vector=vec)
        assert val == 1.0
        summary = json.loads((tmp_path / "summary.json").read_text())
        assert summary["cost_backend"] == "tpucost:h"


# ---------------------------------------------------------------------------
# bench integration (jax-free parent pieces)


class TestBenchIntegration:
    def test_skip_record_carries_predicted_mfu(self, capsys):
        import bench_common

        with pytest.raises(SystemExit) as e:
            bench_common.skip("m", "tok/s", "tunnel", "backend-init",
                              predicted_mfu=0.42)
        assert e.value.code == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["skipped"] and rec["predicted_mfu"] == 0.42
        assert rec["failure_kind"] == "backend-init"

    def test_cost_vector_record_unregistered_entry(self):
        import bench_common

        assert bench_common.cost_vector_record("no/entry") is None

    def test_cost_vector_record_shape(self):
        import bench_common

        register_entry_point(
            "bench/step", fn=jax.jit(lambda x: (x @ x).sum()),
            args=(sds((64, 64)),), expected_collectives=None,
            tags={"tokens_per_step": 64})
        rec = bench_common.cost_vector_record("bench/step")
        assert rec["flops"] > 0 and rec["predicted_mfu"] > 0
        assert rec["bound"] in ("compute", "hbm", "ici")
        assert len(rec["program_hash"]) == 12
        assert rec["predicted_tokens_per_sec"] > 0


# ---------------------------------------------------------------------------
# repo-wide gate (tier-1 acceptance)


class TestRepoGate:
    def test_selftest_engines_clean_under_committed_baseline(self, tmp_path):
        """Acceptance gate: every selftest entry (train/eval, pipeline x4,
        inference prefill/decode, serving prefill_chunk/decode) must produce
        a cost vector with a nonzero predicted-MFU ceiling, gate clean
        against the committed baseline, and surface in the report CLI's
        == cost == section."""
        jsonl = tmp_path / "cost_metrics.jsonl"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpucost",
             "--config", "tools/tpuaudit/selftest_config.json",
             "--baseline", ".tpucost-baseline.json",
             "--metrics-jsonl", str(jsonl), "--format", "json"],
            cwd=REPO, capture_output=True, text=True, timeout=540,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, \
            f"tpucost gate failed:\n{proc.stdout}\n{proc.stderr}"
        out = json.loads(proc.stdout)
        entries = out["entries"]
        expected = {"train/step", "train/eval", "pipeline/loss_fn",
                    "pipeline/grad_fn", "pipeline/step", "pipeline/eval",
                    "inference/prefill", "inference/decode",
                    "serving/prefill_chunk", "serving/decode"}
        assert expected <= set(entries), sorted(entries)
        for name in expected:
            assert entries[name]["mfu_ceiling"] > 0, name
            assert entries[name]["metrics"]["flops"] > 0, name

        # the report CLI renders the dumped gauges as == cost ==
        rep = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.observability", "report",
             str(jsonl)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert rep.returncode == 0, rep.stderr
        assert "== cost ==" in rep.stdout
        for name in expected:
            assert name in rep.stdout
