"""Serving chaos gate — the fleet's detect → remediate → verify loop under
a deterministic fault plan (scripts/chaos_serve.sh runs this file; the
headline smokes are tier-1 too).

The acceptance contract (ISSUE-12):
  * 12+ staggered temperature-0.7 requests through a 3-replica fleet under
    a kill → slow → revive plan are bit-identical to the single-engine
    oracle — replica death, drain/resubmission, quarantine, revival and
    probation are all invisible to clients;
  * ≥ 1 quarantine (the ``replica_slow`` fault convicts through the
    rolling step-time verdict), ≥ 1 revival that graduates probation;
  * ≥ 1 deadline-infeasible submit shed with a structured
    ``Overloaded(retry_after_s=...)``;
  * zero leaked KV blocks: every alive replica's pool drains back to its
    prefix-cache pins, and the fleet request ledger balances
    (submitted == finished + cancelled + shed + deadline_exceeded);
  * the disaggregated variant: a ``handoff_fail`` fault mid-trace retries
    the transfer on another decode replica (or falls back to decoding in
    place) with both sides' blocks freed exactly once — still bit-exact.

Everything is CPU-only, sleep-free (the ``replica_slow`` penalty rides the
health data-plane, not the wall clock) and pinned to router iterations, so
a chaos run is exactly reproducible.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.config.config import FleetConfig, ServingConfig
from deepspeed_tpu.inference import init_inference
from deepspeed_tpu.serving import ServingEngine
from deepspeed_tpu.serving.fleet import (ROLE_DECODE, ROLE_PREFILL,
                                         FleetRouter, Overloaded,
                                         build_replicas)

SCFG = dict(block_size=16, num_blocks=32, max_seqs=4, max_model_len=128,
            prefill_chunk=16, max_queue=64)

N_REQ = 14
N_NEW = 12
TEMP = 0.7

# kill → slow → revive: replica 1 dies mid-stream (auto-revival rebuilds
# it at iteration 17 = 9 + revive_after_iterations), replica 2 turns into
# a straggler right after and is quarantined by the step-time verdict
CHAOS_PLAN = [
    {"kind": "replica_kill", "step": 9, "replica": 1},
    {"kind": "replica_slow", "step": 12, "steps": 18, "replica": 2,
     "sleep_s": 10.0},
]

# warmup 3 keeps compile-heavy first dispatches out of the sampled
# windows; a 2s SLO with ms-scale real steps then only ever convicts the
# injected 10s replica_slow penalty — deterministically
CHAOS_FLEET = dict(
    policy="kv_occupancy", health_window=2, health_warmup_steps=3,
    step_time_slo_s=2.0, quarantine_iterations=8,
    revive_after_iterations=8, probation_requests=2, probation_share=0.5,
    breaker_incidents=6, auto_revive=True)


@pytest.fixture(scope="module")
def tiny_engine():
    return init_inference("tiny", dtype=jnp.float32, max_out_tokens=128)


def mk_prompts(n, lo=4, hi=60, seed=11):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 50, size=rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def oracle_outputs(engine, prompts, seeds, n_new=N_NEW, temperature=TEMP):
    solo = ServingEngine(engine, ServingConfig(**SCFG))
    outs = []
    try:
        for p, s in zip(prompts, seeds):
            outs.append(solo.submit(p, max_new_tokens=n_new, seed=s,
                                    temperature=temperature).result())
    finally:
        solo.close()
    return outs


def run_staggered(router, prompts, stagger=2):
    handles = []
    i, it = 0, 0
    while i < len(prompts) or router.in_flight():
        if i < len(prompts) and it % stagger == 0:
            handles.append(router.submit(prompts[i], max_new_tokens=N_NEW,
                                         seed=i, temperature=TEMP))
            i += 1
        router.step()
        it += 1
        assert it < 10_000, "fleet made no progress"
    return handles


def assert_no_leaked_blocks(replicas):
    for r in replicas:
        if not r.alive:
            continue
        cache = r.engine.sched.prefix
        held = cache.cached_blocks if cache else 0
        assert r.engine.alloc.blocks_in_use == held, (
            f"replica {r.index} leaked "
            f"{r.engine.alloc.blocks_in_use - held} blocks")


class TestServingChaosGate:
    def test_kill_slow_revive_bit_exact(self, tiny_engine):
        """The headline gate: full fault plan through a 3-replica fleet."""
        prompts = mk_prompts(N_REQ + 6)
        want = oracle_outputs(tiny_engine, prompts,
                              seeds=list(range(len(prompts))))
        replicas = build_replicas(tiny_engine, ServingConfig(**SCFG), 3)
        router = FleetRouter(replicas, FleetConfig(**CHAOS_FLEET),
                             fault_plan=CHAOS_PLAN)
        try:
            hs = run_staggered(router, prompts[:N_REQ])
            # -- detect + remediate actually happened --
            assert replicas[1].deaths == 1            # the kill fired
            assert replicas[1].revivals >= 1          # ... and was revived
            assert replicas[2].quarantines >= 1       # the slow verdict
            assert router._quarantine_count >= 1
            assert router._revival_count >= 1
            assert sum(h.resubmits for h in hs) >= 1  # drain mid-stream
            # -- probation graduation (top up with extra oracle-checked
            #    traffic if the staggered trace alone didn't get there;
            #    PAIRS: kv_occupancy tie-breaks an empty probation replica
            #    behind an equally empty full member by index, so the
            #    second of each pair is the one that reaches it) --
            extra = []
            i = N_REQ
            while router._graduation_count == 0 and i + 1 < len(prompts):
                pair = [router.submit(prompts[j], max_new_tokens=N_NEW,
                                      seed=j, temperature=TEMP)
                        for j in (i, i + 1)]
                for j, h in zip((i, i + 1), pair):
                    h.result()
                    extra.append((j, h))
                i += 2
            assert router._graduation_count >= 1
            assert replicas[1].probation_left == 0
            # -- verify: every stream bit-identical to the oracle --
            for i, (h, exp) in enumerate(zip(hs, want)):
                np.testing.assert_array_equal(
                    np.asarray(h.tokens, np.int32), exp,
                    err_msg=f"request {i} diverged from the single engine")
            for i, h in extra:
                np.testing.assert_array_equal(
                    np.asarray(h.tokens, np.int32), want[i],
                    err_msg=f"post-revival request {i} diverged")
            # -- overload: a deadline-infeasible submit sheds with a
            #    structured retry hint (TPOT data exists by now) --
            assert router._tpot_estimate() is not None
            with pytest.raises(Overloaded) as exc:
                router.submit(prompts[0], max_new_tokens=64,
                              deadline_s=1e-9)
            assert exc.value.retry_after_s > 0
            # -- no leaks, balanced ledger --
            assert_no_leaked_blocks(replicas)
            assert router.submitted_count == (
                router.finished_count + router.cancelled_count
                + router.shed_count_total
                + router.deadline_exceeded_count)
            assert router.cancelled_count == 0        # nothing was lost
        finally:
            router.close()

    def test_disagg_handoff_fail_bit_exact(self, tiny_engine):
        """The disaggregated variant: a mid-trace transfer failure retries
        on the other decode replica; streams stay bit-exact and both
        sides' pools drain."""
        prompts = mk_prompts(8, seed=13)
        want = oracle_outputs(tiny_engine, prompts,
                              seeds=list(range(len(prompts))))
        replicas = build_replicas(
            tiny_engine, ServingConfig(**SCFG), 3,
            roles=[ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE])
        router = FleetRouter(
            replicas, FleetConfig(policy="kv_occupancy"),
            fault_plan=[{"kind": "handoff_fail", "step": 2}])
        try:
            hs = run_staggered(router, prompts)
            assert router._handoff_failures >= 1      # the fault fired
            # the failed transfer retried elsewhere or decoded in place —
            # either way every request finished
            assert all(h.state == "finished" for h in hs)
            for i, (h, exp) in enumerate(zip(hs, want)):
                np.testing.assert_array_equal(
                    np.asarray(h.tokens, np.int32), exp,
                    err_msg=f"request {i} diverged across the failure")
            assert_no_leaked_blocks(replicas)
        finally:
            router.close()


class TestThreadedChaos:
    """The threaded variant: a ``dstpu-fleet`` driver thread steps the
    fleet while clients submit and block on handle condvars from the main
    thread — the two-thread topology tpusync's whole-program graph models.
    Under ``pytest --stress`` the ``stress_perturber`` fixture wraps the
    router's and every engine's lock in a seeded
    :class:`~deepspeed_tpu.observability.faultinject.LockPerturber`:
    deterministic GIL-yield points at each lock boundary widen exactly the
    race windows the analyzer reasons about, with zero wall-clock waits.
    ``scripts/chaos_serve.sh`` runs this class both plain and stressed.
    """

    def test_threaded_kill_mid_stream_bit_exact(self, tiny_engine,
                                                stress_perturber):
        prompts = mk_prompts(N_REQ)
        want = oracle_outputs(tiny_engine, prompts,
                              seeds=list(range(N_REQ)))
        replicas = build_replicas(tiny_engine, ServingConfig(**SCFG), 3)
        router = FleetRouter(
            replicas, FleetConfig(**CHAOS_FLEET),
            fault_plan=[{"kind": "replica_kill", "step": 5, "replica": 1}])
        if stress_perturber is not None:
            stress_perturber.instrument(
                router, *[r.engine for r in replicas])
        router.start()
        try:
            handles = [router.submit(p, max_new_tokens=N_NEW, seed=i,
                                     temperature=TEMP)
                       for i, p in enumerate(prompts)]
            outs = [h.result(timeout_s=120.0) for h in handles]
            # the fault fired on the driver thread while clients waited
            assert replicas[1].deaths == 1
            for i, (o, exp) in enumerate(zip(outs, want)):
                np.testing.assert_array_equal(
                    o, exp,
                    err_msg=f"request {i} diverged from the single "
                            f"engine (threaded driver)")
            assert router.submitted_count == (
                router.finished_count + router.cancelled_count
                + router.shed_count_total
                + router.deadline_exceeded_count)
            assert router.cancelled_count == 0
        finally:
            router.close()
        assert_no_leaked_blocks(replicas)
        if stress_perturber is not None:
            # the perturber actually exercised the lock boundaries
            assert stress_perturber.acquires > 0
            assert stress_perturber.yields > 0
