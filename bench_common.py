"""Shared bench-harness guard — watchdogged child, evidence-first kills,
structured skip records.

Both bench entrypoints (`bench.py` train, `bench_infer.py` TTFT/decode) run
their measurement in a watchdogged child process so a tunnel hang cannot eat
the round. The round-5 record showed what a bare SIGKILL costs: a skip
annotated only "tunnel hang suspected", with zero evidence. This guard kills
in two phases instead:

1. **SIGUSR1** to the child's process group and a short grace wait
   (``BENCH_SIGUSR1_GRACE``, default 20 s): the child's observability
   session installs a SIGUSR1 handler that dumps its flight record — ring
   of recent spans/metrics/compiles, per-thread Python stacks, open-span
   stack, device memory (`deepspeed_tpu/observability/flightrecorder.py`);
2. **SIGKILL** only after the grace window.

The skip record then carries the crash-bundle path and the stalled span name
in ``reason``, plus a structured ``failure_kind`` field:

* ``"hang"``         — the watchdog expired (child killed);
* ``"backend-init"`` — the TPU backend never came up / budget spent waiting;
* ``"crash"``        — the backend dropped mid-run twice despite healthy
  probes.

Parent-side code deliberately imports neither jax nor deepspeed_tpu (backend
init over the tunnel is exactly what hangs), so the bundle lookup re-reads
MANIFEST.json with stdlib json.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional, Tuple

# Substrings marking "the backend/tunnel is down", as opposed to a bug in
# the bench itself. Matched against child stderr.
BACKEND_DOWN_MARKERS = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "TPU backend setup",
    "DEADLINE_EXCEEDED",
    "connection dropped",
    "Socket closed",
    "failed to connect",
)


def skip(metric: str, unit: str, reason: str, failure_kind: str,
         predicted_mfu: Optional[float] = None) -> None:
    """Print the structured skip record and exit 0 (the driver still gets a
    parseable result). ``failure_kind``: hang | backend-init | crash.
    ``predicted_mfu`` carries the STATIC roofline number (computed host-side,
    no TPU) so a tunnel-outage round still reports what the program should
    have achieved — the measured-vs-predicted pairing just loses its
    measured half."""
    print(json.dumps({
        "metric": metric, "value": None, "unit": unit,
        "vs_baseline": None, "skipped": True,
        "failure_kind": failure_kind, "reason": reason[-700:],
        "predicted_mfu": predicted_mfu,
    }))
    sys.exit(0)


def static_prediction(script: str,
                      timeout_s: float = 180.0) -> Optional[float]:
    """The bench's analytic predicted-MFU, computed in a throwaway CPU-only
    subprocess (``BENCH_PREDICT=1`` child mode — the parent stays jax-free
    by design, and forcing ``JAX_PLATFORMS=cpu`` keeps the probe off the
    very tunnel whose outage we are annotating). None when the probe fails
    or times out — a skip record must never block on its annotation."""
    env = dict(os.environ, BENCH_PREDICT="1", JAX_PLATFORMS="cpu")
    env.pop("BENCH_CHILD", None)
    try:
        r = subprocess.run([sys.executable, script], env=env,
                           timeout=timeout_s, capture_output=True, text=True)
        if r.returncode != 0:
            return None
        for line in reversed((r.stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            v = rec.get("predicted_mfu")
            return float(v) if v is not None else None
    except (subprocess.TimeoutExpired, OSError, ValueError):
        return None
    return None


def cost_vector_record(entry: str) -> Optional[Dict]:
    """Static cost vector for a registered audit entry, flattened for
    embedding in a BENCH_*.json record (child-side only — pulls in jax and
    the tools/ tree). The next on-chip round reports measured-vs-predicted
    MFU side by side from this. None when the tools tree is absent, the
    entry never registered, or extraction fails — the bench number itself
    must never depend on the annotation."""
    try:
        import jax

        from tools.tpucost import registry_cost_vector

        vec = registry_cost_vector(
            entry, device_kind=jax.devices()[0].device_kind)
    except Exception:                               # noqa: BLE001
        return None
    if vec is None:
        return None
    m = vec.metrics
    rec = {
        "entry": entry,
        "flops": m.get("flops"),
        "bytes_accessed": m.get("bytes_accessed"),
        "peak_hbm_bytes": m.get("peak_hbm_bytes"),
        "collective_bytes": m.get("collective_bytes"),
        "predicted_step_ms": round(vec.predicted_step_s * 1e3, 4),
        "predicted_mfu": round(vec.mfu_ceiling, 4),
        "bound": vec.bound,
        "program_hash": vec.program_hash[:12],
    }
    if vec.predicted_tokens_per_sec is not None:
        rec["predicted_tokens_per_sec"] = round(
            vec.predicted_tokens_per_sec, 1)
    return rec


def probe_backend(attempts: int = 5, probe_timeout: int = 75,
                  cwd: Optional[str] = None) -> Optional[str]:
    """Try to bring up the jax backend in a throwaway subprocess.

    Returns None on success, else the last failure reason. Backend init on
    the tunnel can HANG as well as raise, so every attempt gets its own
    process + timeout.
    """
    last = "unknown"
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print(jax.default_backend())"],
                timeout=probe_timeout, capture_output=True, text=True,
                cwd=cwd)
            if r.returncode == 0:
                return None
            last = (r.stderr or r.stdout or "probe failed").strip()[-500:]
        except subprocess.TimeoutExpired:
            last = f"backend-init probe timed out after {probe_timeout}s"
        if i < attempts - 1:
            time.sleep(8 * (i + 1))
    return last


def crash_bundle_info(crash_dir: Optional[str],
                      newer_than: Optional[float] = None
                      ) -> Optional[Dict[str, str]]:
    """Newest flight-record bundle under ``crash_dir`` → its path and the
    stalled span from MANIFEST.json (stdlib-only duplicate of
    ``flightrecorder.find_latest_bundle`` so the parent stays jax-free).
    ``newer_than`` (wall seconds) rejects bundles left over from a previous
    round — a child that wedged inside native code dumps nothing, and
    attributing an old bundle to THIS hang would be fabricated evidence."""
    if not crash_dir:
        return None
    try:
        bundles = [os.path.join(crash_dir, d) for d in os.listdir(crash_dir)
                   if os.path.isfile(os.path.join(crash_dir, d,
                                                  "MANIFEST.json"))]
        if newer_than is not None:
            bundles = [b for b in bundles
                       if os.path.getmtime(b) >= newer_than]
        if not bundles:
            return None
        bundle = max(bundles, key=os.path.getmtime)
        with open(os.path.join(bundle, "MANIFEST.json")) as fh:
            manifest = json.load(fh)
        return {"bundle": bundle,
                "stalled_span": manifest.get("stalled_span") or "<none open>"}
    except OSError:
        return None


def fleet_skew_from_metrics(path: Optional[str]) -> Optional[float]:
    """``fleet/step_time_median_s{agg=skew}`` from a metrics JSONL dump —
    the fleet-health smoke field the bench records carry as
    ``step_time_skew`` ((max-median)/median across ranks; 0.0 on a one-rank
    fleet). Stdlib-only (parent-side safe); None when the file or the gauge
    is absent (fleet health off)."""
    if not path or not os.path.exists(path):
        return None
    skew = None
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("type") == "gauge"
                        and rec.get("name") == "fleet/step_time_median_s"
                        and rec.get("labels", {}).get("agg") == "skew"):
                    skew = float(rec["value"])   # latest record wins
    except OSError:
        return None
    return skew


def _signal_group(pid: int, sig: int) -> None:
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError):
        pass


def run_child(script: str, timeout_s: float,
              grace_s: float) -> Tuple[Optional[int], str, str, bool]:
    """Run ``script`` with BENCH_CHILD=1 in its own process GROUP so a
    watchdog kill cannot orphan a hung grandchild holding the TPU.

    Returns (returncode, stdout, stderr, hung). On watchdog expiry the child
    gets SIGUSR1 (flight-record dump) + ``grace_s`` to write it, then
    SIGKILL; ``hung`` is True for that whole path even if the child died of
    the SIGUSR1 itself (no handler ≈ no observability session — still a
    hang, just an evidence-free one)."""
    env = dict(os.environ, BENCH_CHILD="1")
    proc = subprocess.Popen([sys.executable, script],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        sys.stderr.write(err or "")   # forward child diagnostics
        return proc.returncode, out, err, False
    except subprocess.TimeoutExpired:
        _signal_group(proc.pid, signal.SIGUSR1)
        try:
            out, err = proc.communicate(timeout=grace_s)
        except subprocess.TimeoutExpired:
            _signal_group(proc.pid, signal.SIGKILL)
            # collect whatever the child managed to write before the kill —
            # it shows WHERE it hung (backend init vs mid-bench)
            out, err = proc.communicate()
        return None, out or "", err or "", True


def run_watchdogged(metric: str, unit: str, script: str,
                    crash_dir: Optional[str] = None) -> None:
    """Parent mode: run the measurement child immediately; probe/retry only
    after a backend-down failure (a healthy tunnel pays zero extra init).

    The WHOLE parent is bounded by BENCH_TOTAL_BUDGET (default 1500 s) so
    the structured skip record always lands before any outer runner's
    timeout — run_bench_suite.py gives each entry 30 min."""
    start = time.monotonic()
    start_wall = time.time()   # bundle mtimes are wall-clock
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 1500))
    grace = float(os.environ.get("BENCH_SIGUSR1_GRACE", 20))

    def remaining() -> float:
        return budget - (time.monotonic() - start)

    _prediction: list = []   # lazy one-shot cache: probe only when skipping

    def _skip(reason: str, kind: str) -> None:
        if not _prediction:
            t = min(max(remaining(), 0.0), 180.0)
            _prediction.append(static_prediction(script, t)
                               if t >= 30 else None)
        skip(metric, unit, reason, kind, predicted_mfu=_prediction[0])

    first_timeout = float(os.environ.get("BENCH_WATCHDOG_TIMEOUT",
                                         budget * 0.6))
    err = ""
    for attempt in range(2):  # one mid-run tunnel drop gets one retry
        timeout_s = (min(first_timeout, remaining()) if attempt == 0
                     else max(remaining(), 60))
        rc, out, errtxt, hung = run_child(script, timeout_s, grace)
        if hung:
            tail = (errtxt or "").strip().splitlines()[-3:]
            reason = (f"bench run exceeded {timeout_s:.0f}s watchdog; "
                      f"child stderr tail: "
                      f"{' | '.join(tail) if tail else '<empty>'}")
            info = crash_bundle_info(crash_dir, newer_than=start_wall)
            if info:
                reason += (f"; flight record: {info['bundle']} "
                           f"(stalled span: {info['stalled_span']})")
            else:
                reason += "; no flight record found (BENCH_OBS=0, or the " \
                          "child hung before its observability session)"
            _skip(reason, "hang")
        if rc == 0:
            sys.stdout.write(out)
            return
        err = (errtxt or "")[-2000:]
        if not any(m in err for m in BACKEND_DOWN_MARKERS):
            # real bug: surface it — INCLUDING the child's stdout, which may
            # hold a structured partial record (bench_infer's OOM JSON with
            # its single_chip_caveat prints before the re-raise)
            sys.stdout.write(out or "")
            sys.stderr.write(errtxt or "")
            sys.exit(rc)
        if attempt == 0:
            # probe ladder capped at 3 attempts (~4.3 min worst case) to
            # stay inside the budget
            down = probe_backend(attempts=3,
                                 cwd=os.path.dirname(os.path.abspath(script)))
            if down is not None:
                _skip(f"TPU backend unavailable after bounded retries: "
                      f"{down}", "backend-init")
            if remaining() < 120:
                _skip("TPU backend recovered but the run budget is spent; "
                      f"first failure: {err[-300:]}", "backend-init")
    _skip(f"TPU backend dropped twice despite a healthy probe: {err[-400:]}",
          "crash")
