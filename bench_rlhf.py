#!/usr/bin/env python
"""RLHF workload benchmark — the DS-Chat step-3 shape on the hybrid engine.

Reference workload (blogs/deepspeed-chat/README.md:57 benchmark setting):
each RLHF iteration GENERATES a rollout (prompt 256 → 256 new tokens with
the inference engine's KV arena + decode kernel, LoRA adapters applied)
and then TRAINS on the concatenated (prompt+response) sequence — the
hybrid engine flips ONE weight set between the two layouts. The reference's
headline claim is end-to-end RLHF throughput (its e2e figure mixes both
phases); this bench reports each phase plus the flip overhead so
regressions in either layout or in the reshard path are visible.

Prints ONE JSON line: e2e tokens/s (generated+trained tokens per wall
second, the DS-Chat e2e metric shape) plus per-phase rates and flip cost.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from deepspeed_tpu.config.config import load_config
    from deepspeed_tpu.models import create_model
    from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

    preset = os.environ.get("BENCH_RLHF_MODEL", "gpt2-125m")
    batch = int(os.environ.get("BENCH_RLHF_BATCH", 8))
    prompt_len = int(os.environ.get("BENCH_RLHF_PROMPT", 256))
    gen_len = int(os.environ.get("BENCH_RLHF_GEN", 256))
    iters = int(os.environ.get("BENCH_RLHF_ITERS", 4))
    lora_rank = int(os.environ.get("BENCH_RLHF_LORA_RANK", 8))

    seq = prompt_len + gen_len
    model = create_model(preset, dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", max_seq_len=seq)
    cfg = load_config({
        "train_micro_batch_size_per_gpu": batch,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-5}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    })
    engine = HybridEngine(model=model, config=cfg, max_out_tokens=seq)

    # LoRA adapters on the attention out-projections (the DS-Chat actor
    # trains LoRA deltas; generation serves W + scaling*right@left)
    mcfg = model.config
    L, H = mcfg.num_layers, mcfg.hidden_size
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    engine.set_lora({"attn/wo": (
        (jax.random.normal(k1, (L, H, lora_rank), jnp.float32)
         * 0.01).astype(jnp.bfloat16),
        jnp.zeros((L, lora_rank, H), jnp.bfloat16))}, scaling=1.0)

    rng = np.random.RandomState(0)
    prompts = rng.randint(0, mcfg.vocab_size, (batch, prompt_len))

    def one_iter(i):
        t0 = time.perf_counter()
        rollout = np.asarray(engine.generate(
            jnp.asarray(prompts), max_new_tokens=gen_len))
        jax.block_until_ready(rollout)
        t1 = time.perf_counter()
        full = np.concatenate([prompts, rollout[:, :gen_len]], axis=1)
        loss = engine.train_batch(batch={
            "input_ids": jnp.asarray(full[None])})
        float(loss)
        t2 = time.perf_counter()
        return t1 - t0, t2 - t1

    one_iter(0)                      # compile both phases + first flip
    # measure the steady-state flip (train step happened => params stale)
    engine.train_batch(batch={"input_ids": jnp.asarray(
        np.concatenate([prompts, prompts[:, :gen_len]], axis=1)[None])})
    tf = time.perf_counter()
    engine.refresh_inference_params()
    jax.block_until_ready(jax.tree.leaves(engine._infer.params)[0])
    flip_s = time.perf_counter() - tf

    gen_s = train_s = 0.0
    for i in range(iters):
        g, t = one_iter(i + 1)
        gen_s += g
        train_s += t

    gen_tok = batch * gen_len * iters
    train_tok = batch * seq * iters
    e2e = (gen_tok + train_tok) / (gen_s + train_s)
    print(json.dumps({
        "metric": f"{preset}_rlhf_e2e_tokens_per_sec_per_chip",
        "value": round(e2e, 1),
        "unit": "tokens/s",
        "generate_tokens_per_sec": round(gen_tok / gen_s, 1),
        "train_tokens_per_sec": round(train_tok / train_s, 1),
        "flip_seconds": round(flip_s, 4),
        "prompt_len": prompt_len, "gen_len": gen_len, "batch": batch,
        "lora_rank": lora_rank, "iters": iters,
    }))


if __name__ == "__main__":
    main()
