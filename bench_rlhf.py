#!/usr/bin/env python
"""RLHF workload benchmark — the DS-Chat step-3 shape over the hybrid
engine v2, with the rollout phase running through the serving stack.

Each RLHF iteration is generate → score → train → flip
(``deepspeed_tpu/rlhf``): candidate groups of ``--group-n`` samples per
prompt ride ONE prefill + COW forks, prompts share a system prefix
through the prefix cache, the policy's own n-gram drafter speculates over
its rollouts (``--spec ngram``), scoring is two more serving passes over
the same arena, and the weight flip reuses the arena with zero
reallocation and zero recompiles.

Prints ONE JSON line: e2e tokens/s (generated + trained tokens per wall
second, the DS-Chat e2e metric shape) plus the per-phase breakdown, the
flip cost, and a rollout A/B over the SAME prompt set:

  * ``stub``              — the seed-era hybrid path: plain batched
                            ``generate()``, every sample prefills its full
                            prompt, no sharing, no speculation;
  * ``serving_spec_off``  — serving-stack rollouts, speculation suspended
                            (fork + prefix sharing only);
  * ``serving_spec_ngram``— the full path (``--spec off|ngram`` pins one
                            arm instead);
plus a ``--group-n`` A/B (group 1 vs the configured group) showing what
fork reuse buys. The rlhf/* + serving/* metrics are dumped to
``BENCH_metrics_rlhf.jsonl`` (``BENCH_OBS=0`` opts out).

Knobs (env): BENCH_RLHF_MODEL, BENCH_RLHF_PROMPTS (prompts/iteration),
BENCH_RLHF_PROMPT (prompt len), BENCH_RLHF_SYS (shared system-prefix
len), BENCH_RLHF_GEN (response len), BENCH_RLHF_GROUP, BENCH_RLHF_ITERS,
BENCH_RLHF_ROWS (decode rows), BENCH_RLHF_SPEC, BENCH_RLHF_LR.

Like bench.py / bench_infer.py, the measurement runs in a watchdogged
child (``bench_common.py``): a hang gets SIGUSR1 (flight-record dump)
then SIGKILL, and the skip record carries ``failure_kind`` + the bundle
path + the static ``predicted_mfu`` half of the measured-vs-predicted
pairing. The parent imports neither jax nor deepspeed_tpu.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_common import run_watchdogged  # noqa: E402


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def predict_main() -> None:
    """BENCH_PREDICT=1 child mode: the analytic train-phase MFU ceiling
    for this bench's config, host-side (the rollout phase is latency- and
    reuse-bound, not flops-bound — the static pairing covers the train
    half, like bench.py)."""
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning.cost_model import (TpuCostModel,
                                                     peak_flops_for)
    from deepspeed_tpu.models import create_model
    from deepspeed_tpu.profiling import transformer_breakdown

    batch = _env_int("BENCH_RLHF_PROMPTS", 8) * _env_int("BENCH_RLHF_GROUP",
                                                         4)
    seq = _env_int("BENCH_RLHF_PROMPT", 128) + _env_int("BENCH_RLHF_GEN",
                                                        128)
    preset = os.environ.get("BENCH_RLHF_MODEL", "gpt2-125m")
    model = create_model(preset, dtype=jnp.bfloat16, max_seq_len=seq)
    cfg = model.config
    n = transformer_breakdown(cfg, batch, seq).total_params
    flops_per_token = 6 * n + 12 * cfg.num_layers * cfg.hidden_size * seq
    cm = TpuCostModel(model_info={
        "num_params": n, "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers, "seq_length": seq,
        "vocab_size": cfg.vocab_size}, mfu=1.0)
    tps = cm.predict_throughput({"train_micro_batch_size_per_gpu": batch})
    print(json.dumps({
        "predicted_mfu": round(tps * flops_per_token / peak_flops_for(None),
                               4),
        "predicted_tokens_per_sec": round(tps, 1),
        "source": "analytic-roofline",
    }))


def _rollout_arm(collector, prompts, base_iter) -> dict:
    """Time one rollout pass over ``prompts`` (fresh iteration index so
    seeds never collide with the e2e loop's) and return tokens/s + the
    collection stats."""
    # collect() host-materializes every sampled token (np.asarray per
    # iteration + handle.result()), so the window is fenced
    t0 = time.perf_counter()
    batch, _ = collector.collect(prompts, base_iter)
    wall = time.perf_counter() - t0  # tpulint: disable=wallclock-timing-without-sync
    gen = batch.stats["generated_tokens"]
    return {
        "tokens_per_sec": round(gen / max(wall, 1e-9), 1),
        "generated_tokens": gen,
        "wall_s": round(wall, 3),
        "fork_reuse_ratio": round(batch.stats["fork_reuse_ratio"], 4),
        "spec_acceptance_rate": (
            round(batch.stats["spec_acceptance_rate"], 4)
            if batch.stats["spec_acceptance_rate"] is not None else None),
    }


def rlhf_main() -> None:
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.rlhf import RLHFTrainer, RolloutCollector

    preset = os.environ.get("BENCH_RLHF_MODEL", "gpt2-125m")
    n_prompts = _env_int("BENCH_RLHF_PROMPTS", 8)
    prompt_len = _env_int("BENCH_RLHF_PROMPT", 128)
    sys_len = _env_int("BENCH_RLHF_SYS", prompt_len // 2)
    gen_len = _env_int("BENCH_RLHF_GEN", 128)
    group = _env_int("BENCH_RLHF_GROUP", 4)
    iters = _env_int("BENCH_RLHF_ITERS", 2)
    rows = _env_int("BENCH_RLHF_ROWS", max(8, n_prompts * group))
    spec_arg = os.environ.get("BENCH_RLHF_SPEC", "both")
    block = 16
    seq = prompt_len + gen_len
    seq += (-seq) % block

    obs_wanted = os.environ.get("BENCH_OBS", "1") != "0"
    if obs_wanted:
        from deepspeed_tpu.config.config import ObservabilityConfig
        from deepspeed_tpu.observability import configure_observability

        configure_observability(ObservabilityConfig(
            enabled=True,
            output_dir=os.environ.get("BENCH_OBS_DIR",
                                      "bench_results/obs_rlhf")))

    engine = deepspeed_tpu.init_rlhf(
        preset,
        config={
            "train_micro_batch_size_per_gpu": n_prompts * group,
            "steps_per_print": 10 ** 9,
            "optimizer": {"type": "adamw", "params": {
                "lr": float(os.environ.get("BENCH_RLHF_LR", 1e-5))}},
            "bf16": {"enabled": True},
            "rlhf": {"algo": "grpo" if group > 1 else "ppo",
                     "group_n": group, "temperature": 0.7,
                     "max_new_tokens": gen_len},
        },
        serving_config={
            "block_size": block, "max_seqs": rows, "max_model_len": seq,
            "prefill_chunk": 64 if prompt_len >= 64 else block,
            "max_queue": 4 * n_prompts * group,
            "speculative": {"mode": "ngram"},
        })

    rng = np.random.RandomState(0)
    vocab = engine.model.config.vocab_size
    system = rng.randint(0, vocab, (sys_len,))
    tails = rng.randint(0, vocab, (n_prompts, prompt_len - sys_len))
    prompts = [np.concatenate([system, t]).astype(np.int32) for t in tails]

    def prompt_fn(_it):
        return prompts

    def reward_fn(_prompt, tokens):
        return float(len(set(tokens)))

    trainer = RLHFTrainer(engine, prompt_fn, reward_fn)
    serving = engine.serving_engine()

    trainer.step()                      # warmup: compiles + first flip
    for k in trainer._phase_s:
        trainer._phase_s[k] = 0.0
    # the warmup's train bracket must not leak into the timed loop's
    # first data_fn (it would book the train-step compile as train time)
    trainer._last_prepare_end = None
    gen0, trained0 = serving._tokens_out, trainer._tokens_trained
    # trainer.step() ends in float(loss) — every iteration is fenced
    t0 = time.perf_counter()
    for _ in range(iters):
        trainer.step()
    wall = time.perf_counter() - t0  # tpulint: disable=wallclock-timing-without-sync
    # close the trailing train bracket (train() normally does this) with
    # the TRAINER's clock — _last_prepare_end is a trainer.clock()
    # timestamp, and perf_counter shares its epoch only on Linux
    trainer._phase_s["train"] += trainer.clock() \
        - trainer._last_prepare_end
    trainer._last_prepare_end = None
    gen_tok = serving._tokens_out - gen0
    train_tok = trainer._tokens_trained - trained0
    phases = {k: round(v, 3) for k, v in trainer._phase_s.items()}

    # steady-state flip cost, isolated (params stale after the last step)
    tf = time.perf_counter()
    engine.refresh_params()
    jax.block_until_ready(jax.tree.leaves(engine._infer.params)[0])
    flip_s = time.perf_counter() - tf

    # -- rollout A/B over the same prompt set ------------------------------
    arm_iter = 10_000   # seed-space far from the e2e loop's iterations
    ab = {}
    def mk(g):
        return RolloutCollector(serving, group_n=g, temperature=0.7,
                                max_new_tokens=gen_len)

    # warm the plain R×1 decode program (the e2e loop only dispatched the
    # verify path) so no A/B arm pays a first compile in its timed window
    serving.spec_suspended = True
    mk(1).collect([prompts[0]], arm_iter + 9)
    serving.spec_suspended = False

    if spec_arg in ("both", "ngram"):
        serving.spec_suspended = False
        ab["serving_spec_ngram"] = _rollout_arm(mk(group), prompts,
                                                arm_iter)
    if spec_arg in ("both", "off"):
        serving.spec_suspended = True
        ab["serving_spec_off"] = _rollout_arm(mk(group), prompts,
                                              arm_iter + 1)
        serving.spec_suspended = False
    # group-n A/B: what fork reuse buys (group 1 = no sharing besides the
    # prefix cache)
    group_ab = {}
    if group > 1:
        serving.spec_suspended = True
        group_ab["n1"] = _rollout_arm(mk(1), prompts, arm_iter + 2)
        group_ab[f"n{group}"] = _rollout_arm(mk(group), prompts,
                                             arm_iter + 3)
        serving.spec_suspended = False
    # stub arm: the seed-era path — batched plain generate, every sample
    # prefilling its full prompt (no fork, no prefix cache, no spec)
    tiled = np.repeat(np.stack(prompts), group, axis=0)
    t0 = time.perf_counter()
    out = np.asarray(engine.generate(tiled, max_new_tokens=gen_len,
                                     temperature=0.7))
    jax.block_until_ready(out)
    stub_wall = time.perf_counter() - t0
    stub_tok = int(out.shape[0]) * gen_len
    ab["stub"] = {"tokens_per_sec": round(stub_tok / stub_wall, 1),
                  "generated_tokens": stub_tok,
                  "wall_s": round(stub_wall, 3)}

    from deepspeed_tpu.observability import get_session

    obs = get_session()
    metric = f"{preset}_rlhf_e2e_tokens_per_sec_per_chip"
    if obs.enabled:
        obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                             "BENCH_metrics_rlhf.jsonl"),
                         metric=metric)
        obs.close(export=False)
    print(json.dumps({
        "metric": metric,
        "value": round((gen_tok + train_tok) / wall, 1),
        "unit": "tokens/s",
        "vs_baseline": None,
        "generated_tokens_per_sec": round(
            gen_tok / max(phases["rollout"], 1e-9), 1),
        "phase_seconds": phases,
        "flip_seconds": round(flip_s, 4),
        "rollout_ab": ab,
        "group_ab": group_ab,
        "prompt_len": prompt_len, "system_len": sys_len,
        "gen_len": gen_len, "prompts": n_prompts, "group_n": group,
        "iters": iters,
    }))


if __name__ == "__main__":
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--spec" and i + 1 < len(argv):
            os.environ["BENCH_RLHF_SPEC"] = argv[i + 1]
        elif a.startswith("--spec="):
            os.environ["BENCH_RLHF_SPEC"] = a.split("=", 1)[1]
        elif a == "--group-n" and i + 1 < len(argv):
            os.environ["BENCH_RLHF_GROUP"] = argv[i + 1]
        elif a.startswith("--group-n="):
            os.environ["BENCH_RLHF_GROUP"] = a.split("=", 1)[1]
    if os.environ.get("BENCH_RLHF_SPEC", "both") not in ("both", "off",
                                                         "ngram"):
        raise SystemExit("--spec must be 'off', 'ngram' or 'both'")
    if os.environ.get("BENCH_PREDICT") == "1":
        predict_main()
    elif os.environ.get("BENCH_CHILD") == "1":
        rlhf_main()
    else:
        preset = os.environ.get("BENCH_RLHF_MODEL", "gpt2-125m")
        # same metric name as the child's success record, so skip and
        # success records pair under one key
        run_watchdogged(
            f"{preset}_rlhf_e2e_tokens_per_sec_per_chip", "tokens/s",
            os.path.abspath(__file__),
            crash_dir=os.path.join(
                os.environ.get("BENCH_OBS_DIR", "bench_results/obs_rlhf"),
                "crash"))
