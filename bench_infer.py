#!/usr/bin/env python
"""Inference benchmark — TTFT + decode throughput (BASELINE tracked config #5,
the driver's "DS-Inference p50 TTFT" metric).

Prints ONE JSON line:
    {"metric": ..., "value": <p50 TTFT ms>, "unit": "ms",
     "decode_tokens_per_sec": ..., "roofline_frac": ..., "vs_baseline": ...}

``--serving`` (or BENCH_INFER_MODE=serving): continuous-batching load test
instead — synthetic Poisson arrivals with mixed prompt lengths through
``ServingEngine`` (deepspeed_tpu/serving), reporting TTFT p50/p99, time per
output token, tokens/s and arena occupancy, with the serving/* metrics
dumped to BENCH_metrics_serve.jsonl. ``--paged-kernel on|off`` pins one
read path; unset runs the A/B (Pallas paged kernels vs dense gather view)
over the same trace plus a prefix-reuse workload (shared 1k-token system
prompt, two rounds), recording the TTFT/TPOT deltas and each arm's tpucost
arena-read bytes. ``--spec ngram|draft`` runs a speculative-decoding A/B
instead (that drafter vs spec-off, SAME trace with a repetitive-text
share): acceptance rate, proposed-vs-emitted tokens,
emitted-per-target-dispatch, drafter time share, TTFT/TPOT deltas and the
per-arm verify-program tpucost land in the record and
BENCH_metrics_serve.jsonl. ``--fleet N`` routes the same trace through a
``FleetRouter`` over N serving replicas instead: a routing-policy A/B
(round-robin vs KV-occupancy-aware) against a single-engine baseline,
with per-replica peak occupancy, routing decisions by reason, and —
with ``--disagg`` (prefill/decode pools + KV block handoff) — the
handoff latency p50/p99 in the record. ``--chaos plan.json`` (fleet mode
only) arms the same deterministic fault plans the chaos_serve gate uses
(replica_kill / replica_slow / replica_flap / handoff_fail, steps =
post-warmup router iterations) and records the self-healing ledger —
deaths, quarantines, revivals, mean time-to-revival (iterations), shed
rate — per arm; ``--deadline S`` gives every request a deadline so
admission-control shedding engages. Knobs (env): BENCH_SERVE_REQUESTS,
BENCH_SERVE_RATE (req/s), BENCH_SERVE_PROMPT (max prompt len),
BENCH_SERVE_NEW, BENCH_SERVE_ROWS, BENCH_SERVE_BLOCK, BENCH_SERVE_BLOCKS,
BENCH_SERVE_LEN, BENCH_SERVE_CHUNK, BENCH_SERVE_SYS (shared-prefix len),
BENCH_SERVE_PREFIX_REQS, BENCH_SERVE_PAGED_KERNEL (= the flag),
BENCH_SERVE_SPEC (= --spec), BENCH_SERVE_SPEC_K (draft tokens/iteration),
BENCH_SERVE_DRAFT_MODEL (draft-arm model), BENCH_SERVE_REPEAT
(repetitive-prompt fraction; default 0.5 when speculating, else 0).

Decode is HBM-bandwidth-bound: the roofline is
    BW / (param_bytes + live-KV bytes per token);
``vs_baseline`` reports achieved/roofline — 1.0 == the chip's memory system
is saturated (the analog of the reference's kernel-injected decode claim).

Model: largest preset that fits the attached chip (env BENCH_INFER_MODEL to
override; weights are random — zero-egress environment — which does not
change the memory-bound timing).

Like bench.py, the measurement runs in a watchdogged child
(``bench_common.py``): a hang gets SIGUSR1 (flight-record dump) then
SIGKILL, and the skip record carries ``failure_kind`` + the bundle path.
The parent imports neither jax nor deepspeed_tpu — backend init over the
tunnel is exactly what hangs.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_common import run_watchdogged  # noqa: E402

def hbm_bandwidth() -> float:
    """Attached chip's HBM bytes/s — the shared cost-model table, so the
    measured roofline_frac and tpucost's predicted numbers can never be
    computed against different bandwidths."""
    import jax

    from deepspeed_tpu.autotuning.cost_model import hbm_bw_for

    return hbm_bw_for(jax.devices()[0].device_kind)


def predict_main() -> None:
    """BENCH_PREDICT=1 child mode: the analytic decode roofline for this
    bench's config, host-side (no engine, no params — weight bytes come
    from the analytic param count, KV bytes from ``cache_memory_bytes``).
    Decode MFU is tiny by nature (memory-bound); the number still pins the
    skip record to THIS config's ceiling."""
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning.cost_model import (hbm_bw_for,
                                                     peak_flops_for)
    from deepspeed_tpu.inference import cache_memory_bytes
    from deepspeed_tpu.models import create_model
    from deepspeed_tpu.profiling import transformer_breakdown

    model_name = os.environ.get("BENCH_INFER_MODEL", "llama-7b")
    prompt_len = int(os.environ.get("BENCH_INFER_PROMPT", 512))
    n_new = int(os.environ.get("BENCH_INFER_NEW", 64))
    dtype_name = os.environ.get("BENCH_INFER_DTYPE", "bf16")
    model = create_model(model_name, dtype=jnp.bfloat16)
    cfg = model.config
    n = transformer_breakdown(cfg, 1, 1).total_params
    weight_bytes = {"int8": 1.0, "w8a8": 1.0,
                    "int4": 0.5, "w4a8": 0.5}.get(dtype_name, 2.0)
    live = prompt_len + n_new // 2
    # KV stays bf16 for every allowed BENCH_INFER_DTYPE: the quant modes are
    # weight-storage-only and InferenceConfig normalizes their compute/arena
    # dtype to bf16 — matching main()'s engine.config.dtype sizing
    kv = cache_memory_bytes(cfg, 1, live, jnp.bfloat16)
    roofline_tps = hbm_bw_for(None) / (n * weight_bytes + kv)
    print(json.dumps({
        # ~2N matmul flops per decoded token against the chip's peak
        "predicted_mfu": round(roofline_tps * 2 * n / peak_flops_for(None),
                               6),
        "predicted_decode_tokens_per_sec": round(roofline_tps, 1),
        "source": "analytic-roofline",
    }))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference import init_inference

    # TTFT / decode spans and kv-cache metrics land in a metrics JSONL next
    # to the BENCH record so the trajectory keeps per-phase breakdowns
    # (BENCH_OBS=0 opts out)
    if os.environ.get("BENCH_OBS", "1") == "1":
        from deepspeed_tpu.config.config import ObservabilityConfig
        from deepspeed_tpu.observability import configure_observability

        configure_observability(ObservabilityConfig(
            enabled=True,
            output_dir=os.environ.get("BENCH_OBS_DIR",
                                      "bench_results/obs_infer")))

    model_name = os.environ.get("BENCH_INFER_MODEL", "llama-7b")
    prompt_len = int(os.environ.get("BENCH_INFER_PROMPT", 512))
    n_new = int(os.environ.get("BENCH_INFER_NEW", 64))
    arena = int(os.environ.get("BENCH_INFER_ARENA", 1024))
    # 'int8'/'int4' => weight-only quantized storage (compute bf16): halves/
    # quarters the weight side of the decode roofline denominator
    dtype_name = os.environ.get("BENCH_INFER_DTYPE", "bf16")
    if dtype_name not in ("bf16", "int8", "int4", "w8a8", "w4a8"):
        raise SystemExit(f"BENCH_INFER_DTYPE must be bf16|int8|int4|w8a8|"
                         f"w4a8, got '{dtype_name}' — refusing to run a "
                         "mislabelled benchmark")
    dtype = jnp.bfloat16 if dtype_name == "bf16" else dtype_name

    try:
        engine = init_inference(model_name, dtype=dtype, max_out_tokens=arena)
        cfg = engine.model.config
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, cfg.vocab_size, (1, prompt_len))

        # warmup (compiles prefill + decode)
        engine.generate(prompt, max_new_tokens=n_new)
    except Exception as e:  # noqa: BLE001 — structured OOM record below
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            print(json.dumps({
                "metric": f"{model_name}_{dtype_name}_p50_ttft_ms",
                "value": None, "unit": "ms", "vs_baseline": None,
                "oom": True,
                "single_chip_caveat": (
                    f"{model_name} at {dtype_name} exceeds one chip's HBM "
                    "(use int8/int4 weight storage or TP>1)"),
                "reason": msg[-300:],
            }))
        raise

    ttfts = []
    t_all = []
    for _ in range(5):
        t0 = time.perf_counter()
        out, ttft = engine.generate(prompt, max_new_tokens=n_new,
                                    return_ttft=True)
        np.asarray(out)  # fence
        t_all.append(time.perf_counter() - t0)
        ttfts.append(ttft)
    p50_ttft = sorted(ttfts)[len(ttfts) // 2]
    p50_all = sorted(t_all)[len(t_all) // 2]
    decode_tps = (n_new - 1) / (p50_all - p50_ttft)

    param_bytes = sum(int(p.size) * p.dtype.itemsize
                      for p in jax.tree.leaves(engine.params))
    # live KV read per decode token (valid region ~ prompt + half the gen);
    # sized at the ENGINE's arena dtype — the roofline denominator must not
    # silently assume bf16 for an fp16/fp32 engine
    from deepspeed_tpu.inference import cache_memory_bytes

    live = prompt_len + n_new // 2
    kv_bytes = cache_memory_bytes(cfg, 1, live, engine.config.dtype)
    roofline_tps = hbm_bandwidth() / (param_bytes + kv_bytes)
    frac = decode_tps / roofline_tps

    from deepspeed_tpu.observability import get_session

    obs = get_session()
    if obs.enabled:
        obs.registry.gauge("bench/p50_ttft_ms").set(p50_ttft * 1e3)
        obs.registry.gauge("bench/decode_tokens_per_sec").set(decode_tps)
        obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                             "BENCH_metrics_infer.jsonl"),
                         metric=f"{model_name}_{dtype_name}_p50_ttft_ms")
        obs.export_chrome_trace()
        obs.close(export=False)   # already exported to the bench paths

    record = {
        "metric": f"{model_name}_{dtype_name}_p50_ttft_ms",
        "value": round(p50_ttft * 1e3, 2),
        "unit": "ms",
        "decode_tokens_per_sec": round(decode_tps, 1),
        "roofline_frac": round(frac, 4),
        "vs_baseline": round(frac, 4),
    }
    # static cost vectors for the prefill/decode programs generate() just
    # ran (registered with the audit registry at first generate); the next
    # on-chip round reports measured-vs-predicted side by side
    if os.environ.get("BENCH_COST", "1") == "1":
        from bench_common import cost_vector_record

        cost = cost_vector_record("inference/decode")
        if cost is not None:
            record["tpucost"] = cost
            prefill = cost_vector_record("inference/prefill")
            if prefill is not None:
                record["tpucost_prefill"] = prefill
    print(json.dumps(record))


def _serve_load(srv, prompts, arrivals, n_new, deadline_s=None):
    """Drive one Poisson-arrival load through a ServingEngine (or a
    FleetRouter — same surface). Returns (handles, wall_seconds,
    admission_sheds): with ``deadline_s`` set, a fleet under pressure may
    shed deadline-infeasible submissions with ``Overloaded`` — those count
    as sheds, not handles."""
    from deepspeed_tpu.serving.fleet import Overloaded

    t0 = time.perf_counter()
    handles = []
    sheds = 0
    i = 0
    n_requests = len(prompts)
    while i < n_requests or srv.in_flight():
        # every srv.step() host-materializes its sampled tokens
        # (np.asarray inside the iteration) — the clock reads below are
        # fenced by construction, the linter just can't see through step()
        now = time.perf_counter() - t0  # tpulint: disable=wallclock-timing-without-sync
        while i < n_requests and arrivals[i] <= now:
            try:
                handles.append(srv.submit(prompts[i], max_new_tokens=n_new,
                                          deadline_s=deadline_s))
            except Overloaded:
                sheds += 1
            i += 1
        if srv.in_flight():
            srv.step()
        elif i < n_requests:
            time.sleep(min(arrivals[i] - now, 0.01))
    wall = time.perf_counter() - t0  # tpulint: disable=wallclock-timing-without-sync
    return handles, wall, sheds


def _configure_bench_obs(tune=False, ttft_slo_ms=0.0, tpot_slo_ms=0.0):
    from deepspeed_tpu.config.config import (ObservabilityConfig,
                                             ProfilingConfig, TuneConfig)
    from deepspeed_tpu.observability import configure_observability

    tune_cfg = TuneConfig()
    if tune:
        # the tuned A/B arm: store + controller on, cadence short enough
        # to act within a bench-scale trace
        tune_cfg = TuneConfig(
            enabled=True, controller=True,
            interval_iterations=int(
                os.environ.get("BENCH_SERVE_TUNE_INTERVAL", 8)),
            hold_iterations=int(
                os.environ.get("BENCH_SERVE_TUNE_HOLD", 16)))
    # BENCH_PROFILE=1: deep-profiler capture windows during the serving
    # trace — scheduled every BENCH_PROFILE_EVERY iterations (plus any
    # telemetry triggers), with profile_summary.json's measured-vs-
    # predicted rows landing next to the bench record
    prof_cfg = ProfilingConfig()
    if os.environ.get("BENCH_PROFILE", "0") == "1":
        prof_cfg = ProfilingConfig(
            enabled=True,
            profile_every_steps=int(
                os.environ.get("BENCH_PROFILE_EVERY", 64)),
            window_iterations=int(
                os.environ.get("BENCH_PROFILE_WINDOW", 8)))
    configure_observability(ObservabilityConfig(
        enabled=True,
        output_dir=os.environ.get("BENCH_OBS_DIR",
                                  "bench_results/obs_serve"),
        # request traces (BENCH_TRACE=0 opts out): head-sample everything —
        # the arm dumps Chrome timelines for its top-3 TTFT outliers
        request_tracing=os.environ.get("BENCH_TRACE", "1") == "1",
        # per-iteration serving wall-time buckets; the arm records carry
        # the bucket shares and the gauges land in the metrics JSONL
        serve_goodput=True,
        # nonzero only for the autotune A/B: burn rates are its outcome
        # metric AND the live tuner's input signal
        serve_ttft_slo_ms=ttft_slo_ms,
        serve_tpot_slo_ms=tpot_slo_ms,
        tune=tune_cfg, profiling=prof_cfg))


def _arm_observability_stats(stats, tag, accts):
    """Fold the observability arm outputs into one arm's stats dict: the
    serve_goodput bucket shares (per accountant) and a Chrome trace of the
    top-3 TTFT-outlier request timelines (BENCH_TRACE=0 opt-out)."""
    from deepspeed_tpu.observability import get_session

    obs = get_session()
    if not obs.enabled:
        return
    shares = {rep: a.bucket_shares() for rep, a in accts if a is not None}
    if shares:
        stats["serve_goodput"] = (next(iter(shares.values()))
                                  if len(shares) == 1 else shares)
    if obs.reqtrace is not None:
        path = os.path.join(obs.output_dir, f"trace_top_{tag}.json")
        top = obs.reqtrace.export_chrome_top(path, k=3, key="ttft_ms")
        if top:
            stats["trace_outliers"] = {"chrome_trace": path,
                                       "trace_ids": top}


def _load_stats(handles, wall):
    """Latency/throughput aggregation shared by the single-engine and
    fleet arms — one implementation so the numbers the fleet record is
    compared against are computed identically. Requests that never
    streamed a token (shed from the queue / expired deadlines under a
    chaos plan) have no TTFT and stay out of the percentiles."""
    from deepspeed_tpu.serving.api import _percentile as p

    ttfts = sorted(h.ttft_s for h in handles if h.ttft_s is not None)
    tpots = sorted(h.tpot_s for h in handles if h.tpot_s is not None)
    total_tokens = sum(len(h.tokens) for h in handles)
    return {
        "p50_ttft_ms": round(p(ttfts, 0.50) * 1e3, 2) if ttfts else None,
        "p99_ttft_ms": round(p(ttfts, 0.99) * 1e3, 2) if ttfts else None,
        "tpot_ms": round(p(tpots, 0.50) * 1e3, 3) if tpots else None,
        "tokens_per_sec": round(total_tokens / wall, 1),
        "requests_per_sec": round(len(handles) / wall, 2),
    }


def _serve_one_mode(engine, scfg_kwargs, paged_kernel, prompts, arrivals,
                    prefix_prompts, n_new, block, enable_obs=False,
                    spec_mode="off", draft_engine=None, deadline_s=None):
    """One A/B arm: build a ServingEngine with ``paged_kernel`` (and
    optionally a speculative-decoding arm via ``spec_mode``), run the
    Poisson load, then the prefix-reuse workload (every request shares one
    long system prompt — round 2 should hit the prefix cache). Returns the
    arm's stats dict. ``enable_obs`` turns the observability session on
    for THIS arm, strictly AFTER its warmup — compile-scale TTFTs never
    land in the serving histograms, and the metrics JSONL describes
    exactly one configuration (the primary arm), not a blend of both."""
    import numpy as np

    from deepspeed_tpu.serving import ServingConfig, ServingEngine
    from deepspeed_tpu.serving.api import _percentile as p

    spec_cfg = {"mode": spec_mode}
    if spec_mode != "off":
        spec_cfg["num_draft_tokens"] = int(
            os.environ.get("BENCH_SERVE_SPEC_K", 4))
    srv = ServingEngine(engine, ServingConfig(paged_kernel=paged_kernel,
                                              speculative=spec_cfg,
                                              **scfg_kwargs),
                        draft_engine=draft_engine)
    # warmup: compile the serving programs off the clock, BEFORE the
    # observability session exists
    srv.submit(prompts[0][: max(block, 8)], max_new_tokens=2).result()
    if enable_obs:
        _configure_bench_obs()
    srv.reset_latency_stats()

    handles, wall, _ = _serve_load(srv, prompts, arrivals, n_new,
                                   deadline_s=deadline_s)
    stats = _load_stats(handles, wall)
    if deadline_s is not None:
        stats["deadline_exceeded"] = srv.sched.deadline_exceeded_count
    stats.update({
        "arena_peak_blocks": srv.alloc.peak_in_use,
        "arena_peak_occupancy": round(
            srv.alloc.peak_in_use / srv.alloc.capacity, 4),
        "preemptions": srv.sched.preemption_count,
    })
    if spec_mode != "off":
        # the proposed-vs-emitted ledger: how many tokens each target
        # dispatch actually bought (> 1 is the speculative win)
        stats["spec"] = {
            "mode": spec_mode,
            "proposed_tokens": srv._spec_proposed,
            "accepted_tokens": srv._spec_accepted,
            "acceptance_rate": round(
                srv._spec_accepted / max(srv._spec_proposed, 1), 4),
            "emitted_tokens": srv._spec_emitted,
            "verify_dispatches": srv._spec_dispatches,
            "emitted_per_dispatch": round(
                srv._spec_emitted / max(srv._spec_dispatches, 1), 3),
            "draft_time_share": round(
                srv._spec_draft_s
                / max(srv._spec_draft_s + srv._spec_verify_s, 1e-9), 4),
            "pressure_disabled_rows": srv._spec_disabled_rows,
        }
    # prefix-reuse workload: round 1 populates the cache, round 2 (same
    # shared system prompt, fresh tails) should skip the shared chunks —
    # the TTFT ratio IS the prefix-sharing win
    if prefix_prompts:
        # snapshot the counters so the reported rate describes the reuse
        # workload alone, not the (mostly-miss) Poisson load before it
        hit0 = srv.sched.prefix_hit_tokens
        look0 = srv.sched.prefix_lookup_tokens
        r1, _, _ = _serve_load(srv, prefix_prompts[0],
                               np.zeros(len(prefix_prompts[0])), n_new)
        r2, _, _ = _serve_load(srv, prefix_prompts[1],
                               np.zeros(len(prefix_prompts[1])), n_new)
        ttft1 = sorted(h.ttft_s for h in r1)
        ttft2 = sorted(h.ttft_s for h in r2)
        stats["prefix_reuse"] = {
            "cold_p50_ttft_ms": round(p(ttft1, 0.50) * 1e3, 2),
            "warm_p50_ttft_ms": round(p(ttft2, 0.50) * 1e3, 2),
            "prefix_hit_rate": round(
                (srv.sched.prefix_hit_tokens - hit0)
                / max(srv.sched.prefix_lookup_tokens - look0, 1), 4),
            "blocks_shared_peak": srv.alloc.peak_shared,
            "cow_copies": srv._cow_copies,
        }
    if os.environ.get("BENCH_COST", "1") == "1":
        # the cost vector of THIS arm's registered serving/decode program —
        # bytes_accessed is the arena-read traffic the A/B is about
        from bench_common import cost_vector_record

        cost = cost_vector_record("serving/decode")
        if cost is not None:
            stats["tpucost"] = cost
        if spec_mode != "off":
            # the R×(K+1) verify program this arm actually dispatched —
            # its static cost against the R×1 decode is the speculative
            # FLOPs overhead the acceptance rate has to amortize
            vcost = cost_vector_record("serving/verify")
            if vcost is not None:
                stats["tpucost_verify"] = vcost
    if enable_obs:
        _arm_observability_stats(
            stats, f"{paged_kernel}_{spec_mode}",
            [("0", srv._serve_acct)])
    srv.close()
    return stats


def _serve_autotune_arm(engine, scfg_kwargs, paged_kernel, prompts,
                        arrivals, n_new, block, fleet_n, tuned,
                        ttft_slo_ms=50.0, tpot_slo_ms=3.0,
                        deadline_s=None):
    """One closed-loop A/B arm: the SAME engine config and Poisson trace
    (with its mid-trace load shift) either static (``tuned=False``) or
    with the live tuner walking knobs against measured burn. Both arms
    own an observability session (burn is the measured outcome); only the
    tuned arm's session carries the time-series store + controller, and it
    runs LAST so the exported metrics JSONL describes the tuned fleet.
    Returns ``(stats, token_streams)`` — the streams feed the bit-exactness
    check (data-only knobs must not change a single sampled token)."""
    from deepspeed_tpu.serving import ServingConfig, ServingEngine

    scfg = ServingConfig(paged_kernel=paged_kernel, **scfg_kwargs)
    if fleet_n:
        from deepspeed_tpu.config.config import FleetConfig
        from deepspeed_tpu.serving.fleet import FleetRouter, build_replicas

        replicas = build_replicas(engine, scfg, fleet_n)
        srv = FleetRouter(replicas, FleetConfig(policy="kv_occupancy"))
        engines = [r.engine for r in replicas]
    else:
        srv = ServingEngine(engine, scfg)
        engines = [srv]
    # warmup: compile off the clock, BEFORE the observability session —
    # the tuner must never see (or cause) a compile
    srv.submit(prompts[0][: max(block, 8)], max_new_tokens=2).result()
    _configure_bench_obs(tune=tuned, ttft_slo_ms=ttft_slo_ms,
                         tpot_slo_ms=tpot_slo_ms)
    srv.reset_latency_stats()

    handles, wall, sheds = _serve_load(srv, prompts, arrivals, n_new,
                                       deadline_s=deadline_s)
    stats = _load_stats(handles, wall)
    streams = [list(map(int, h.tokens)) for h in handles]
    # measured outcome: worst-replica burn + mean goodput fraction from
    # the serve_goodput accountants (the same signals the tuner read)
    accts = [e._serve_acct for e in engines if e._serve_acct is not None]
    totals = [a.totals() for a in accts]
    if totals:
        # burn keys are absent until a request finished in the window
        stats["slo_burn"] = {
            "ttft": round(max(t.get("ttft_slo_burn_rate", 0.0)
                              for t in totals), 4),
            "tpot": round(max(t.get("tpot_slo_burn_rate", 0.0)
                              for t in totals), 4),
            "goodput_fraction": round(
                sum(t["goodput_fraction"] for t in totals) / len(totals),
                4),
        }
    if sheds:
        stats["admission_sheds"] = sheds
    tuner = srv._tuner
    if tuned and tuner is not None:
        rep = tuner.report()
        stats["autotune"] = {
            "moves": rep["moves"],
            "rollbacks": rep["rollbacks"],
            "knobs_final": rep["knobs"],
            "objective": {"initial": rep["objective_initial"],
                          "last": rep["objective_last"]},
            # the knob trajectory, decision by decision
            "trajectory": [
                {"iteration": d["iteration"], "kind": d["kind"],
                 "knob": d["knob"], "action": d["action"],
                 "reason": d["reason"], "from": d["from"], "to": d["to"]}
                for d in rep["decisions"]],
        }
        from deepspeed_tpu.observability import get_session

        obs = get_session()
        if obs.enabled:
            stats["autotune"]["recommendations_file"] = (
                tuner.export_recommendations(os.path.join(
                    obs.output_dir,
                    obs.config.tune.recommendations_file)))
    srv.close()
    return stats, streams


def _serve_fleet_arm(engine, scfg_kwargs, paged_kernel, n, policy, disagg,
                     prompts, arrivals, n_new, block, enable_obs=False,
                     chaos_plan=None, deadline_s=None):
    """One fleet arm: N serving replicas behind a FleetRouter under
    ``policy`` (optionally split into prefill/decode pools), driven through
    the SAME Poisson trace — and the same ``paged_kernel`` read path — as
    the single-engine baseline. Returns the arm's stats dict: fleet-level
    TTFT/TPOT/throughput, per-replica peak occupancy, routing decisions by
    reason, and (disagg) the KV-handoff latency histogram.

    ``chaos_plan`` (``--chaos plan.json``) arms the router's fault
    injector AFTER warmup — plan steps are post-warmup router iterations —
    and the arm's record gains the self-healing ledger: deaths,
    quarantines, time-to-revival (iterations dead), shed rate."""
    from deepspeed_tpu.config.config import FleetConfig
    from deepspeed_tpu.serving import ServingConfig
    from deepspeed_tpu.serving.api import _percentile as p
    from deepspeed_tpu.serving.fleet import (ROLE_DECODE, ROLE_PREFILL,
                                             FleetRouter, build_replicas)

    roles = None
    if disagg:
        n_prefill = max(n // 2, 1)
        roles = ([ROLE_PREFILL] * n_prefill
                 + [ROLE_DECODE] * (n - n_prefill))
    replicas = build_replicas(
        engine, ServingConfig(paged_kernel=paged_kernel, **scfg_kwargs), n,
        roles=roles)
    router = FleetRouter(replicas, FleetConfig(policy=policy))
    # warmup: compile the serving (and, disagg, the kv_export/kv_import
    # handoff) programs off the clock, BEFORE the observability session
    router.submit(prompts[0][: max(block, 8)], max_new_tokens=2).result()
    if enable_obs:
        _configure_bench_obs()
    # drops the warmup handoff's compile-scale latency sample too
    router.reset_latency_stats()
    if chaos_plan is not None:
        # armed strictly after warmup, with the iteration counter zeroed:
        # plan steps mean "measured-load iterations", never compile time
        from deepspeed_tpu.observability.faultinject import FaultInjector

        router._injector = FaultInjector(plan=chaos_plan, rank=0,
                                         restart=0)
        router._iterations = 0
    # ledger baseline: the warmup submit is pre-measurement traffic and
    # must stay out of the chaos shed-rate denominator
    submitted0 = router.submitted_count

    handles, wall, admission_sheds = _serve_load(
        router, prompts, arrivals, n_new, deadline_s=deadline_s)
    stats = _load_stats(handles, wall)
    if chaos_plan is not None:
        # drive the healing loop to quiescence so time-to-revival and the
        # ledger describe a CLOSED loop, not a snapshot mid-remediation
        for _ in range(256):
            router.step()
            if all(r.alive or r.retired for r in router.replicas):
                break
        attempts = (router.submitted_count - submitted0) + admission_sheds
        stats["chaos"] = {
            "deaths": router._death_count,
            "quarantines": router._quarantine_count,
            "revivals": router._revival_count,
            "graduations": router._graduation_count,
            "retirements": sum(r.retired for r in router.replicas),
            "resubmits": router._resubmit_count,
            "handoff_failures": router._handoff_failures,
            "time_to_revival_iters": (
                round(sum(router._revive_iters)
                      / len(router._revive_iters), 1)
                if router._revive_iters else None),
            "shed": {
                "admission": admission_sheds,
                "degraded": router.shed_count_total,
                "rate": round((admission_sheds
                               + router.shed_count_total)
                              / max(attempts, 1), 4)},
            "degraded_mode_final": router.degraded_mode,
        }
    stats.update({
        "policy": policy,
        "per_replica": [
            {"replica": r.index, "role": r.role,
             "peak_blocks": r.engine.alloc.peak_in_use,
             "peak_occupancy": round(
                 r.engine.alloc.peak_in_use / r.engine.alloc.capacity, 4),
             "preemptions": r.engine.sched.preemption_count,
             "handoffs_out": r.engine.sched.handoffs_out}
            for r in replicas],
        "routing_decisions": {
            f"{pol}/{reason}": int(c)
            for (pol, reason), c in sorted(router._decisions.items())},
    })
    if disagg:
        xs = sorted(router._handoff_ms)
        stats["handoffs"] = {
            "count": len(xs),
            "fallbacks": router._handoff_fallbacks,
            "p50_ms": round(p(xs, 0.50), 3) if xs else None,
            "p99_ms": round(p(xs, 0.99), 3) if xs else None,
        }
    if enable_obs:
        _arm_observability_stats(
            stats, f"fleet{n}_{policy}",
            [(str(r.index), r.engine._serve_acct) for r in replicas])
    router.close()
    return stats


def serving_main() -> None:
    """Continuous-batching load test: Poisson arrivals over a synthetic
    request trace, real-time injected between scheduler iterations.
    ``--paged-kernel on|off`` pins one read path; unset runs the A/B
    (paged kernels vs dense gather view) over the same trace and reports
    the TTFT/TPOT deltas plus each arm's tpucost arena-read bytes."""
    import numpy as np

    model_name = os.environ.get("BENCH_INFER_MODEL", "llama-7b")
    dtype_name = os.environ.get("BENCH_INFER_DTYPE", "bf16")
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", 32))
    rate = float(os.environ.get("BENCH_SERVE_RATE", 8.0))      # req/s
    prompt_max = int(os.environ.get("BENCH_SERVE_PROMPT", 256))
    n_new = int(os.environ.get("BENCH_SERVE_NEW", 32))
    rows = int(os.environ.get("BENCH_SERVE_ROWS", 8))
    block = int(os.environ.get("BENCH_SERVE_BLOCK", 16))
    sys_len = int(os.environ.get("BENCH_SERVE_SYS", 1024))   # shared prefix
    prefix_reqs = int(os.environ.get("BENCH_SERVE_PREFIX_REQS", 8))
    max_len = int(os.environ.get("BENCH_SERVE_LEN",
                                 max(prompt_max, sys_len + 32) + n_new))
    max_len = -(-max_len // block) * block      # whole-block budget
    num_blocks = int(os.environ.get("BENCH_SERVE_BLOCKS",
                                    rows * (max_len // block) * 3 // 4))
    chunk = int(os.environ.get("BENCH_SERVE_CHUNK", max(block, 64)))
    chunk = -(-chunk // block) * block
    ab_flag = os.environ.get("BENCH_SERVE_PAGED_KERNEL", "")
    # primary arm LAST: the observability session turns on just before it
    modes = {"on": ["auto"], "off": ["off"]}.get(ab_flag, ["off", "auto"])
    spec_flag = os.environ.get("BENCH_SERVE_SPEC", "off")
    if spec_flag not in ("off", "ngram", "draft"):
        raise SystemExit("--spec must be 'off', 'ngram' or 'draft'")
    fleet_n = int(os.environ.get("BENCH_SERVE_FLEET", "0"))
    disagg = os.environ.get("BENCH_SERVE_DISAGG", "0") == "1"
    if fleet_n < 0:
        raise SystemExit("--fleet needs N >= 0 (0, the default, disables "
                         "fleet mode)")
    if disagg and fleet_n < 2:
        raise SystemExit("--disagg needs --fleet N with N >= 2 "
                         "(at least one prefill and one decode replica)")
    if fleet_n and spec_flag != "off":
        raise SystemExit("--fleet and --spec are separate A/Bs — "
                         "run them in two invocations")
    chaos_spec = os.environ.get("BENCH_SERVE_CHAOS", "")
    chaos_plan = None
    if chaos_spec:
        if not fleet_n:
            raise SystemExit("--chaos drives the FLEET's self-healing "
                             "loop — pair it with --fleet N")
        from deepspeed_tpu.observability.faultinject import load_plan

        # validates the plan up front; a bare path means @path
        chaos_plan = load_plan(
            chaos_spec if chaos_spec.startswith(("@", "[", "{"))
            else "@" + chaos_spec)
    deadline_env = os.environ.get("BENCH_SERVE_DEADLINE", "")
    deadline_s = float(deadline_env) if deadline_env else None
    if spec_flag != "off":
        # the speculative A/B replaces the paged-kernel A/B: both spec
        # arms run the SAME read path (primary) over the SAME trace
        modes = modes[-1:]
    repeat_frac = float(os.environ.get(
        "BENCH_SERVE_REPEAT", 0.5 if spec_flag != "off" else 0.0))

    import jax.numpy as jnp

    from deepspeed_tpu.inference import init_inference

    dtype = jnp.bfloat16 if dtype_name == "bf16" else dtype_name
    metric = f"{model_name}_{dtype_name}_serving_p50_ttft_ms"
    scfg_kwargs = dict(block_size=block, num_blocks=num_blocks,
                       max_seqs=rows, max_model_len=max_len,
                       prefill_chunk=chunk,
                       max_queue=max(2 * n_requests, 64))
    try:
        engine = init_inference(model_name, dtype=dtype,
                                max_out_tokens=max_len)
        cfg = engine.model.config
        rng = np.random.RandomState(0)
        # mixed lengths: uniform over [prompt_max/4, prompt_max]
        lens = rng.randint(max(prompt_max // 4, 1), prompt_max + 1,
                           size=n_requests)
        prompts = [rng.randint(0, cfg.vocab_size, (int(n),)) for n in lens]
        # repetitive-text share (speculation workload: prompt-lookup and
        # draft acceptance both feed on repeated structure) — same trace
        # for every arm, so deltas are apples-to-apples
        for i in range(int(round(repeat_frac * n_requests))):
            pat = rng.randint(0, cfg.vocab_size, (rng.randint(4, 12),))
            prompts[i] = np.tile(pat, -(-int(lens[i]) // pat.size)
                                 )[:int(lens[i])]
        arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
        # prefix-reuse workload: a shared system prompt + short unique
        # tails, two rounds with DIFFERENT tails (only the prefix repeats)
        sys_len = min(sys_len, max_len - n_new - 32)
        system = rng.randint(0, cfg.vocab_size, (sys_len,))
        prefix_prompts = [
            [np.concatenate([system,
                             rng.randint(0, cfg.vocab_size, (8 + r,))])
             for r in range(prefix_reqs)]
            for _ in range(2)] if sys_len >= block else []
    except Exception as e:  # noqa: BLE001 — structured OOM record
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            print(json.dumps({
                "metric": metric, "value": None, "unit": "ms",
                "vs_baseline": None, "oom": True, "reason": msg[-300:],
            }))
        raise

    obs_wanted = os.environ.get("BENCH_OBS", "1") == "1"
    autotune_flag = os.environ.get("BENCH_SERVE_AUTOTUNE", "off")
    if autotune_flag == "on":
        # closed-loop A/B: static arm vs live-tuner arm over the SAME
        # trace, re-timed with a mid-trace load shift (arrival rate
        # triples halfway) so the tuner has a regime change to react to
        if spec_flag != "off" or chaos_plan is not None:
            raise SystemExit("--autotune is its own A/B — run --spec / "
                             "--chaos in separate invocations")
        shift_rng = np.random.RandomState(7)
        n_half = n_requests // 2
        gaps = np.concatenate([
            shift_rng.exponential(1.0 / rate, size=n_half),
            shift_rng.exponential(1.0 / (3.0 * rate),
                                  size=n_requests - n_half)])
        shift_arrivals = np.cumsum(gaps)
        primary_mode = modes[-1]
        metric = (f"{model_name}_{dtype_name}_autotune"
                  f"{f'_fleet{fleet_n}' if fleet_n else ''}"
                  "_serving_p50_ttft_ms")
        # both arms measure burn against the SAME SLOs (or the deltas
        # mean nothing); defaults target a CPU-scale tiny-model trace
        ttft_slo = float(os.environ.get("BENCH_SERVE_TTFT_SLO_MS", 50.0))
        tpot_slo = float(os.environ.get("BENCH_SERVE_TPOT_SLO_MS", 3.0))
        static, static_streams = _serve_autotune_arm(
            engine, scfg_kwargs, primary_mode, prompts, shift_arrivals,
            n_new, block, fleet_n, tuned=False, ttft_slo_ms=ttft_slo,
            tpot_slo_ms=tpot_slo, deadline_s=deadline_s)
        from deepspeed_tpu.observability import get_session

        # close the static arm's session BEFORE the tuned arm's warmup:
        # its compile must not trip the live session's recompile watchdog
        if get_session().enabled:
            get_session().close(export=False)
        tuned, tuned_streams = _serve_autotune_arm(
            engine, scfg_kwargs, primary_mode, prompts, shift_arrivals,
            n_new, block, fleet_n, tuned=True, ttft_slo_ms=ttft_slo,
            tpot_slo_ms=tpot_slo, deadline_s=deadline_s)
        obs = get_session()
        if obs.enabled:
            obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                                 "BENCH_metrics_serve"
                                                 ".jsonl"),
                             metric=metric)
            obs.close(export=False)
        sb, tb = static.get("slo_burn", {}), tuned.get("slo_burn", {})
        record = {
            "metric": metric,
            "value": tuned["p50_ttft_ms"],
            "unit": "ms",
            "vs_baseline": None,
            "autotune_ab": {
                "static": static,
                "tuned": tuned,
                # the headline: burn and goodput deltas (tuned - static;
                # negative burn delta = the tuner bought SLO health)
                "ttft_burn_delta": (round(tb["ttft"] - sb["ttft"], 4)
                                    if sb and tb else None),
                "tpot_burn_delta": (round(tb["tpot"] - sb["tpot"], 4)
                                    if sb and tb else None),
                "goodput_delta": (round(tb["goodput_fraction"]
                                        - sb["goodput_fraction"], 4)
                                  if sb and tb else None),
                # data-only knobs: every sampled token identical
                "streams_match": static_streams == tuned_streams,
            },
        }
        print(json.dumps(record))
        return
    if fleet_n:
        # fleet mode: single-engine baseline, then the routing-policy A/B
        # (round-robin vs occupancy-aware) over the SAME trace; the
        # occupancy arm runs LAST and owns the obs session, so the metrics
        # JSONL carries the fleet_serving/* per-replica gauges
        primary_mode = modes[-1]
        metric = (f"{model_name}_{dtype_name}_fleet{fleet_n}"
                  f"{'_disagg' if disagg else ''}_serving_p50_ttft_ms")
        single = _serve_one_mode(engine, scfg_kwargs, primary_mode,
                                 prompts, arrivals, [], n_new, block,
                                 deadline_s=deadline_s)
        fleet_arms = {}
        for i, policy in enumerate(("round_robin", "kv_occupancy")):
            fleet_arms[policy] = _serve_fleet_arm(
                engine, scfg_kwargs, primary_mode, fleet_n, policy, disagg,
                prompts, arrivals, n_new, block,
                enable_obs=(obs_wanted and i == 1),
                chaos_plan=chaos_plan, deadline_s=deadline_s)
        primary = fleet_arms["kv_occupancy"]

        from deepspeed_tpu.observability import get_session

        obs = get_session()
        if obs.enabled:
            obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                                 "BENCH_metrics_serve"
                                                 ".jsonl"),
                             metric=metric)
            obs.export_chrome_trace()
            obs.close(export=False)
        rr = fleet_arms["round_robin"]
        record = {
            "metric": metric,
            "value": primary["p50_ttft_ms"],
            "unit": "ms",
            "vs_baseline": None,
            "fleet": fleet_n,
            "disagg": disagg,
            "chaos": bool(chaos_plan),
            "paged_kernel": "on" if primary_mode == "auto" else "off",
            "single_engine": single,
            "fleet_ab": {
                "round_robin": rr,
                "kv_occupancy": primary,
                # occupancy-aware routing's win over blind round-robin
                "ttft_p50_delta_pct": round(
                    100.0 * (rr["p50_ttft_ms"] - primary["p50_ttft_ms"])
                    / max(rr["p50_ttft_ms"], 1e-9), 2),
            },
            # the scale-out headline: fleet throughput / one engine's
            "tokens_per_sec_vs_single": round(
                primary["tokens_per_sec"]
                / max(single["tokens_per_sec"], 1e-9), 3),
            "ttft_p50_vs_single_pct": round(
                100.0 * (single["p50_ttft_ms"] - primary["p50_ttft_ms"])
                / max(single["p50_ttft_ms"], 1e-9), 2),
        }
        print(json.dumps(record))
        return
    arms = {}
    spec_arms = {}
    if spec_flag != "off":
        draft_engine = None
        if spec_flag == "draft":
            draft_name = os.environ.get("BENCH_SERVE_DRAFT_MODEL",
                                        model_name)
            draft_engine = init_inference(draft_name, dtype=dtype,
                                          max_out_tokens=max_len)
        # both speculative arms ride the primary read path over the SAME
        # trace; the speculative arm runs LAST (it owns the obs session)
        for i, sm in enumerate(["off", spec_flag]):
            spec_arms[sm] = _serve_one_mode(
                engine, scfg_kwargs, modes[0], prompts, arrivals,
                prefix_prompts if sm == spec_flag else [], n_new, block,
                enable_obs=(obs_wanted and i == 1), spec_mode=sm,
                draft_engine=(draft_engine if sm == "draft" else None),
                deadline_s=deadline_s)
        arms["on" if modes[0] == "auto" else "off"] = spec_arms[spec_flag]
    else:
        for i, mode in enumerate(modes):
            label = "on" if mode == "auto" else "off"
            arms[label] = _serve_one_mode(
                engine, scfg_kwargs, mode, prompts, arrivals,
                prefix_prompts, n_new, block,
                enable_obs=(obs_wanted and i == len(modes) - 1),
                deadline_s=deadline_s)

    primary = arms.get("on") or arms["off"]

    from deepspeed_tpu.observability import get_session

    obs = get_session()
    if obs.enabled:
        obs.dump_metrics(path=os.environ.get("BENCH_METRICS_JSONL",
                                             "BENCH_metrics_serve.jsonl"),
                         metric=metric)
        obs.export_chrome_trace()
        obs.close(export=False)

    record = {
        "metric": metric,
        "value": primary["p50_ttft_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "paged_kernel": "on" if "on" in arms else "off",
        "spec": spec_flag,
    }
    record.update({k: v for k, v in primary.items() if k != "tpucost"})
    if primary.get("tpucost") is not None:
        record["tpucost"] = primary["tpucost"]
    if spec_arms:
        off, on = spec_arms["off"], spec_arms[spec_flag]
        ab = {"off": off, spec_flag: on,
              "ttft_p50_delta_pct": round(
                  100.0 * (off["p50_ttft_ms"] - on["p50_ttft_ms"])
                  / max(off["p50_ttft_ms"], 1e-9), 2)}
        if on.get("tpot_ms") and off.get("tpot_ms"):
            # the speculative headline: TPOT bought per target dispatch
            ab["tpot_delta_pct"] = round(
                100.0 * (off["tpot_ms"] - on["tpot_ms"])
                / max(off["tpot_ms"], 1e-9), 2)
        if on.get("tpucost_verify") and off.get("tpucost"):
            ab["verify_vs_decode_flops"] = {
                "verify": on["tpucost_verify"].get("flops"),
                "decode": off["tpucost"].get("flops")}
        record["spec_ab"] = ab
    if len(arms) == 2:
        on, off = arms["on"], arms["off"]
        ab = {"on": on, "off": off,
              "ttft_p50_delta_pct": round(
                  100.0 * (off["p50_ttft_ms"] - on["p50_ttft_ms"])
                  / max(off["p50_ttft_ms"], 1e-9), 2)}
        if on.get("tpot_ms") and off.get("tpot_ms"):
            ab["tpot_delta_pct"] = round(
                100.0 * (off["tpot_ms"] - on["tpot_ms"])
                / max(off["tpot_ms"], 1e-9), 2)
        if on.get("tpucost") and off.get("tpucost"):
            ab["arena_read_bytes"] = {
                "on": on["tpucost"].get("bytes_accessed"),
                "off": off["tpucost"].get("bytes_accessed")}
        record["paged_kernel_ab"] = ab
    print(json.dumps(record))


if __name__ == "__main__":
    serving = ("--serving" in sys.argv[1:]
               or os.environ.get("BENCH_INFER_MODE") == "serving")
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        # --paged-kernel on|off pins one A/B arm; unset runs both
        if a == "--paged-kernel" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_PAGED_KERNEL"] = argv[i + 1]
        elif a.startswith("--paged-kernel="):
            os.environ["BENCH_SERVE_PAGED_KERNEL"] = a.split("=", 1)[1]
        # --spec ngram|draft runs that speculative arm vs spec-off over
        # the SAME Poisson trace (acceptance rate, proposed-vs-emitted,
        # per-arm verify tpucost); 'off'/unset keeps speculation out
        elif a == "--spec" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_SPEC"] = argv[i + 1]
        elif a.startswith("--spec="):
            os.environ["BENCH_SERVE_SPEC"] = a.split("=", 1)[1]
        # --fleet N routes the trace through a FleetRouter over N serving
        # replicas (routing-policy A/B vs a single-engine baseline);
        # --disagg splits the replicas into prefill/decode pools with KV
        # block handoff between them
        elif a == "--fleet" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_FLEET"] = argv[i + 1]
        elif a.startswith("--fleet="):
            os.environ["BENCH_SERVE_FLEET"] = a.split("=", 1)[1]
        elif a == "--disagg":
            os.environ["BENCH_SERVE_DISAGG"] = "1"
        # --chaos plan.json drives the fleet arms through a deterministic
        # fault plan (replica_kill/slow/flap, handoff_fail) and records
        # the self-healing ledger: time-to-revival, shed rate, ...
        elif a == "--chaos" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_CHAOS"] = argv[i + 1]
        elif a.startswith("--chaos="):
            os.environ["BENCH_SERVE_CHAOS"] = a.split("=", 1)[1]
        # --deadline S gives every benched request a deadline, engaging
        # admission-control shedding under pressure
        elif a == "--deadline" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_DEADLINE"] = argv[i + 1]
        elif a.startswith("--deadline="):
            os.environ["BENCH_SERVE_DEADLINE"] = a.split("=", 1)[1]
        # --autotune on runs the closed-loop A/B: live tuner vs static
        # config over the same mid-trace-load-shift Poisson trace
        elif a == "--autotune" and i + 1 < len(argv):
            os.environ["BENCH_SERVE_AUTOTUNE"] = argv[i + 1]
        elif a.startswith("--autotune="):
            os.environ["BENCH_SERVE_AUTOTUNE"] = a.split("=", 1)[1]
    if os.environ.get("BENCH_SERVE_PAGED_KERNEL", "") not in ("", "on",
                                                              "off"):
        raise SystemExit("--paged-kernel must be 'on' or 'off'")
    if os.environ.get("BENCH_SERVE_SPEC", "off") not in ("off", "ngram",
                                                         "draft"):
        raise SystemExit("--spec must be 'off', 'ngram' or 'draft'")
    if os.environ.get("BENCH_SERVE_AUTOTUNE", "off") not in ("off", "on"):
        raise SystemExit("--autotune must be 'on' or 'off'")
    if os.environ.get("BENCH_PREDICT") == "1":
        predict_main()
    elif os.environ.get("BENCH_CHILD") == "1":
        serving_main() if serving else main()
    else:
        if serving:
            # the watchdogged child re-runs this file argv-less; mode rides
            # the environment (as does BENCH_SERVE_PAGED_KERNEL)
            os.environ["BENCH_INFER_MODE"] = "serving"
        model = os.environ.get("BENCH_INFER_MODEL", "llama-7b")
        dtype = os.environ.get("BENCH_INFER_DTYPE", "bf16")
        suffix = "serving_p50_ttft_ms" if serving else "p50_ttft_ms"
        obs_dir = "bench_results/obs_serve" if serving \
            else "bench_results/obs_infer"
        run_watchdogged(
            f"{model}_{dtype}_{suffix}", "ms", os.path.abspath(__file__),
            crash_dir=os.path.join(
                os.environ.get("BENCH_OBS_DIR", obs_dir), "crash"))
