#!/usr/bin/env bash
# The unified static-analysis driver: lint (source) + audit (program
# semantics) + cost (program cost) + shard (program layout: every
# parameter/output placement vs the logical-axis rule registry) + sync
# (host concurrency: thread-root reachability, guarded-by discipline,
# lock-order cycles over the serving/observability orchestration) + parity
# (serving kernel-path tests, tier-1 marker set) + chaos (training
# fault-injection recovery smoke) + chaos_serve (serving-fleet self-healing
# smoke) + rlhf (hybrid-engine-v2 post-training smoke: flip-no-recompile +
# replay-bit-exact) + tune (closed-loop telemetry: time-series store +
# live-tuner state machine + tuner-on bit-exactness) + profile (triggered
# deep-profiling: capture-window state machine + trace attribution + the
# measured-vs-predicted join) in one run, one exit code for CI.
#
# The five analyzers share the same gate semantics (committed baseline,
# stale-entry rot detection, the render_report tail in
# tools/tpulint/baseline.py), so this script is just sequencing: every gate
# runs even when an earlier one fails, and the exit code is the OR of
# all of them — CI output always shows the full picture, not the first
# failure.
#
# Usage: scripts/check.sh            # everything
#        scripts/check.sh lint cost  # a subset
set -uo pipefail

cd "$(dirname "$0")/.."

selected=("$@")
fail=0
for gate in lint audit cost shard sync parity chaos chaos_serve rlhf tune profile; do
    if [ "${#selected[@]}" -gt 0 ]; then
        case " ${selected[*]} " in
            *" $gate "*) ;;
            *) continue ;;
        esac
    fi
    echo "==== $gate ===="
    if ! "scripts/$gate.sh"; then
        fail=1
    fi
    echo
done
exit $fail
