#!/usr/bin/env python
"""Run every bench + chip-validation script and commit raw JSON artifacts.

VERDICT r3 weak #5/#7: README's numbers must cite driver-auditable files,
not builder prose. Writes bench_results/r{N}/<name>.json with the bench's
own JSON line plus run metadata; validation scripts get their stdout
captured verbatim. Skips (with a recorded reason) anything that needs a
real accelerator when only CPU is present.

Usage: python scripts/run_bench_suite.py r04 [filter-substring]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SUITE = [
    ("bench", ["python", "bench.py"], {}),
    ("bench_infer_bf16", ["python", "bench_infer.py"], {}),
    ("bench_infer_int8", ["python", "bench_infer.py"],
     {"BENCH_INFER_DTYPE": "int8"}),
    ("bench_infer_int4", ["python", "bench_infer.py"],
     {"BENCH_INFER_DTYPE": "int4"}),
    # W8A8: s8xs8 MXU decode (VERDICT r4 #3 — the weight-only kernel is
    # VPU-convert-bound; this removes the convert entirely)
    ("bench_infer_w8a8", ["python", "bench_infer.py"],
     {"BENCH_INFER_DTYPE": "w8a8"}),
    ("bench_infer_w4a8", ["python", "bench_infer.py"],
     {"BENCH_INFER_DTYPE": "w4a8"}),
    # MoE expert-parallel inference (VERDICT r4 #2) + BLOOM-7B kernel-
    # injected inference as tracked config #5 names it (VERDICT r4 #6)
    ("bench_infer_moe8e", ["python", "bench_infer.py"],
     {"BENCH_INFER_MODEL": "moe-gpt-125m-8e"}),
    ("bench_infer_bloom7b", ["python", "bench_infer.py"],
     {"BENCH_INFER_MODEL": "bloom-7b"}),
    # bf16 bloom-7b (14.1 GB weights + 250k-vocab logits) is borderline on
    # 16 GB — the int8 variant is the reference's kernel-injected headline
    ("bench_infer_bloom7b_int8", ["python", "bench_infer.py"],
     {"BENCH_INFER_MODEL": "bloom-7b", "BENCH_INFER_DTYPE": "int8"}),
    # tracked config #2 as specified: resident (no-offload) partitioned-Adam
    # ZeRO — 1.3B records the honest single-chip OOM caveat, 125m the number
    ("bench_zero2_resident_opt1.3b", ["python", "bench_zero.py"],
     {"BENCH_ZERO_OFFLOAD": "none"}),
    ("bench_zero2_resident_opt125m", ["python", "bench_zero.py"],
     {"BENCH_ZERO_OFFLOAD": "none", "BENCH_ZERO_MODEL": "opt-125m",
      "BENCH_ZERO_BATCH": "16"}),
    ("bench_moe_sparse", ["python", "bench_moe.py"], {}),
    ("bench_moe_einsum", ["python", "bench_moe.py"],
     {"BENCH_MOE_DISPATCH": "einsum"}),
    ("bench_zero_optim_offload", ["python", "bench_zero.py"], {}),
    ("bench_zero_param_offload_7b", ["python", "bench_zero.py"],
     {"BENCH_ZERO_PARAM_OFFLOAD": "cpu", "BENCH_ZERO_MODEL": "llama-7b",
      "BENCH_WARMUP": "1", "BENCH_STEPS": "1"}),
    ("bench_zero_param_offload_9.8b", ["python", "bench_zero.py"],
     {"BENCH_ZERO_PARAM_OFFLOAD": "cpu", "BENCH_ZERO_MODEL": "llama-13b",
      "BENCH_ZERO_LAYERS": "30", "BENCH_WARMUP": "1", "BENCH_STEPS": "1"}),
    ("bench_rlhf", ["python", "bench_rlhf.py"], {}),
    ("validate_kernels", ["python", "scripts/validate_kernels_tpu.py"], {}),
    ("validate_offload", ["python", "scripts/validate_offload_tpu.py"], {}),
    # VERDICT r4 #5: fetch-vs-compute overlap + h2d utilization evidence
    ("validate_offload_overlap",
     ["python", "scripts/validate_offload_overlap.py"], {}),
    ("validate_offload_overlap_1.3b",
     ["python", "scripts/validate_offload_overlap.py"],
     {"BENCH_OVERLAP_MODEL": "opt-1.3b", "BENCH_OVERLAP_BATCH": "4"}),
]


def main() -> None:
    tag = sys.argv[1] if len(sys.argv) > 1 else "r04"
    filt = sys.argv[2] if len(sys.argv) > 2 else ""
    outdir = os.path.join(REPO, "bench_results", tag)
    os.makedirs(outdir, exist_ok=True)

    import jax

    on_accel = jax.default_backend() != "cpu"
    for name, cmd, env in SUITE:
        if filt and filt not in name:
            continue
        if not on_accel:
            record = {"name": name, "skipped":
                      "needs a real accelerator (backend is cpu)"}
            with open(os.path.join(outdir, f"{name}.json"), "w") as f:
                json.dump(record, f, indent=1)
            print(f"[skip] {name}: cpu backend", flush=True)
            continue
        t0 = time.time()
        try:
            proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                  text=True, env={**os.environ, **env},
                                  timeout=60 * 30)
        except subprocess.TimeoutExpired:
            record = {"name": name, "cmd": cmd, "env_overrides": env,
                      "wall_seconds": round(time.time() - t0, 1),
                      "returncode": "timeout(30m)"}
            with open(os.path.join(outdir, f"{name}.json"), "w") as f:
                json.dump(record, f, indent=1)
            print(f"[TIMEOUT] {name}", flush=True)
            continue
        dt = round(time.time() - t0, 1)
        record = {"name": name, "cmd": cmd, "env_overrides": env,
                  "wall_seconds": dt, "returncode": proc.returncode}
        # the benches print ONE JSON line (last); validators print text
        lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
        parsed = None
        for line in reversed(lines):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue
        if parsed is not None:
            record["result"] = parsed
        else:
            record["stdout_tail"] = lines[-30:]
        if proc.returncode != 0:
            record["stderr_tail"] = proc.stderr.strip().splitlines()[-15:]
        path = os.path.join(outdir, f"{name}.json")
        with open(path, "w") as f:
            json.dump(record, f, indent=1)
        status = "ok" if proc.returncode == 0 else "FAIL"
        print(f"[{status}] {name}: {dt}s -> {path}", flush=True)


if __name__ == "__main__":
    main()
