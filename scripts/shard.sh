#!/usr/bin/env bash
# Repo static-sharding gate: tpushard over the selftest engines against the
# committed baseline. Exits non-zero on any new layout finding (rule
# violation, implicit reshard, cross-program mismatch, replication waste)
# or stale baseline entry. Usage: scripts/shard.sh [extra tpushard args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m tools.tpushard \
    --config tools/tpuaudit/selftest_config.json \
    --baseline .tpushard-baseline.json "$@"
