#!/usr/bin/env python
"""SPMD-efficiency regression guard: run the multichip dryrun in a
subprocess and fail if XLA logs an involuntary full rematerialization
(a full-tensor replication in the hot loop — the class of silent perf bug
that sank the round-2 zero3×TP×SP config).

Usage: python scripts/check_spmd_clean.py [n_devices]
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    n = sys.argv[1] if len(sys.argv) > 1 else "8"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + f" --xla_force_host_platform_device_count={n}"),
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=1200)
    out = proc.stdout + proc.stderr
    bad = [l for l in out.splitlines() if "Involuntary full remat" in l]
    if proc.returncode != 0:
        sys.stderr.write(out[-4000:])
        print(f"FAIL: dryrun exited {proc.returncode}")
        return 1
    if bad:
        for l in bad:
            print(l)
        print(f"FAIL: {len(bad)} involuntary full rematerialization(s) — "
              "a sharding transition is replicating a tensor in the hot loop")
        return 1
    print("OK: dryrun clean of involuntary rematerialization")
    return 0


if __name__ == "__main__":
    sys.exit(main())
