#!/usr/bin/env bash
# Repo static-cost gate: tpucost over the selftest engines against the
# committed baseline. Exits non-zero on any over-band metric regression or
# stale baseline entry. Usage: scripts/cost.sh [extra tpucost args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m tools.tpucost \
    --config tools/tpuaudit/selftest_config.json \
    --baseline .tpucost-baseline.json "$@"
