#!/usr/bin/env bash
# Closed-loop telemetry gate: the metric time-series store + the live
# serving autotuner (docs/observability.md "Closed loop",
# docs/autotuning.md).
#
# Runs the fake-clock controller suite and the end-to-end contract on the
# tiny model, asserting:
#   * the store's rings stay bounded, stats/query/adoption behave, and a
#     session replacement carries the rolling windows over;
#   * the controller's full state machine under synthetic burn — propose
#     one notch, hold, judge, keep/rollback, cooldown, relax to defaults;
#   * the jit-cache discipline: a fleet serving with the tuner ON walking
#     knobs mid-trace produces token streams bit-identical to the untuned
#     solo oracle, with zero steady-state recompiles;
#   * the disabled path wires nothing — no store allocation, no
#     controller on either ServingEngine or FleetRouter;
#   * the recommendations artifact (tune_recommendations.json) exists at
#     close and carries the versioned schema (format, knobs, evidence).
#
# CPU-only, wall-clock-free (the controller runs on iteration counts, the
# synthetic signals on a fake clock) — a tune gate run is exactly
# reproducible.
#
# Usage: scripts/tune.sh [extra pytest args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest \
    "tests/unit/test_livetuner.py" \
    -q -p no:cacheprovider "$@"
