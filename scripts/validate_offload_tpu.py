#!/usr/bin/env python
"""Offload-tier validation — run on a real TPU chip (CPU XLA cannot lower
host-pinned jit operands, so this lives outside the pytest CPU mesh suite).

Checks: (1) trajectory equivalence offload vs no-offload; (2) optimizer
state actually resides in pinned_host; (3) device-resident argument bytes
drop by the fp32 master+moment footprint (via compiled memory_analysis).
Measured on v5e / gpt2-125m: 1.62 -> 0.23 GiB device args (1.39 GiB saved),
temps 1.56 -> 1.77 GiB."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
import deepspeed_tpu
from deepspeed_tpu.models import create_model
from deepspeed_tpu.parallel import mesh as mesh_mod

def run(offload):
    mesh_mod.reset_mesh()
    model = create_model("gpt2-125m", dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", max_seq_len=512)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0,
                              "offload_optimizer": {"device": "cpu" if offload else "none"}},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    kinds = {getattr(x.sharding, "memory_kind", None)
             for x in jax.tree.leaves(engine.opt_state)}
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8, 512), 0,
                             model.config.vocab_size)
    losses = [float(engine.train_batch(batch={"input_ids": ids})) for _ in range(3)]
    stats = jax.devices()[0].memory_stats() or {}
    hbm = stats.get("bytes_in_use", 0)
    del engine
    return losses, kinds, hbm

l_no, k_no, hbm_no = run(False)
print("no-offload:", [round(l,4) for l in l_no], k_no, f"{hbm_no/2**30:.2f} GiB")
l_off, k_off, hbm_off = run(True)
print("offload:   ", [round(l,4) for l in l_off], k_off, f"{hbm_off/2**30:.2f} GiB")
assert k_off == {"pinned_host"}, k_off
for a, b in zip(l_no, l_off):
    # bf16 model: the pinned-in/out update program fuses differently from
    # the resident one, so step-3+ losses drift at bf16 rounding scale
    # (measured 2.1e-3 absolute at loss ~10.75, i.e. 2e-4 relative; exact
    # equivalence at fp32 is covered by tests/unit/test_offload.py)
    assert abs(a - b) < 5e-3, (a, b)

# compiled-step memory accounting: device args must shrink by ~master+moments
# (metrics parsed through the shared tpucost extraction helpers — the same
# implementation the CI cost gate uses)
from tools.tpucost.extract import memory_analysis_dict  # noqa: E402


def arg_bytes(offload):
    mesh_mod.reset_mesh()
    model = create_model("gpt2-125m", dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", max_seq_len=512)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0,
                              "offload_optimizer": {"device": "cpu" if offload else "none"}},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    step = engine._build_train_step()
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8, 512), 0,
                             model.config.vocab_size)
    batch = jax.device_put({"input_ids": ids},
                           engine._batch_sharding({"input_ids": ids}, True))
    with engine.mesh:
        ma = memory_analysis_dict(
            step.lower(engine.params, engine.opt_state, engine.scaler_state,
                       batch).compile())
    return ma["argument_hbm_bytes"]

saved = (arg_bytes(False) - arg_bytes(True)) / 2**30
print(f"device-resident argument bytes saved: {saved:.2f} GiB")
assert saved > 1.2, saved
print("OFFLOAD CHECK OK")
