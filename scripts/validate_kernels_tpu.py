#!/usr/bin/env python
"""Real-TPU kernel validation — the non-interpret twins of the CPU-mesh
kernel parity tests (tests/kernels run through the pallas interpreter; this
script runs the compiled kernels on the attached chip).

Run: python scripts/validate_kernels_tpu.py       (~2 min)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def check(name, got, want, atol=2e-2, rtol=2e-2):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = np.abs(got - want).max()
    ok = np.allclose(got, want, atol=atol, rtol=rtol)
    print(f"{name:<42} max|err|={err:.2e}  {'OK' if ok else 'FAIL'}")
    return ok


def main() -> int:
    assert jax.default_backend() == "tpu", jax.default_backend()
    from deepspeed_tpu.ops import (decode_attention, flash_attention,
                                   int4_matmul, int8_matmul, quantize_int4,
                                   reference_decode_attention,
                                   reference_int4_matmul,
                                   reference_int8_matmul)
    from deepspeed_tpu.models.transformer import (alibi_slopes,
                                                  dot_product_attention)

    ok = True
    rng = np.random.RandomState(0)

    # flash attention fwd
    q = jnp.asarray(rng.randn(2, 256, 4, 64), jnp.bfloat16)
    k = jnp.asarray(rng.randn(2, 256, 4, 64), jnp.bfloat16)
    v = jnp.asarray(rng.randn(2, 256, 4, 64), jnp.bfloat16)
    ok &= check("flash_attention causal",
                flash_attention(q, k, v, causal=True),
                dot_product_attention(q, k, v, None, causal=True))

    # decode attention with ragged alibi key positions
    qd = jnp.asarray(rng.randn(2, 8, 64), jnp.float32)
    kc = jnp.asarray(rng.randn(2, 256, 8, 64), jnp.float32)
    vc = jnp.asarray(rng.randn(2, 256, 8, 64), jnp.float32)
    valid = jnp.broadcast_to(
        (jnp.arange(256)[None] < 100).astype(jnp.int32), (2, 256))
    al = alibi_slopes(8)
    col = jnp.arange(256, dtype=jnp.float32)
    kpos = jnp.stack([col, col - 30.0 * (col >= 50)])
    ok &= check("decode_attention alibi+key_positions",
                decode_attention(qd, kc, vc, valid, alibi=al,
                                 key_positions=kpos),
                reference_decode_attention(qd, kc, vc, valid, alibi=al,
                                           key_positions=kpos),
                atol=2e-2, rtol=2e-2)   # jnp oracle einsums run at TPU
                                        # default (bf16-internal) precision

    # int8 / int4 dequant GEMM
    x = jnp.asarray(rng.randn(8, 2048), jnp.bfloat16)
    w = jnp.asarray(rng.randn(2048, 1024) * 0.02, jnp.float32)
    q8 = jnp.clip(jnp.round(w / 0.01), -127, 127).astype(jnp.int8)
    s8 = jnp.full((1, 1024), 0.01, jnp.float32)
    ok &= check("int8_matmul", int8_matmul(x, q8, s8),
                reference_int8_matmul(x, q8, s8, out_dtype=jnp.float32),
                atol=0.5)
    q4, s4 = quantize_int4(w, group_size=128)
    ok &= check("int4_matmul (grouped)", int4_matmul(x, q4, s4),
                reference_int4_matmul(x, q4, s4, out_dtype=jnp.float32),
                atol=0.5)
    from deepspeed_tpu.ops import (int4_a8_matmul, int8_a8_matmul,
                                   reference_int4_a8_matmul,
                                   reference_int8_a8_matmul)

    ok &= check("int8_a8_matmul (W8A8)", int8_a8_matmul(x, q8, s8),
                reference_int8_a8_matmul(x, q8, s8, out_dtype=jnp.float32),
                atol=0.5)
    ok &= check("int4_a8_matmul (W4A8 grouped)", int4_a8_matmul(x, q4, s4),
                reference_int4_a8_matmul(x, q4, s4, out_dtype=jnp.float32),
                atol=0.5)

    # block-sparse attention incl. the empty-row guard
    from deepspeed_tpu.ops.block_sparse_attention import (
        block_sparse_attention, build_tile_plan)

    layout = np.zeros((1, 2, 2), np.int64)
    layout[0, 0, 0] = 1                      # q-tile 1 attends NOTHING
    plan = build_tile_plan(layout, 128, 256)
    qs = jnp.asarray(rng.randn(1, 256, 1, 64), jnp.float32)
    ks_ = jnp.asarray(rng.randn(1, 256, 1, 64), jnp.float32)
    vs = jnp.asarray(rng.randn(1, 256, 1, 64), jnp.float32)
    out = block_sparse_attention(qs, ks_, vs, plan)
    ref = dot_product_attention(qs[:, :128], ks_[:, :128], vs[:, :128],
                                None, causal=False)
    ok &= check("block_sparse active rows", out[:, :128], ref,
                atol=2e-2, rtol=2e-2)
    tail = float(np.abs(np.asarray(out[:, 128:])).max())
    print(f"{'block_sparse empty-row guard':<42} max|tail|={tail:.2e}  "
          f"{'OK' if tail == 0.0 else 'FAIL'}")
    ok &= tail == 0.0

    print("ALL OK" if ok else "FAILURES")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
