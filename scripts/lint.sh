#!/usr/bin/env bash
# Repo lint gate: tpulint over the source tree with the committed baseline.
# Exits non-zero on any NEW finding (existing debt lives in the baseline).
# Usage: scripts/lint.sh [extra tpulint args...]
set -euo pipefail

cd "$(dirname "$0")/.."

python -m tools.tpulint \
    deepspeed_tpu/ tools/ scripts/ tests/ \
    bench.py bench_infer.py bench_moe.py bench_rlhf.py bench_zero.py \
    --baseline .tpulint-baseline.json "$@"

# metric-name <-> docs drift gate: every literal registry.counter/gauge/
# histogram name in the tree must appear in docs/observability.md's metric
# table (tools/tpulint/metricsdoc.py)
python -m tools.tpulint.metricsdoc
