#!/usr/bin/env bash
# Repo program-audit gate: tpuaudit over the selftest engines (train with
# ZeRO-3 on the virtual mesh, pipeline-parallel train, inference) with the
# committed baseline. Exits non-zero on any NEW finding or stale baseline
# entry. Usage: scripts/audit.sh [extra tpuaudit args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m tools.tpuaudit \
    --config tools/tpuaudit/selftest_config.json \
    --baseline .tpuaudit-baseline.json \
    --devices 8 "$@"
