#!/usr/bin/env bash
# RLHF smoke gate: the hybrid-engine-v2 post-training loop cannot rot
# silently (docs/rlhf.md).
#
# Drives a 2-iteration GRPO run on a tiny model through the full
# generate → score → train → flip loop and asserts the ISSUE-13
# acceptance bar:
#   * the weight flip triggers ZERO serving-program recompiles and ZERO
#     arena reallocation (recompile-watchdog counter + block-pool
#     identity);
#   * a candidate group of n=4 costs ONE prefill (prefill-chunk dispatch
#     count) and every forked sibling is bit-identical to a solo submit
#     of the same seed;
#   * replay(manifest) reproduces every rollout token stream bit-exactly
#     with speculation toggled OPPOSITE to the recording run — including
#     under forced preemption (pool too small) and after a NaN→rollback
#     recovery mid-iteration.
#
# CPU-only and deterministic; part of scripts/check.sh (8th gate).
#
# Usage: scripts/rlhf.sh [extra pytest args...]
set -euo pipefail

cd "$(dirname "$0")/.."

# the WHOLE file, slow-marked replay suites included (tier-1 runs only
# the not-slow subset to protect its time budget; this gate is the
# comprehensive pass)
JAX_PLATFORMS=cpu python -m pytest \
    tests/unit/test_rlhf.py \
    -q -p no:cacheprovider "$@"
