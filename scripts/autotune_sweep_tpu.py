#!/usr/bin/env python
"""Record a real autotuning sweep on the attached chip and check the
model-based tuner against it: the cost model's ranking should surface the
measured-best config in <= half the grid. Writes
autotuning_results/recorded_sweep.json.

Run: python scripts/autotune_sweep_tpu.py   (real TPU; ~5 min)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np


def measure(name, cfg):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import create_model
    from deepspeed_tpu.parallel import mesh as mesh_mod

    mesh_mod.reset_mesh()
    micro = cfg["train_micro_batch_size_per_gpu"]
    seq = 1024
    try:
        model = create_model("gpt2-125m", dtype=jnp.bfloat16, remat=True,
                             remat_policy="dots", max_seq_len=seq)
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            **cfg, "steps_per_print": 1000, "bf16": {"enabled": True},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}}})
        ids = np.random.default_rng(0).integers(0, 50257, (1, micro, seq))
        tree = {"input_ids": ids}
        for _ in range(2):
            loss = engine.train_batch(batch=tree)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(4):
            loss = engine.train_batch(batch=tree)
        float(loss)
        tps = micro * seq * 4 / (time.perf_counter() - t0)
        print(f"{name}: {tps:,.0f} tokens/s", flush=True)
        return tps
    except Exception as e:
        print(f"{name}: FAILED ({str(e)[:80]})", flush=True)
        return None


def main():
    from deepspeed_tpu.autotuning import Autotuner, TpuCostModel

    space = {"train_micro_batch_size_per_gpu": [8, 16, 32],
             "zero_optimization.stage": [0, 1]}
    model_info = {"num_params": 124e6, "hidden_size": 768, "num_layers": 12,
                  "seq_length": 1024, "vocab_size": 50257}
    out_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "autotuning_results")

    # full grid (the recorded sweep)
    grid_tuner = Autotuner({}, results_dir=os.path.join(out_dir, "grid"),
                           runner=measure)
    g_best, g_val = grid_tuner.tune(space=space, tuner_type="gridsearch")

    # model-based with half the trials
    calls = []

    def counting(name, cfg):
        calls.append(name)
        key = name
        return grid_tuner.results.get(key)   # reuse recorded measurements

    mb_tuner = Autotuner({}, results_dir=os.path.join(out_dir, "model_based"),
                         runner=counting)
    m_best, m_val = mb_tuner.tune(space=space, tuner_type="model_based",
                                  num_trials=3, model_info=model_info,
                                  device_kind="TPU v5 lite")
    rec = {"grid_best": g_best, "grid_val": g_val,
           "grid_trials": len(grid_tuner.results),
           "model_based_best": m_best, "model_based_val": m_val,
           "model_based_trials": len(calls),
           "sweep": grid_tuner.results}
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "recorded_sweep.json"), "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))
    ok = (m_val == g_val and len(calls) <= len(grid_tuner.results) // 2)
    print("MODEL-BASED TUNER:", "OK" if ok else "MISSED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
