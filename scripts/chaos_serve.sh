#!/usr/bin/env bash
# Serving chaos gate: the fleet's detect → remediate → verify loop
# (docs/serving.md "Fleet self-healing & overload").
#
# Drives 12+ staggered temperature-0.7 requests through a 3-replica fleet
# under a deterministic kill → slow → revive fault plan and asserts:
#   * every client stream is bit-identical to the single-engine oracle —
#     replica death, drain + recompute resubmission, quarantine, revival
#     and probation are invisible to clients;
#   * at least one quarantine fired (step-time verdict on the
#     replica_slow straggler) and at least one revival graduated
#     probation;
#   * a deadline-infeasible submit was shed with a structured
#     Overloaded(retry_after_s=...);
#   * zero leaked KV blocks (pools drain to prefix-cache pins) and a
#     balanced fleet request ledger;
# plus the disaggregated variant (handoff_fail mid-transfer → retry on
# another decode replica / decode-in-place fallback, blocks freed exactly
# once) and the full fleet lifecycle/overload suites.
#
# CPU-only and sleep-free: injected slowness rides the health data-plane,
# faults are pinned to router iterations — a chaos run is exactly
# reproducible. The stress pass reruns the threaded variant with
# deterministic seeded GIL-yield points at every lock boundary
# (LockPerturber, pytest --stress): same seed, same interleaving pressure.
#
# Usage: scripts/chaos_serve.sh [extra pytest args...]
set -euo pipefail

cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest \
    "tests/unit/test_fleet_chaos.py" \
    "tests/unit/test_fleet.py::TestReplicaLifecycle" \
    "tests/unit/test_fleet.py::TestOverloadControl" \
    "tests/unit/test_fleet.py::TestHandoffFaultTolerance" \
    "tests/unit/test_fleet.py::TestParkedResubmission" \
    -q -p no:cacheprovider "$@"

for seed in 1234 7; do
    JAX_PLATFORMS=cpu python -m pytest \
        "tests/unit/test_fleet_chaos.py::TestThreadedChaos" \
        "tests/unit/test_sync_regressions.py" \
        --stress --stress-seed "$seed" \
        -q -p no:cacheprovider "$@"
done
