#!/usr/bin/env python
"""Offload streaming overlap validation — run on a real TPU chip.

VERDICT r4 #5: prove (or quantify) fetch-vs-compute overlap in the ZeRO-3
param-offload streaming loop. Prints ONE JSON line:

    {"model": ..., "steps": ..., "tokens_per_sec": ...,
     "peak_h2d_gbps": ...,        # pure-fetch streaming ceiling
     "achieved_h2d_gbps": ...,    # real step's h2d rate
     "h2d_utilization": ...,      # achieved / peak — >=0.8 == saturated
     "t_fetch_s"/"t_compute_s"/"t_step_s": ...,
     "overlap_efficiency": ...}   # 1.0 = shorter phase fully hidden

Env: BENCH_OVERLAP_MODEL (default llama-7b), BENCH_OVERLAP_BATCH (1),
BENCH_OVERLAP_SEQ (1024), BENCH_OVERLAP_BUFFER (offload block bytes).
"""
import json
import os
import sys


sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import create_model


def main() -> None:
    preset = os.environ.get("BENCH_OVERLAP_MODEL", "llama-7b")
    batch = int(os.environ.get("BENCH_OVERLAP_BATCH", 1))
    seq = int(os.environ.get("BENCH_OVERLAP_SEQ", 1024))
    buf = int(os.environ.get("BENCH_OVERLAP_BUFFER", 800_000_000))
    model = create_model(preset, dtype=jnp.bfloat16, remat=True,
                         remat_policy="dots", max_seq_len=seq)
    cfg = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "offload_param": {
            "device": "cpu", "buffer_size": buf}},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    ex = engine._param_offload
    ids = jax.random.randint(jax.random.PRNGKey(0), (1, batch, seq), 0,
                             model.config.vocab_size)
    with engine.mesh:
        stack = engine._globalize_batch({"input_ids": ids}, leading_gas=True)
        rep = ex.overlap_report(stack)
    toks = batch * seq / rep["t_step_s"]
    print(json.dumps({
        "model": preset, "blocks": ex.num_blocks,
        "tokens_per_sec": round(toks, 1), **rep,
    }))


if __name__ == "__main__":
    main()
