#!/usr/bin/env bash
# Deep-profiling gate: the triggered-capture state machine (fake clock, no
# wall time), trace-artifact attribution on the committed fixture, a live
# CPU capture smoke joining measured seconds against the tpucost
# prediction, and the boot-recommendations apply/refuse matrix.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m pytest "tests/unit/test_profiler.py" -q \
    -p no:cacheprovider "$@"
